#!/usr/bin/env python
"""Benchmark driver over the BASELINE.md configs.

Default config: Sycamore-53 depth-14 single-amplitude contraction (the
north-star, BASELINE.md #3): build the amplitude network, plan with the
native hyper-optimizer, slice-and-reconfigure to fit single-chip HBM,
execute on the JAX backend (TPU when available). Prints ONE JSON line:

    {"metric": ..., "value": <wall-clock seconds>, "unit": "s",
     "vs_baseline": <speedup vs the CPU (numpy/BLAS) oracle>}

Methodology mirrors the reference benchmark's ``time_to_solution``
(``benchmark/src/main.rs:365-405``): path optimization is excluded from
the timed region; the contraction itself — all slices — is timed after a
warmup run that triggers XLA compilation. The CPU baseline runs the SAME
program (subset of slices, extrapolated linearly for the sliced config —
slices are identical work by construction).

Robustness contract (the driver parses stdout): exactly one JSON line is
printed no matter what. Backend init is probed in a subprocess with a
timeout first; if the accelerator is unreachable the run falls back to a
pinned CPU platform (honest numeric result, ``device: cpu-fallback``); if
a config run dies on the accelerator, an on-accelerator retry ladder runs
in fresh subprocesses (batch=1 → deeper slicing → other executor) before
the CPU fallback; only if everything fails does the line carry an
``error`` field.

Env knobs:
  BENCH_CONFIG  sycamore_amplitude (default) | ghz3 | random20 | qaoa30 |
                sycamore_m20_partitioned (runs on the virtual 8-CPU mesh)
  BENCH_QUBITS / BENCH_DEPTH / BENCH_SEED
  BENCH_TARGET_LOG2_PEAK (29), BENCH_NTRIALS (128),
  BENCH_CPU_SLICES (1; serial baseline-timing sample),
  BENCH_PARITY_SLICES (16; parallel complex128 oracle sample),
  BENCH_PARITY_TARGET (1e-5), BENCH_COMPLEX_MULT
  naive|gauss|fused|strassen|chain|auto (default auto: the per-step
  kernel promotion ladder over the tuned gauss base),
  BENCH_NO_PLAN_CACHE=1 (force replanning),
  BENCH_REPS (3), BENCH_PEAK_FLOPS (per device),
  BENCH_PIPELINE_CALLS (32; small configs — dispatches enqueued per
    timed region, blocked once: steady-state per-eval time),
  BENCH_EXEC chunked|loop, BENCH_BATCH (8), BENCH_PROBE_SLICES (64),
  BENCH_HOIST (1; slice-invariant stem hoisting — prelude once, residual
    per slice), BENCH_HOIST_AB (1; probe-subset A/B hoisted vs naive
    when the stem is non-trivial),
  BENCH_LOOP_UNROLL (1; loop strategy only — unrolled-scan slice loop),
  BENCH_FULL_SECONDS (900; run all slices if projected under this),
  BENCH_TRACE =1 to capture a profiler trace (off otherwise: the axon
    tunnel's profiler wedges — see _maybe_trace),
  BENCH_SUBSET_TIMEOUT (900; parity-subset subprocess, accelerators),
  BENCH_INLINE_FETCH=1 (accelerators: fetch parity in-process, pre-r4),
  BENCH_NO_PARITY=1 (skip parity entirely; wall-clock A/B stages),
  BENCH_PRECISION float32 (HIGHEST dots, default) | high (bf16x3) |
    default (1-pass bf16),
  BENCH_STAGE_TIMEOUT (1500 + 2*BENCH_FULL_SECONDS; per retry stage),
  BENCH_SA_SECONDS (60) / BENCH_SA_ROUNDS (partitioned configs; SA budget),
  BENCH_PARTITIONS (8) / BENCH_HBM_BYTES (16 GiB; config-5 modeled
    per-device budget — part of the partitioning-ratchet cache key),
  BENCH_OBS (1; tnc_tpu.obs span/metric recording — the per-phase
    "phases" breakdown in the JSON record and the Chrome-trace export;
    0 disables both),
  BENCH_CALIBRATE (1; sycamore config — one extra UNTIMED complex64
    oracle slice with per-step spans on feeds the record's
    "calibration" block without perturbing any timed region; 0 skips
    the pass, ~minutes of host work on the full north-star),
  BENCH_TRACE_JSON (bench_trace.json next to this file; where the
    Chrome-trace/Perfetto timeline of the run is written — load it in
    ui.perfetto.dev; docs/observability.md)

The JSON record also gains "rep_stats" (per-rep timing spread, the
perf gate's noise model — scripts/perf_gate.py) and "calibration"
(fitted effective device model + cost-model error percentiles + the
worst-mispredicted steps, from the run's per-step spans —
tnc_tpu/obs/calibrate.py; set TNC_TPU_STEP_TIME=1 to add device-side
per-step samples, at the cost of eager step-by-step dispatch).

Flags: ``--serve`` (equivalently ``BENCH_SERVE=1`` — the flag is
forwarded to virtual-mesh/retry relaunches via that env var)
additionally runs the in-process amplitude serving
benchmark (docs/serving.md) and records a ``"serving"`` block in the
JSON — queries/sec, batch-size distribution, p50/p99 latency — so the
perf gate can watch serving throughput alongside contraction
wall-clock (knobs: BENCH_SERVE_QUERIES (256), BENCH_SERVE_QUBITS (10),
BENCH_SERVE_DEPTH (6), BENCH_SERVE_BATCH (32), BENCH_SERVE_WAIT_MS
(2), BENCH_SERVE_BACKEND jax|numpy). BENCH_SERVE_OPENLOOP=qps:duration
adds the open-loop overload leg: arrivals at a fixed rate regardless
of completions, on an elastic-enabled service with a priority rider
every BENCH_SERVE_OPENLOOP_PRIO_EVERY-th (16) arrival — tail
percentiles, admission rejections, and the serve.elastic
preemption/reassignment counter deltas land in ``serving.openloop``.

``--resume`` arms slice-range checkpointing (sets TNC_TPU_CKPT
to .cache/bench_ckpt unless already set): a run killed mid-slice-range
resumes from the persisted accumulator+cursor instead of restarting at
slice 0 (docs/resilience.md). Retry-ladder subprocesses inherit it, so
a degraded retry also resumes whatever range the crashed stage
finished. Resilience activity (retries, degradation rungs, checkpoint
saves/resumes) lands in the JSON record's "resilience" field.

Executor/precision/target defaults may also come from the hardware-
promoted marker .cache/best_config.json (see _tuned_default); env wins.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

log = lambda *a: print(*a, file=sys.stderr)  # noqa: E731


class BenchCheckError(RuntimeError):
    """A correctness/parity check failed; caught by main() so the one-
    JSON-line contract holds."""


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))


def _probe_backend() -> str | None:
    """Initialize JAX in a *subprocess* (twice on failure) so a hung or
    broken accelerator runtime cannot take the driver down with it.
    Returns the platform name, or None if no backend comes up."""
    code = (
        "import jax; d = jax.devices()[0]; "
        "print('PROBE', d.platform, d.device_kind)"
    )
    for attempt, timeout_s in ((1, 180.0), (2, 90.0)):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            log(f"[bench] backend probe attempt {attempt}: timed out")
            continue
        for line in r.stdout.splitlines():
            if line.startswith("PROBE "):
                _, platform, *kind = line.split()
                log(f"[bench] backend probe: {platform} ({' '.join(kind)})")
                return platform
        log(
            f"[bench] backend probe attempt {attempt}: rc={r.returncode} "
            f"{r.stderr.strip().splitlines()[-1] if r.stderr.strip() else ''}"
        )
    return None


def _pin_cpu() -> None:
    """Force the CPU platform before any in-process backend init."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


# bf16 MXU peak FLOP/s by device kind (public spec sheets); the honest
# ceiling for our float32 split-complex matmuls is lower, but MFU vs the
# headline peak is the comparable convention. Override: BENCH_PEAK_FLOPS.
_PEAK_FLOPS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _device_peak_flops(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in _PEAK_FLOPS.items():
        if tag in kind:
            return peak
    return None


def _tuned_default(
    key: str, fallback: str, allowed: tuple, marker_path: str | None = None
) -> str:
    """Default from the hardware-promoted config marker
    (``.cache/best_config.json``, written by scripts/hw_campaign2.sh's
    ``promote`` after a full-measured, parity-passing on-device record
    beats the incumbent). Env knobs always win over the marker."""
    if marker_path is None:
        marker_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            ".cache",
            "best_config.json",
        )
    try:
        with open(marker_path) as f:
            val = json.load(f).get(key)
        return val if val in allowed else fallback
    except Exception:
        return fallback


def _plan_cache():
    """The on-disk plan/oracle artifact cache (``.cache/plans/``)."""
    from tnc_tpu.benchmark.cache import ArtifactCache

    return ArtifactCache(
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".cache", "plans"
        )
    )


def _current_exec() -> str:
    """Resolved sliced-executor strategy: BENCH_EXEC env, else the
    hardware-promoted marker, else chunked. One definition so the retry
    ladder always flips AWAY from the strategy the failed run used."""
    return os.environ.get("BENCH_EXEC") or _tuned_default(
        "exec", "chunked", ("chunked", "loop")
    )


def _current_target_log2() -> float:
    """Resolved slicing target: BENCH_TARGET_LOG2_PEAK env, else the
    hardware-promoted marker, else 2^29. One definition shared by the
    run, the retry ladder's target-downgrade step, and
    scripts/oracle_status.py's parity clamp (which must report the
    oracle cache of the SAME plan the run will execute)."""
    return float(
        os.environ.get("BENCH_TARGET_LOG2_PEAK")
        or _tuned_default("target_log2", "29", ("28", "29", "30"))
    )


def _time_backend(run, reps, region="run"):
    """Median wall-clock of ``run()`` over ``reps`` after one warmup.

    ``run()`` may return device arrays (host=False executors) — timing
    blocks on readiness WITHOUT a device→host transfer: on tunneled
    backends the first D2H permanently degrades dispatch ~400×
    (TPU_EVIDENCE_r03.md), so every timed region must stay on device.

    Every region is also recorded as an obs span (``bench.warmup`` /
    ``bench.timed_run`` — the span INCLUDES the readiness block, so the
    exported timeline covers the real device wall time, not just the
    async dispatch).
    """
    import jax

    from tnc_tpu import obs

    t0 = time.monotonic()
    with obs.span("bench.warmup"):
        out = run()
        jax.block_until_ready(out)
    log(f"[bench] warmup (incl. compile): {time.monotonic() - t0:.2f}s")
    times = []
    for _ in range(reps):
        t0 = time.monotonic()
        with obs.span("bench.timed_run"):
            out = run()
            jax.block_until_ready(out)
        times.append(time.monotonic() - t0)
        # per-rep histogram, labeled by timed region: the perf gate's
        # noise estimate is the WITHIN-region rep spread — pooling the
        # probe with the full run would read their level difference as
        # noise and saturate the gate's tolerance
        obs.observe("bench.rep_s", times[-1], region=region)
    log(f"[bench] runs: {[round(t, 4) for t in times]}")
    return float(np.median(times)), out


def _time_pipelined(bound, reps, calls=None):
    """Steady-state per-evaluation wall-clock of a zero-transfer bound
    executable (``JaxBackend.bind_resident``): enqueue ``calls``
    dispatches back-to-back and block once on the last result, so
    dispatch latency overlaps device execution instead of paying a full
    host↔device round-trip per evaluation (the VERDICT-r4 async timing
    discipline for dispatch-bound small networks). Median over ``reps``
    such timed regions; returns (per_eval_s, calls, last_out)."""
    import jax

    from tnc_tpu import obs

    if calls is None:
        calls = _env_int("BENCH_PIPELINE_CALLS", 32)
    t0 = time.monotonic()
    with obs.span("bench.warmup"):
        out = bound()
        jax.block_until_ready(out)
    log(f"[bench] warmup (incl. compile): {time.monotonic() - t0:.2f}s")
    times = []
    for _ in range(reps):
        t0 = time.monotonic()
        with obs.span("bench.timed_run", pipeline_calls=calls):
            for _ in range(calls):
                out = bound()
            jax.block_until_ready(out)
        times.append((time.monotonic() - t0) / calls)
        obs.observe("bench.rep_s", times[-1], region="pipelined")
    log(f"[bench] pipelined per-eval (x{calls}): "
        f"{[round(t * 1e3, 4) for t in times]} ms")
    return float(np.median(times)), calls, out


def _time_numpy(run, reps, calibration_run=None):
    """CPU-oracle counterpart of :func:`_time_pipelined`: same
    steady-state contract (arrays already in memory, repeated
    evaluation), median per-eval over ``reps`` regions.

    ``run`` must execute with per-step spans OFF (``step_spans=False``)
    so span bookkeeping never sits inside the timed region — on
    tiny-step programs it would rival the steps themselves and inflate
    the published baseline. ``calibration_run`` (same work, step spans
    on) is invoked ONCE, untimed, afterwards: the per-step calibration
    samples without the measurement distortion."""
    from tnc_tpu import obs

    run()  # warmup: allocator + BLAS thread pools
    times = []
    for _ in range(reps):
        t0 = time.monotonic()
        with obs.span("bench.cpu_baseline"):
            run()
        times.append(time.monotonic() - t0)
        obs.observe("bench.rep_s", times[-1], region="cpu_baseline")
    if calibration_run is not None and obs.enabled():
        calibration_run()
    return float(np.median(times))


def bench_sycamore_amplitude():
    """North-star: Sycamore-53 m=14 single amplitude, sliced (config #3)."""
    from tnc_tpu.builders.sycamore_circuit import sycamore_circuit
    from tnc_tpu.contractionpath.contraction_path import ContractionPath
    from tnc_tpu.contractionpath.paths.hyper import Hyperoptimizer
    from tnc_tpu.contractionpath.slicing import (
        slice_and_reconfigure,
        sliced_flops,
    )
    from tnc_tpu.ops.backends import JaxBackend
    from tnc_tpu.ops.program import flat_leaf_tensors
    from tnc_tpu.ops.sliced import build_sliced_program
    from tnc_tpu.tensornetwork.simplify import simplify_network

    qubits = _env_int("BENCH_QUBITS", 53)
    depth = _env_int("BENCH_DEPTH", 14)
    seed = _env_int("BENCH_SEED", 42)
    # 2^29 beats 2^28 on every axis for the north-star (CPU-verified
    # sweep, planner_refine r3): 12% fewer total flops, half the
    # dispatch count, modeled peak 5.5 GiB/slice -> batch clamp 2.
    # 2^30 cuts sliced-total flops another 9.7% (7.55e13, 2048 slices)
    # at batch clamp 1 — whether that wins on-device is campaign2's
    # stage 1d/1e A/B; a promotion pins it via the marker.
    target_log2 = _current_target_log2()
    ntrials = _env_int("BENCH_NTRIALS", 128)
    # one oracle slice by default: with the polished planner each slice
    # is ~4x bigger, and one 2^29-peak slice already takes minutes on a
    # single CPU core (the parity statistic is per-element max over the
    # whole stored tensor either way)
    cpu_slices = _env_int("BENCH_CPU_SLICES", 1)
    reps = _env_int("BENCH_REPS", 3)

    rng = np.random.default_rng(seed)
    raw, _ = sycamore_circuit(qubits, depth, rng).into_amplitude_network(
        "0" * qubits
    )
    tn = simplify_network(raw)
    log(
        f"[bench] network: {len(raw)} tensors -> {len(tn)} cores after host "
        f"simplification (sycamore-{qubits} m={depth})"
    )

    # -- plan (excluded from timing, like the reference's Sweep phase) ------
    # The plan is deterministic in (circuit, seed, ntrials, target), so it
    # is cached on disk like the reference's Sweep/Run artifact split
    # (``benchmark/src/main.rs:223-242``): a hardware attempt should spend
    # <1 s loading the plan, not ~107 s recomputing it (VERDICT r3 #3).
    from tnc_tpu.benchmark.northstar import northstar_plan_key

    target = 2.0**target_log2
    plan_t0 = time.monotonic()
    cache = _plan_cache()
    key = northstar_plan_key(qubits, depth, seed, ntrials, target_log2)
    inputs = list(tn.tensors)
    cached = None if os.environ.get("BENCH_NO_PLAN_CACHE") == "1" else cache.load_obj(key)
    if cached is not None:
        path_flops, path_size, replace_pairs, slicing = cached
        replace = ContractionPath.simple(replace_pairs)
        total_flops = sliced_flops(inputs, replace.toplevel, slicing)
        planning_s = time.monotonic() - plan_t0
        log(
            f"[bench] plan loaded from cache ({key}) in {planning_s:.2f}s: "
            f"flops={path_flops:.3e} peak=2^{np.log2(max(path_size, 1)):.1f}, "
            f"{len(slicing.legs)} sliced legs, {slicing.num_slices} slices"
        )
    else:
        result = Hyperoptimizer(
            ntrials=ntrials, seed=seed, target_size=target
        ).find_path(tn)
        path_flops, path_size = result.flops, result.size
        log(
            f"[bench] path: flops={result.flops:.3e} "
            f"peak=2^{np.log2(max(result.size, 1)):.1f} "
            f"(planned in {time.monotonic() - plan_t0:.1f}s)"
        )
        t0 = time.monotonic()
        replace_pairs, slicing = slice_and_reconfigure(
            inputs, result.ssa_path.toplevel, target
        )
        replace = ContractionPath.simple(replace_pairs)
        total_flops = sliced_flops(inputs, replace.toplevel, slicing)
        planning_s = time.monotonic() - plan_t0
        log(
            f"[bench] slicing: {len(slicing.legs)} legs, "
            f"{slicing.num_slices} slices, total flops {total_flops:.3e} "
            f"(slice+reconfigure in {time.monotonic() - t0:.1f}s)"
        )
        cache.store_obj(key, (path_flops, path_size, replace_pairs, slicing))
        log(f"[bench] plan cached as {key}")

    from tnc_tpu import obs

    with obs.span("bench.build_program", slices=slicing.num_slices):
        sp = build_sliced_program(tn, replace, slicing)
    arrays = [leaf.data.into_data() for leaf in flat_leaf_tensors(tn)]

    if os.environ.get("BENCH_PREWARM") == "1":
        # Tunnel-independent preparation (run under BENCH_FORCE_CPU=1):
        # plan + complex128 parity oracle + serial baseline timing are
        # all deterministic host work; computing them now means a live
        # hardware window spends zero time on anything but device runs.
        n_sub = max(
            1, min(_env_int("BENCH_PARITY_SLICES", 16), slicing.num_slices)
        )
        oracle = _oracle_artifact(
            cache, key, sp, arrays, n_sub,
            max(1, min(cpu_slices, slicing.num_slices)),
        )
        return (
            "prewarm_northstar",
            0.0,
            0.0,
            {
                "oracle_slices": int(oracle["n"]),
                "cpu_per_slice_s": round(float(oracle["cpu_per_slice_s"]), 3),
                "planning_s": round(planning_s, 1),
                "num_slices": slicing.num_slices,
            },
        )

    strategy = _current_exec()
    # complex-multiply lowering: `gauss` is the single tuned per-step
    # default (3 dots via the Gauss identity; the parity ladder pins
    # it), and unforced ("auto") the kernel promotion ladder
    # (ops.split_complex.KernelPolicy) decides per step on top of that
    # base — strassen for stem GEMMs over the crossover, fused
    # multi-step chains for dispatch-bound runs of small steps. Setting
    # TNC_TPU_COMPLEX_MULT / BENCH_COMPLEX_MULT / a hardware-promoted
    # marker (scripts/hw_campaign2.sh `promote`) forces ONE mode
    # everywhere — the A/B knob, no longer the primary mechanism.
    complex_mult = (
        os.environ.get("TNC_TPU_COMPLEX_MULT")
        or os.environ.get("BENCH_COMPLEX_MULT")
        or _tuned_default(
            "complex_mult",
            "auto",
            (
                "naive", "gauss", "fused", "fused_transpose", "strassen",
                "chain", "auto",
            ),
        )
    )
    if complex_mult != "auto":
        os.environ["TNC_TPU_COMPLEX_MULT"] = complex_mult
    precision = os.environ.get("BENCH_PRECISION") or _tuned_default(
        "precision", "float32", ("float32", "high", "default")
    )
    hoist_on = os.environ.get("BENCH_HOIST", "1") != "0"
    backend = JaxBackend(
        dtype="complex64",
        sliced_strategy=strategy,
        slice_batch=_env_int("BENCH_BATCH", 8),
        chunk_steps=_env_int("BENCH_CHUNK_STEPS", 48),
        precision=precision,
        loop_unroll=_env_int("BENCH_LOOP_UNROLL", 1),
        hoist=hoist_on,
    )
    log(
        f"[bench] executor: {strategy} "
        f"(complex_mult={complex_mult}, precision={precision}, "
        f"hoist={hoist_on})"
    )

    # -- hoist flop accounting (host-only; catches hoist-pass regressions
    # without TPU hardware). Two INDEPENDENT implementations must agree:
    # the planner's metadata-level split (StemAccountant marks variant
    # steps over the leg-replay) and the compiled-program split
    # (hoist_sliced_program marks variant steps over the actual
    # SlicedProgram; hoist_step_flops sums its dot shapes). Both count
    # the same k*m*n per step, so a step misclassified by the hoist
    # pass shifts cost between the two sides of exactly one of them and
    # breaks the agreement.
    from tnc_tpu.contractionpath.slicing import hoisted_sliced_flops
    from tnc_tpu.ops.hoist import hoist_step_flops

    inv_flops, res_flops, hoisted_total = hoisted_sliced_flops(
        inputs, replace.toplevel, slicing
    )
    per_slice_flops = total_flops / max(slicing.num_slices, 1)
    step_inv, step_res = hoist_step_flops(sp)
    scale = max(per_slice_flops, 1.0)
    # the split comparison holds for EVERY slice count — including the
    # 1-slice plan, where both the compiled hoist pass and
    # StemAccountant.hoist_split degrade to the same no-op (invariant
    # 0, everything residual); PR 6's bench-side carve-out is gone
    if (
        abs(step_inv - inv_flops) > 1e-6 * scale
        or abs((step_inv + step_res) - per_slice_flops) > 1e-6 * scale
        or res_flops > per_slice_flops * (1 + 1e-9)
    ):
        raise BenchCheckError(
            "hoist flop accounting disagrees: compiled split "
            f"(inv {step_inv:.6e}, res {step_res:.6e}) vs planner split "
            f"(inv {inv_flops:.6e}, res {res_flops:.6e}, per-slice "
            f"{per_slice_flops:.6e}) — hoist pass or StemAccountant "
            "regressed"
        )
    stem_fraction = inv_flops / max(per_slice_flops, 1e-30)
    log(
        f"[bench] hoist stem: invariant {inv_flops:.3e} flops "
        f"({stem_fraction:.1%} of per-slice), hoisted total "
        f"{hoisted_total:.3e} vs naive {total_flops:.3e} "
        f"({hoisted_total / max(total_flops, 1e-30):.3f}x)"
    )

    subset_npz = os.environ.get("BENCH_SUBSET_NPZ")
    if subset_npz:
        # Parity-subset worker mode: dispatch ONLY the parity slices and
        # fetch them while this fresh tunnel client is still healthy
        # (see _subset_via_subprocess for the why).
        n_sub = max(
            1, min(_env_int("BENCH_PARITY_SLICES", 16), slicing.num_slices)
        )
        got = np.asarray(
            backend.execute_sliced(sp, arrays, max_slices=n_sub)
        ).astype(np.complex128)
        import jax

        np.savez(
            subset_npz,
            got=got,
            n_sub=n_sub,
            platform=np.array(jax.devices()[0].platform),
        )
        return ("parity_subset", 0.0, 0.0, {"parity_slices": n_sub})

    extra = {
        "planning_s": round(planning_s, 1),
        "path_flops": float(f"{path_flops:.4e}"),
        "sliced_total_flops": float(f"{total_flops:.4e}"),
        "num_slices": slicing.num_slices,
        "complex_mult": complex_mult,
        "precision": precision,
        "hoist": hoist_on,
        "invariant_flops": float(f"{inv_flops:.4e}"),
        # residual fraction: per-slice flops the loop still pays after
        # hoisting, as a share of the naive per-slice flops
        "residual_flops_fraction": round(
            res_flops / max(per_slice_flops, 1e-30), 4
        ),
        "hoisted_total_flops": float(f"{hoisted_total:.4e}"),
    }
    num = slicing.num_slices

    # -- kernel promotion ladder: the plan the EXECUTORS actually run ------
    # The sliced executors apply the ladder per loop body (residual
    # chains fuse into single Pallas dispatches, eligible steps promote)
    # and the hoisted prelude auto-promotes stem GEMMs to strassen; the
    # credit mirrors that exact per-step resolution, weighted
    # prelude-once / residual-per-slice, so the headline MFU divides by
    # the arithmetic that executed. First-order: the chunked executor
    # re-plans chains per ~48-step chunk, so a chain crossing a chunk
    # boundary runs unfused (credit unaffected — chained steps cost
    # naive flops either way). Only split-complex (off-CPU) runs execute
    # these kernels; complex-dtype runs take no credit. The measured
    # per-bucket MFU comes from step spans when TNC_TPU_STEP_TIME is
    # armed — see "kernel_buckets" in the record.
    try:
        from tnc_tpu.ops.hoist import hoist_sliced_program
        from tnc_tpu.ops.program import step_flops as _step_flops
        from tnc_tpu.ops.split_complex import (
            auto_step_mode,
            effective_step_flops,
            kernel_plan_summary,
            plan_kernels,
            resolved_step_mode,
        )

        hp = hoist_sliced_program(sp) if (hoist_on and num > 1) else None
        if hp is not None and hp.is_noop:
            hp = None
        loop_program = hp.residual.program if hp is not None else sp.program
        loop_policy = plan_kernels(loop_program)
        kplan = kernel_plan_summary(loop_program, loop_policy)
        res_naive = res_eff = 0.0
        for i, st in enumerate(loop_program.steps):
            res_naive += _step_flops(st)
            res_eff += effective_step_flops(
                st, resolved_step_mode(st, loop_policy.modes[i])
            )
        pre_naive = pre_eff = 0.0
        pre_modes: dict = {}
        if hp is not None:
            for ps in hp.prelude_steps:
                mode = auto_step_mode(ps.step) or resolved_step_mode(ps.step)
                pre_naive += _step_flops(ps.step)
                pre_eff += effective_step_flops(ps.step, mode)
                pre_modes[mode] = pre_modes.get(mode, 0) + 1
        kplan["prelude"] = {
            "steps": len(hp.prelude_steps) if hp is not None else 0,
            "modes": pre_modes,
        }
        extra["kernel_plan"] = kplan
        log(
            f"[bench] kernel plan (per-slice loop): {kplan['dispatches']} "
            f"dispatches for {len(loop_program.steps)} steps "
            f"({kplan['chains']} fused chains covering "
            f"{kplan['chained_steps']}; prelude "
            f"{kplan['prelude']['steps']} steps "
            f"{kplan['prelude']['modes'] or ''}), buckets "
            + ", ".join(
                f"{name}: {b['steps']} steps "
                f"{b['effective_flops'] / max(b['flops'], 1e-30):.2f}x credit "
                f"{b['pred_bytes_planned'] / max(b['pred_bytes_naive'], 1e-30):.2f}x bytes "
                f"({'/'.join(sorted(b['modes']))}; "
                f"prec {'/'.join(sorted(b['precision']))})"
                for name, b in sorted(kplan["buckets"].items())
            )
        )
        naive_exec = pre_naive + num * res_naive
        eff_exec = pre_eff + num * res_eff
        if (
            backend.split_complex
            and naive_exec > 0
            and eff_exec < naive_exec
        ):
            # effective-flop crediting: the executed kernels run
            # algorithmically fewer multiplies (gauss 0.75x, strassen
            # 21/32x) — scale the MFU's flop numerator down to match
            extra["effective_flop_credit"] = round(eff_exec / naive_exec, 4)
    except Exception as e:  # noqa: BLE001 — reporting must not kill a run
        log(f"[bench] kernel plan unavailable: {type(e).__name__}: {e}")

    # -- probe: time a slice subset through the real executor --------------
    # All timed runs keep results ON DEVICE (host=False): on tunneled
    # backends the first device->host transfer permanently degrades
    # dispatch ~400x (TPU_EVIDENCE_r03.md), so the single D2H for the
    # amplitude happens only after every timed region is done.
    probe = _env_int("BENCH_MAX_SLICES", 0) or _env_int("BENCH_PROBE_SLICES", 64)
    probe = max(1, min(probe, num))
    log(f"[bench] probe: timing {probe}/{num} slices")
    with obs.span("bench.probe", slices=probe):
        probe_s, amp = _time_backend(
            lambda: backend.execute_sliced(
                sp, arrays, max_slices=probe, host=False
            ),
            reps,
            region="probe",
        )
    per_slice = probe_s / probe
    projected = per_slice * num
    log(f"[bench] {per_slice*1000:.2f} ms/slice -> projected full {projected:.1f}s")

    # -- A/B: hoisted vs naive sliced execution on the same probe subset --
    # (cheap: probe-sized timed regions; the prelude re-runs per probe
    # call, so the hoisted number is conservative for the full run)
    if (
        hoist_on
        and inv_flops > 0
        and slicing.num_slices > 1  # 1-slice plans bypass the slice loop
        and os.environ.get("BENCH_HOIST_AB", "1") != "0"
    ):
        with obs.span("bench.hoist_ab_naive", slices=probe):
            naive_probe_s, _ = _time_backend(
                lambda: backend.execute_sliced(
                    sp, arrays, max_slices=probe, host=False, hoist=False
                ),
                reps,
                region="hoist_ab_naive",
            )
        extra["probe_s_hoisted"] = round(probe_s, 4)
        extra["probe_s_naive"] = round(naive_probe_s, 4)
        if probe_s > 0:
            extra["hoist_probe_speedup"] = round(naive_probe_s / probe_s, 3)
        log(
            f"[bench] hoist A/B ({probe} slices): hoisted {probe_s:.3f}s "
            f"vs naive {naive_probe_s:.3f}s "
            f"({naive_probe_s / max(probe_s, 1e-9):.2f}x)"
        )

    forced_subset = bool(_env_int("BENCH_MAX_SLICES", 0))
    full_limit = float(os.environ.get("BENCH_FULL_SECONDS", "900"))
    if not forced_subset and probe < num and projected <= full_limit:
        # cheap enough: run and time ALL slices (the honest number)
        with obs.span("bench.full_run", slices=num):
            tpu_s, amp = _time_backend(
                lambda: backend.execute_sliced(sp, arrays, host=False),
                reps,
                region="full_run",
            )
    else:
        tpu_s = projected
        if probe < num:
            extra["extrapolated_from_slices"] = probe
            if hoist_on and inv_flops > 0:
                # the probe pays the one-time prelude once per timed
                # call, so linear extrapolation re-counts it num/probe
                # times: the projected wall-clock is an UPPER bound
                # (and the derived MFU a lower bound). Marked, not
                # modeled away — no unmeasured subtraction enters a
                # published number.
                extra["projection_includes_prelude_per_probe"] = True
            log(f"[bench] extrapolated full wall-clock: {tpu_s:.1f}s")

    # optional profiler trace (BENCH_TRACE=1 only — on the axon tunnel
    # the trace itself wedges; see _maybe_trace). On accelerators this
    # process performs NO device work after this point: the parity
    # subset and the only D2H happen in a fresh subprocess below.
    _maybe_trace(backend, sp, arrays, probe, extra)

    # everything after this line is untimed. On accelerators the
    # amplitude fetch AND the parity subset both run in a FRESH
    # subprocess: measured on the v5e (r4, 2026-07-31), after the
    # full-scale timed runs this process's next device operation —
    # profiler trace dispatch or even a scalar D2H — wedges the axon
    # tunnel indefinitely (>25 min at 0% CPU, twice), while a fresh
    # client dispatches the small subset and fetches it fine.
    import jax

    on_accel = jax.devices()[0].platform != "cpu"
    n_sub = max(1, min(_env_int("BENCH_PARITY_SLICES", 16), slicing.num_slices))
    parity_skip_reason = None
    if os.environ.get("BENCH_NO_PARITY") == "1":
        # wall-clock-only A/B stages: the parity subprocess costs ~2 min
        # of hardware window (fresh client init + probe) per invocation
        parity_skip_reason = "BENCH_NO_PARITY=1"
    elif on_accel and os.environ.get("BENCH_INLINE_FETCH") != "1":
        got_partial = _subset_via_subprocess(n_sub)
        if got_partial is None:  # one retry: a fresh client each attempt
            got_partial = _subset_via_subprocess(n_sub)
        if got_partial is None:
            # never fall back to this process's wedge-prone client: keep
            # the timing and mark parity unmeasured rather than hanging
            parity_skip_reason = "parity subset subprocess failed twice"
        else:
            amplitude = complex(np.asarray(got_partial).reshape(-1)[0])
            log(f"[bench] amplitude (partial sum ok): {amplitude}")
    else:
        # CPU path (or explicit BENCH_INLINE_FETCH=1): fetch and run the
        # subset in-process, the pre-r4 behavior.
        with obs.span("bench.parity_fetch", slices=n_sub):
            amplitude = complex(
                _fetch_device_result(backend, amp).reshape(-1)[0]
            )
            got_partial = np.asarray(
                backend.execute_sliced(sp, arrays, max_slices=n_sub)
            ).astype(np.complex128)
        log(f"[bench] amplitude (partial sum ok): {amplitude}")

    # -- achieved throughput / MFU -----------------------------------------
    # flops actually executed: hoisted runs skip the invariant stem on
    # all but one pass, so crediting the naive total would inflate MFU
    work_flops = hoisted_total if (hoist_on and inv_flops > 0) else total_flops
    # effective-flop crediting (kernel promotion ladder): the credit was
    # computed from the executors' actual per-step mode resolution,
    # prelude-once / residual-per-slice weighted — see the kernel-plan
    # block above; absent on complex-dtype (CPU) runs
    if extra.get("effective_flop_credit"):
        work_flops *= extra["effective_flop_credit"]
    achieved = work_flops / tpu_s if tpu_s > 0 else 0.0
    extra["tflops"] = round(achieved / 1e12, 3)
    peak = _device_peak_flops(jax.devices()[0])
    if peak:
        extra["mfu"] = round(achieved / peak, 4)
        if achieved > peak:
            # Physicality guard: implied throughput above the device's
            # bf16 headline peak means the timed region did not await
            # completion (measured r4: the tunnel resolves readiness of
            # a single fori_loop dispatch early — 4096 slices "in" 70 ms
            # = 6x peak — while multi-dispatch chunked timing is linear
            # in slice count and physically consistent). Never publish
            # such a number as a claim.
            extra["timing_suspect"] = (
                "implied FLOP/s exceeds device peak; completion not "
                "awaited by the timed region (tunnel early-ready — see "
                "CAMPAIGN_EVIDENCE_r04.md)"
            )
            log(
                f"[bench] TIMING SUSPECT: {achieved / 1e12:.1f} TFLOP/s "
                f"> device peak {peak / 1e12:.0f}"
            )
    log(
        f"[bench] achieved {achieved / 1e12:.2f} TFLOP/s"
        + (f" (MFU {achieved / peak:.1%} of bf16 peak)" if peak else "")
    )

    # -- parity: accelerator vs numpy oracle on the same slice subset ------
    # ≥16 slices by default (VERDICT r3 weak #3). The complex128 oracle
    # is minutes/slice of deterministic host numpy, so its per-slice
    # results and the serial baseline timing are cached keyed by the
    # plan (BENCH_PREWARM=1 computes them tunnel-independently).
    with obs.span("bench.oracle", parity_slices=n_sub):
        oracle = _oracle_artifact(
            cache, key, sp, arrays,
            # parity-skipped stages still need the serial CPU baseline for
            # vs_baseline, but must not pay minutes-per-slice of complex128
            # numpy for per-slice oracle results nothing will compare
            0 if parity_skip_reason is not None else n_sub,
            max(1, min(cpu_slices, slicing.num_slices)),
        )
    if parity_skip_reason is None:
        want_partial = np.sum(
            oracle["per_slice"][:n_sub], axis=0, dtype=np.complex128
        )
        denom = max(float(np.max(np.abs(want_partial))), 1e-30)
        parity = float(np.max(np.abs(got_partial - want_partial))) / denom
        log(f"[bench] parity vs numpy oracle ({n_sub} slices): {parity:.2e}")
        # BASELINE.md accuracy target (1e-5), restored from the quietly
        # relaxed 1e-4 gate now that naive-mult + Kahan close the gap
        parity_target = float(os.environ.get("BENCH_PARITY_TARGET", "1e-5"))
        if parity > parity_target:
            raise BenchCheckError(
                f"parity check failed: {parity:.2e} > {parity_target:g}"
            )
        extra["parity"] = float(f"{parity:.3e}")
        extra["parity_slices"] = n_sub
    else:
        log(f"[bench] parity UNMEASURED: {parity_skip_reason}")
        extra["parity_unmeasured"] = parity_skip_reason

    # -- calibration pass: one untimed complex64 slice with per-step
    # spans ON — the numpy-side samples obs.calibrate fits the record's
    # "calibration" block from. The timed baseline above runs with
    # spans OFF (bookkeeping must never sit inside a published timed
    # region); this pass is host-only work (safe on accelerator runs —
    # it never touches the device). BENCH_CALIBRATE=0 skips it.
    if obs.enabled() and os.environ.get("BENCH_CALIBRATE", "1") != "0":
        from tnc_tpu.ops.sliced import execute_sliced_numpy

        with obs.span("bench.calibration_pass", slices=1):
            execute_sliced_numpy(
                sp, arrays, dtype=np.complex64, max_slices=1
            )

    # -- CPU baseline: same program, serial slice subset, extrapolated -----
    # (rounds 1-3 methodology: slices are identical work by construction)
    cpu_s = float(oracle["cpu_per_slice_s"]) * slicing.num_slices
    extra["cpu_baseline_from_slices"] = int(oracle["cpu_timed_slices"])
    log(
        f"[bench] cpu oracle extrapolated (from "
        f"{oracle['cpu_timed_slices']} serial slices): {cpu_s:.1f}s"
    )

    return (
        f"sycamore{qubits}_m{depth}_amplitude_wallclock",
        tpu_s,
        cpu_s / tpu_s if tpu_s > 0 else 0.0,
        extra,
    )


def _oracle_artifact(cache, plan_key, sp, arrays, n_sub, n_time) -> dict:
    """Complex128 per-slice oracle results + serial complex64 baseline
    timing, cached keyed by the plan. Deterministic host work, so a
    cache hit costs ~0 s of a hardware window; ``BENCH_NO_PLAN_CACHE=1``
    forces recomputation.

    The artifact records the plan *content* fingerprint: oracle slices
    are meaningless for a different plan, and the plan under a given key
    can legitimately change across code versions (e.g. the native replay
    kernel shifted FP tie-breaks in leg selection) — a stale pairing is
    detected and recomputed rather than producing garbage parity."""
    from tnc_tpu.benchmark.northstar import oracle_key, plan_fingerprint
    from tnc_tpu.ops.sliced import execute_sliced_numpy, sliced_partials_numpy

    plan_fp = plan_fingerprint(sp)
    okey = oracle_key(plan_key)
    obj = (
        None
        if os.environ.get("BENCH_NO_PLAN_CACHE") == "1"
        else cache.load_obj(okey)
    )
    if isinstance(obj, dict) and obj.get("plan_fp") != plan_fp:
        # strict: an unstamped artifact is treated as mismatched too —
        # appending new-plan slices to unverified old-plan partials
        # would launder a mixed artifact as fresh
        # (scripts/stamp_oracle_fp.py migrates known-consistent caches)
        log(
            f"[bench] oracle cache {okey} was computed for a different "
            f"plan ({obj.get('plan_fp')} != {plan_fp}); recomputing"
        )
        obj = None
    if not isinstance(obj, dict):
        obj = {"n": 0, "per_slice": None, "cpu_per_slice_s": 0.0,
               "cpu_timed_slices": 0}
    obj["plan_fp"] = plan_fp
    have = int(obj.get("n", 0))
    if have >= n_sub and obj.get("cpu_timed_slices", 0) >= n_time:
        log(
            f"[bench] oracle loaded from cache ({okey}): {have} parity "
            f"slices, baseline {obj['cpu_per_slice_s']:.1f}s/slice"
        )
        return obj
    # incremental + parallel: slices are minutes of numpy each. Store
    # after every completed slice so a killed prewarm loses at most one
    # slice; with multiple cores, ONE spawn pool is started for all
    # remaining slices (pool cold-start + input pickling cost seconds,
    # so per-batch pools would pay them repeatedly) and results are
    # consumed in id order to keep the stored prefix contiguous.
    workers = max(1, min(os.cpu_count() or 1, n_sub - have))

    def append_and_store(s: int, part: np.ndarray) -> None:
        obj["per_slice"] = (
            part
            if obj["per_slice"] is None
            else np.concatenate([obj["per_slice"], part])
        )
        obj["n"] = s + 1
        cache.store_obj(okey, obj)

    if have < n_sub and workers > 1:
        import concurrent.futures
        import multiprocessing
        import pickle
        import zlib

        from tnc_tpu.ops.sliced import _par_init, _par_slice

        full = [np.asarray(a, dtype=np.complex128) for a in arrays]
        blob = zlib.compress(pickle.dumps((sp, full)), 1)
        try:
            ctx = multiprocessing.get_context("spawn")
            with concurrent.futures.ProcessPoolExecutor(
                max_workers=workers, mp_context=ctx,
                initializer=_par_init, initargs=(blob,),
            ) as pool:
                futures = {
                    s: pool.submit(_par_slice, s) for s in range(have, n_sub)
                }
                try:
                    for s in range(have, n_sub):
                        t0 = time.monotonic()
                        part = np.asarray(futures[s].result()).reshape(
                            (1,) + tuple(sp.program.result_shape)
                        )
                        append_and_store(s, part)
                        log(
                            f"[bench] oracle slice {s + 1}/{n_sub} in "
                            f"{time.monotonic() - t0:.1f}s (cached)"
                        )
                except Exception:
                    # don't let the context-exit shutdown(wait=True) sit
                    # through minutes-per-slice futures whose results the
                    # serial fallback would recompute anyway
                    for f in futures.values():
                        f.cancel()
                    raise
            have = n_sub
        except Exception as e:  # pool failure: serial loop below
            log(f"[bench] oracle pool failed ({e}); continuing serially")
            have = int(obj.get("n", have))
    for s in range(have, n_sub):
        t0 = time.monotonic()
        part = sliced_partials_numpy(
            sp, arrays, dtype=np.complex128, slice_ids=[s], workers=1
        )
        append_and_store(s, part)
        log(
            f"[bench] oracle slice {s + 1}/{n_sub} in "
            f"{time.monotonic() - t0:.1f}s (cached)"
        )
    if obj.get("cpu_timed_slices", 0) < n_time:
        t0 = time.monotonic()
        # step_spans=False: the published (and disk-cached) baseline
        # seconds must not include per-step span bookkeeping; the
        # calibration sample comes from a separate untimed pass
        # (bench_sycamore_amplitude's bench.calibration_pass)
        execute_sliced_numpy(
            sp, arrays, dtype=np.complex64, max_slices=n_time,
            step_spans=False,
        )
        obj["cpu_per_slice_s"] = (time.monotonic() - t0) / n_time
        obj["cpu_timed_slices"] = n_time
        cache.store_obj(okey, obj)
        log(
            f"[bench] baseline timing: {obj['cpu_per_slice_s']:.1f}s/slice "
            f"over {n_time} serial complex64 slices (cached)"
        )
    return obj


def _sa_rebalance(tn, partitioning, sa_rng, sa_seconds):
    """SA rebalancing of an initial min-cut partitioning against the
    critical-path objective (`IntermediatePartitioningModel`, the
    reference's best-performing trial model). Returns the improved
    assignment and a report dict for the bench JSON. ``sa_seconds<=0``
    skips; ``BENCH_SA_ROUNDS`` switches to a work-bounded,
    machine-independent round count (the wall-clock budget makes the
    plan load-dependent otherwise)."""
    if sa_seconds <= 0:
        return partitioning, {"sa_seconds": 0}
    from tnc_tpu.contractionpath.repartitioning.simulated_annealing import (
        IntermediatePartitioningModel,
        balance_partitions,
    )

    max_rounds = _env_int("BENCH_SA_ROUNDS", 0) or None
    t0 = time.monotonic()
    model = IntermediatePartitioningModel(tn)
    best_solution, best_score = balance_partitions(
        model,
        model.initial_solution(partitioning),
        sa_rng,
        max_time=sa_seconds,
        max_rounds=max_rounds,
    )
    took = time.monotonic() - t0
    log(
        f"[bench] SA partitioner: critical-path cost {best_score:.3e} "
        f"in {took:.1f}s"
    )
    report = {
        "sa_seconds": round(took, 1),
        "sa_score": float(f"{best_score:.4e}"),
    }
    if max_rounds:
        report["sa_rounds"] = max_rounds
    return best_solution[0], report


def _ssa_to_replace(ssa_pairs):
    """SSA pair list → replace-left pair list (flat paths only); thin
    wrapper over the canonical converter."""
    from tnc_tpu.contractionpath.contraction_path import (
        ContractionPath,
        ssa_replace_ordering,
    )

    return ssa_replace_ordering(
        ContractionPath.simple(list(ssa_pairs)), len(ssa_pairs) + 1
    ).toplevel


def _rank_solution(solution, hbm):
    """Execution-faithful lexicographic rank of a partitioned solution:
    (global slice count at the device budget, critical-path cost). The
    slice count comes from the SAME planner the executor runs
    (``plan_global_slicing``) — on the mesh the per-slice fixed cost
    dominates the flop term (measured round 4)."""
    from tnc_tpu.contractionpath.slicing import sliced_peak
    from tnc_tpu.parallel.partitioned import (
        flatten_partitioned_path,
        global_slicing_target,
        plan_global_slicing,
    )

    ptn, ppath, par, _ser = solution
    leaves, pairs = flatten_partitioned_path(ptn, ppath)
    target = global_slicing_target(hbm)
    # deep ranking cap: recognize budget-infeasible plans instead of
    # relaxing silently (executors keep the default executable cap)
    slicing = plan_global_slicing(leaves, pairs, target, max_slices=1 << 40)
    if sliced_peak(leaves, pairs, slicing) > target:
        # plan_global_slicing relaxed past the budget: the plan cannot
        # execute on the modeled device (measured r5: the 53q SA plan
        # relaxed to 2^42 elements and OOM'd at a 2.2 TB allocation) —
        # rank it unplaceable so a feasible strategy wins
        return (float("inf"), float("inf")), slicing
    return (slicing.num_slices, par), slicing


def _config5_serial_plan(tn, qubits, depth, seed):
    """Best-known *serial* plan for the config-5 instance (native hyper
    search, disk-cached): (flops, ssa_pairs, peak_elements). The serial
    plan anchors two candidate strategies (tree-cut partitioning and
    slice-parallel SPMD) and the honest cross-strategy speedup metric
    ``speedup_vs_best_serial``. Returns None when planning fails."""
    from tnc_tpu.benchmark.cache import cache_key

    trials = _env_int("BENCH_CONFIG5_TRIALS", 16)
    pcache = _plan_cache()
    key = cache_key(
        "config5-serial-v1", f"sycamore-{qubits}-m{depth}", seed, trials, "hyper"
    )
    use_cache = os.environ.get("BENCH_NO_PLAN_CACHE") != "1"
    if use_cache:
        cached = pcache.load_obj(key)
        if (
            isinstance(cached, dict)
            and len(cached.get("ssa", ())) == len(tn.tensors) - 1
        ):
            return cached["flops"], cached["ssa"], cached["peak"]
    try:
        from tnc_tpu.contractionpath.paths.hyper import Hyperoptimizer

        t0 = time.monotonic()
        result = Hyperoptimizer(
            ntrials=trials,
            seed=seed,
            reconfigure_budget=float(
                os.environ.get("BENCH_CONFIG5_RECONF_S", "30")
            ),
            polish_rounds=_env_int("BENCH_CONFIG5_POLISH", 6),
        ).find_path(tn)
        log(
            f"[bench] serial plan: {result.flops:.4e} flops, "
            f"peak 2^{np.log2(max(result.size, 1)):.1f} "
            f"({time.monotonic() - t0:.1f}s)"
        )
        obj = {
            "flops": float(result.flops),
            "ssa": [tuple(p) for p in result.ssa_path.toplevel],
            "peak": float(result.size),
        }
        if use_cache:
            pcache.store_obj(key, obj)
        return obj["flops"], obj["ssa"], obj["peak"]
    except Exception as e:  # noqa: BLE001 — serial plan is an optional anchor
        log(f"[bench] serial plan failed: {type(e).__name__}: {e}")
        return None


def _is_hw_device(dev: str) -> bool:
    """device is "{platform}:{device_kind}" — anything that isn't a
    CPU / cpu-fallback / virtual-mesh record is hardware evidence
    (same rule as scripts/consolidate_bench.py)."""
    return bool(dev) and not dev.startswith(("cpu", "virtual"))


def _attach_last_hw_record(
    record: dict, config: str, root: str | None = None
) -> None:
    """On a cpu-fallback capture, attach the round's most recent ON-DEVICE
    record for the same config from the consolidated repo artifact, so a
    collapsed tunnel window at capture time (the round-3 failure: good
    mid-round hardware evidence, cpu-fallback in the official JSON)
    doesn't strip the artifact of its pointer to real measurements. The
    fallback stays clearly labelled — this only ADDs provenance."""
    import glob

    here = root or os.path.dirname(os.path.abspath(__file__))
    try:  # newest consolidated round artifact wins
        art = sorted(glob.glob(os.path.join(here, "BENCH_ALL_r*.json")))[-1]
        with open(art) as f:
            merged = json.load(f)
        prior = merged.get(config)
        if isinstance(prior, dict) and _is_hw_device(str(prior.get("device", ""))):
            record["last_hw_record"] = prior
            record["last_hw_record_source"] = os.path.basename(art)
    except Exception:  # best-effort annotation must never break the run
        pass


def _subset_via_subprocess(n_sub: int) -> "np.ndarray | None":
    """Run the parity slice subset on the device in a FRESH process and
    return the fetched complex128 partial sum (None on failure).

    Round-4 hardware evidence: after the full-scale timed runs the axon
    tunnel wedges on the parent's next device operation (a scalar D2H sat
    >25 min at 0% CPU, twice), while a fresh client dispatches and
    fetches a small subset without trouble. The parent therefore never
    touches the device again after its timed (host=False) runs; the
    subset worker (BENCH_SUBSET_NPZ mode above) does the only D2H."""
    import tempfile

    tmp = tempfile.NamedTemporaryFile(suffix=".npz", delete=False)
    tmp.close()
    env = dict(os.environ)
    env["BENCH_SUBSET_NPZ"] = tmp.name
    env["BENCH_NO_RETRY"] = "1"
    env["BENCH_PARITY_SLICES"] = str(n_sub)
    env.pop("BENCH_MAX_SLICES", None)  # subset size is n_sub, not probe
    timeout = float(os.environ.get("BENCH_SUBSET_TIMEOUT", "900"))
    log(f"[bench] parity subset ({n_sub} slices) in a fresh process")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-2000:] if r.stderr else "")
        data = np.load(tmp.name)
        child_platform = str(data["platform"]) if "platform" in data else "?"
        if child_platform == "cpu":
            # the child silently fell back to CPU: its numbers are NOT
            # hardware parity; treating them as such would stamp
            # CPU-computed evidence with this process's device field
            log("[bench] parity subset child ran on CPU; discarding")
            return None
        return np.asarray(data["got"])
    except Exception as e:  # noqa: BLE001 — any failure → caller retries/skips
        log(f"[bench] parity subset subprocess failed: {type(e).__name__}: {e}")
        return None
    finally:
        try:
            os.unlink(tmp.name)
        except OSError:
            pass


def _fetch_device_result(backend, out) -> np.ndarray:
    """Single untimed D2H of an ``execute_on_device`` result (a
    (real, imag) pair in split mode), as a flat complex ndarray."""
    if backend.split_complex and isinstance(out, tuple):
        from tnc_tpu.ops.split_complex import combine_array

        return np.asarray(combine_array(*out))
    return np.asarray(out)


def _maybe_trace(backend, sp, arrays, probe, extra):
    """Capture a jax.profiler device trace of a subset run (SURVEY §5:
    trace-based profiling alongside the analytic cost model). Opt-in via
    BENCH_TRACE=1: on the tunneled axon backend jax.profiler.trace was
    measured to hang indefinitely (round 4, 2026-07-31 — the process sat
    >25 min at 0% CPU inside the trace with timed runs already done), so
    a default-on trace can wedge an otherwise-successful bench run."""
    if os.environ.get("BENCH_TRACE") != "1":
        return
    import jax
    trace_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_trace"
    )
    try:
        with jax.profiler.trace(trace_dir):
            backend.execute_sliced(sp, arrays, max_slices=min(probe, 8))
        extra["trace_dir"] = trace_dir
        log(f"[bench] profiler trace captured in {trace_dir}")
    except Exception as e:  # tunnel backends may not support profiling
        log(f"[bench] profiler trace unavailable: {type(e).__name__}: {e}")


def _attach_kernel_plan(extra: dict, program, backend) -> None:
    """Static kernel-plan block for single-program configs: per-bucket
    modes, dot-precision mix, credited flops, and predicted HBM bytes
    under naive vs planned modes — the surface
    ``scripts/perf_gate.py``'s planned≤naive bytes invariant checks on
    EVERY record, including the CPU smoke in check.sh. Best-effort:
    reporting must never fail a run."""
    try:
        from tnc_tpu.ops.split_complex import kernel_plan_summary

        extra["kernel_plan"] = kernel_plan_summary(
            program, backend.kernel_policy(program)
        )
    except Exception as e:  # noqa: BLE001 — reporting only
        log(f"[bench] kernel plan unavailable: {type(e).__name__}: {e}")


def bench_ghz3():
    """Config #1: 3-qubit GHZ statevector from QASM (README example)."""
    from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    from tnc_tpu.io.qasm import import_qasm
    from tnc_tpu.ops.backends import JaxBackend, NumpyBackend
    from tnc_tpu.ops.program import build_program, flat_leaf_tensors

    reps = _env_int("BENCH_REPS", 5)
    qasm = """OPENQASM 2.0;\ninclude "qelib1.inc";\nqreg q[3];\nh q[0];\ncx q[0], q[1];\ncx q[1], q[2];\n"""
    circuit = import_qasm(qasm)
    tn, _ = circuit.into_statevector_network()
    result = Greedy(OptMethod.GREEDY).find_path(tn)
    program = build_program(tn, result.replace_path())
    arrays = [leaf.data.into_data() for leaf in flat_leaf_tensors(tn)]

    backend = JaxBackend(dtype="complex64")
    # steady-state contract: inputs resident in HBM, dispatches
    # pipelined (block once per region), D2H only after timing — the
    # tunnel's first D2H degrades later dispatches ~430x
    bound = backend.bind_resident(program, arrays)
    tpu_s, calls, out = _time_pipelined(bound, reps)
    sv = _fetch_device_result(backend, out).reshape(-1)
    if abs(abs(sv[0]) - 1 / np.sqrt(2)) >= 1e-5:
        raise BenchCheckError(f"ghz3 amplitude wrong: {sv[0]} vs 1/sqrt(2)")

    cpu = NumpyBackend(dtype=np.complex64)
    cpu_s = _time_numpy(
        lambda: cpu.execute(program, arrays, step_spans=False), reps,
        calibration_run=lambda: cpu.execute(program, arrays),
    )
    extra = {"timing": "pipelined-steady-state", "pipeline_calls": calls}
    _attach_kernel_plan(extra, program, backend)
    return ("ghz3_statevector_wallclock", tpu_s,
            cpu_s / tpu_s if tpu_s else 0.0, extra)


def bench_random20():
    """Config #2: 20-qubit depth-12 random-circuit statevector, Greedy."""
    from tnc_tpu.builders.random_circuit import random_circuit
    from tnc_tpu.builders.connectivity import ConnectivityLayout
    from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    from tnc_tpu.ops.backends import JaxBackend, NumpyBackend
    from tnc_tpu.ops.program import build_program, flat_leaf_tensors

    seed = _env_int("BENCH_SEED", 42)
    reps = _env_int("BENCH_REPS", 3)
    rng = np.random.default_rng(seed)
    tn = random_circuit(
        20, 12, 0.4, 0.4, rng, ConnectivityLayout.SYCAMORE, bitstring="*" * 20
    )
    result = Greedy(OptMethod.GREEDY).find_path(tn)
    log(f"[bench] random20: flops={result.flops:.3e} peak={result.size:.3e}")
    program = build_program(tn, result.replace_path())
    arrays = [leaf.data.into_data() for leaf in flat_leaf_tensors(tn)]

    backend = JaxBackend(dtype="complex64")
    bound = backend.bind_resident(program, arrays)
    tpu_s, calls, out = _time_pipelined(bound, reps)
    sv = _fetch_device_result(backend, out).reshape(-1)
    norm = float(np.vdot(sv, sv).real)
    log(f"[bench] statevector norm: {norm:.6f}")
    if abs(norm - 1.0) >= 1e-3:
        raise BenchCheckError(f"random20 statevector norm wrong: {norm}")

    cpu = NumpyBackend(dtype=np.complex64)
    cpu_s = _time_numpy(
        lambda: cpu.execute(program, arrays, step_spans=False), reps,
        calibration_run=lambda: cpu.execute(program, arrays),
    )
    extra = {"timing": "pipelined-steady-state", "pipeline_calls": calls}
    _attach_kernel_plan(extra, program, backend)
    return ("random20_d12_statevector_wallclock", tpu_s,
            cpu_s / tpu_s if tpu_s else 0.0, extra)


def bench_qaoa30():
    """Config #4: 30-qubit QAOA Pauli-expectation with the SA partitioner."""
    import random as pyrandom

    from tnc_tpu.builders.qaoa_circuit import qaoa_circuit
    from tnc_tpu.contractionpath.repartitioning import compute_solution
    from tnc_tpu.ops.backends import JaxBackend, NumpyBackend
    from tnc_tpu.ops.program import build_program, flat_leaf_tensors
    from tnc_tpu.tensornetwork.partitioning import find_partitioning
    from tnc_tpu.tensornetwork.simplify import simplify_network

    qubits = _env_int("BENCH_QUBITS", 30)
    rounds = _env_int("BENCH_DEPTH", 2)
    seed = _env_int("BENCH_SEED", 42)
    reps = _env_int("BENCH_REPS", 3)
    k = _env_int("BENCH_PARTITIONS", 4)
    sa_seconds = float(os.environ.get("BENCH_SA_SECONDS", "30"))

    rng = np.random.default_rng(seed)
    raw = qaoa_circuit(qubits, rounds, rng).into_expectation_value_network()
    tn = simplify_network(raw)
    log(f"[bench] qaoa{qubits} p={rounds}: {len(raw)} -> {len(tn)} cores")

    partitioning = find_partitioning(tn, k)
    sa_rng = pyrandom.Random(seed)
    partitioning, _sa_report = _sa_rebalance(
        tn, partitioning, sa_rng, sa_seconds
    )
    ptn, ppath, parallel_cost, _ = compute_solution(
        tn, partitioning, rng=sa_rng
    )
    program = build_program(ptn, ppath)
    arrays = [leaf.data.into_data() for leaf in flat_leaf_tensors(ptn)]

    backend = JaxBackend(dtype="complex64")
    bound = backend.bind_resident(program, arrays)
    tpu_s, calls, out = _time_pipelined(bound, reps)
    ev = complex(_fetch_device_result(backend, out).reshape(-1)[0])
    log(f"[bench] <Z...Z> = {ev}")

    cpu = NumpyBackend(dtype=np.complex64)
    cpu_s = _time_numpy(
        lambda: cpu.execute(program, arrays, step_spans=False), reps,
        calibration_run=lambda: cpu.execute(program, arrays),
    )
    extra = {"timing": "pipelined-steady-state", "pipeline_calls": calls}
    _attach_kernel_plan(extra, program, backend)
    return (f"qaoa{qubits}_expectation_wallclock", tpu_s,
            cpu_s / tpu_s if tpu_s else 0.0, extra)


def bench_sycamore_m20_partitioned():
    """Config #5: Sycamore-53 depth-20 amplitude, 8-way partitioned with
    per-device slicing (the composed pipeline of BASELINE.md #5;
    reference entry points ``partitioning.rs:31`` +
    ``mpi/communication.rs:125,199``).

    The full contraction is ~1e19 flops — far beyond one round's budget
    on any backend — so the local phase is timed on a slice subset per
    partition and extrapolated (marked in the JSON). ``vs_baseline``
    reports the plan's parallel speedup (serial sum cost over
    critical-path cost), the same ratio the reference benchmark records
    as ``flops_sum``/``flops`` (``benchmark/src/results.rs:5-16``).
    """
    import random as pyrandom

    import jax

    from tnc_tpu.builders.sycamore_circuit import sycamore_circuit
    from tnc_tpu.contractionpath.repartitioning import compute_solution
    from tnc_tpu.parallel.partitioned import partitioned_sliced_executor
    from tnc_tpu.tensornetwork.partitioning import find_partitioning
    from tnc_tpu.tensornetwork.simplify import simplify_network

    # Default is a scaled instance: the full 53-qubit m=20 needs ~2^48
    # bytes per slice even at the slicing planner's cap — beyond any
    # single host (the reference runs this config only on a multi-node
    # cluster). The composed pipeline is identical at any size.
    qubits = _env_int("BENCH_QUBITS", 24)
    depth = _env_int("BENCH_DEPTH", 20)
    seed = _env_int("BENCH_SEED", 42)
    k = _env_int("BENCH_PARTITIONS", 8)
    probe = _env_int("BENCH_PROBE_SLICES", 2)
    sa_seconds = float(os.environ.get("BENCH_SA_SECONDS", "60"))

    devices = jax.devices()
    if len(devices) < k:
        raise BenchCheckError(
            f"config needs {k} devices, have {len(devices)} "
            "(driver runs this on the virtual 8-CPU mesh)"
        )
    split_complex = devices[0].platform != "cpu"

    rng = np.random.default_rng(seed)
    raw, _ = sycamore_circuit(qubits, depth, rng).into_amplitude_network(
        "0" * qubits
    )
    tn = simplify_network(raw)
    log(f"[bench] network: {len(raw)} -> {len(tn)} cores (m={depth})")

    t0 = time.monotonic()
    # SA rebalancing of the initial min-cut partitioning: on this
    # instance it cuts the critical path ~500x (measured: parallel
    # 9.3e12 -> 1.9e10; TPU_EVIDENCE_r04.md).
    # Best-known ratchet: the SA trajectory is wall-budgeted and runs
    # pooled chains, so equal-seed outcomes vary run to run (measured
    # r4: critical-path 1.5e10 vs 3.5e10 across equal 300 s budgets).
    # The best assignment is cached by instance key; each run WARM-
    # STARTS SA from it (the optimizer seeds best-so-far with the
    # initial solution) and the store is improve-only — captures never
    # regress, the same ratchet discipline the north-star plan cache
    # provides. "Better" is LEXICOGRAPHIC (the composed pipeline's
    # actual global slice count at the device budget, then critical
    # path): SA's critical-path objective alone happily trades memory
    # for parallel cost, and the composed run then pays for it in
    # global slices — measured r4: a 1.85e10 critical path needing 128
    # slices ran ~8x slower end-to-end than a 1.85e10 one needing 32;
    # on the mesh the per-slice fixed cost dominates the flop term. The
    # slice count comes from the SAME planner the executor runs
    # (plan_global_slicing), so the rank is execution-faithful.
    from tnc_tpu.benchmark.cache import cache_key

    # The budget is the MODELED device's (BASELINE #5 is an 8-way v5e
    # mesh; the virtual CPU mesh stands in for it), pinned explicitly so
    # plan ranks are comparable across hosts and processes — CPU
    # backends report host-dependent memory limits.
    hbm = _env_int("BENCH_HBM_BYTES", 0) or 16 * 2**30

    def _rank(assignment):
        """(global_slices, critical_path) for lexicographic compare."""
        solution = compute_solution(tn, assignment, rng=pyrandom.Random(seed))
        r, _slicing = _rank_solution(solution, hbm)
        return r, solution

    use_plan_cache = os.environ.get("BENCH_NO_PLAN_CACHE") != "1"
    pcache = _plan_cache()
    # budget is part of the key: ranks computed under different budgets
    # are not comparable (slice counts depend on the slicing target)
    pkey = cache_key(
        "config5-partition-v5",
        f"sycamore-{qubits}-m{depth}-hbm{hbm}",
        seed,
        k,
        "sa",
    )

    def _valid(obj) -> bool:
        # stale-artifact guard: an assignment is positional over the
        # simplified network's tensors; any upstream change that shifts
        # the tensor count invalidates it (fail safe: replan)
        return (
            isinstance(obj, dict)
            and len(obj.get("assignment", ())) == len(tn.tensors)
            and len(obj.get("rank", ())) == 2
        )

    cached_best = pcache.load_obj(pkey) if use_plan_cache else None
    if cached_best is not None and not _valid(cached_best):
        log("[bench] cached partitioning is stale (size mismatch); replanning")
        cached_best = None
    if cached_best is not None:
        partitioning = cached_best["assignment"]
    else:
        partitioning = find_partitioning(tn, k)
    partitioning, sa_report = _sa_rebalance(
        tn, partitioning, pyrandom.Random(seed), sa_seconds
    )
    if cached_best is not None:
        sa_report["warm_started_from_cache"] = True
    rank, (ptn, ppath, parallel_cost, serial_cost) = _rank(partitioning)
    if cached_best is not None and tuple(cached_best["rank"]) < rank:
        log(
            f"[bench] cached partitioning wins: rank "
            f"{tuple(cached_best['rank'])} < {rank}"
        )
        partitioning = cached_best["assignment"]
        sa_report["from_plan_cache"] = True
        rank, (ptn, ppath, parallel_cost, serial_cost) = _rank(partitioning)
    elif use_plan_cache:
        # improve-only store under an exclusive lock: concurrent runs
        # serialize the load-compare-store, so the ratchet is monotone
        import contextlib
        import fcntl

        lock_path = str(pcache.directory / f"{pkey}.lock")
        with open(lock_path, "w") as lf:
            with contextlib.suppress(OSError):
                fcntl.flock(lf, fcntl.LOCK_EX)
            latest = pcache.load_obj(pkey)
            if not _valid(latest) or rank < tuple(latest["rank"]):
                pcache.store_obj(
                    pkey,
                    {"assignment": list(partitioning), "rank": list(rank)},
                )
    sa_report["planned_global_slices"] = rank[0]
    log(
        f"[bench] partitioned: k={k}, critical-path {parallel_cost:.3e}, "
        f"serial {serial_cost:.3e}"
    )

    # ---- candidate strategies beyond the SA-rebalanced assignment ----
    # (round 5, VERDICT r4 #5): all ranks are execution-faithful
    # (sequential mesh rounds, then critical-path naive op cost) so the
    # three parallelism shapes compare on what the mesh actually pays.
    from tnc_tpu.contractionpath.repartitioning import (
        compute_solution_with_paths,
    )
    from tnc_tpu.contractionpath.communication_schemes import (
        CommunicationScheme,
    )
    from tnc_tpu.contractionpath.slicing import (
        find_parallel_slicing,
        sliced_flops,
    )
    from tnc_tpu.contractionpath.treecut import plan_treecut

    serial_plan = _config5_serial_plan(tn, qubits, depth, seed)
    strategy = os.environ.get("BENCH_STRATEGY", "auto")
    chosen = {
        "strategy": "partitioned",
        "rank": rank,
        "solution": (ptn, ppath, parallel_cost, serial_cost),
        "report": sa_report,
    }

    if serial_plan is not None:
        serial_flops, serial_ssa, _serial_peak = serial_plan
        # (b) tree-cut partitioning: contiguous frontier of the serial
        # tree, local paths preserved, latency-aware fan-in
        try:
            tc = plan_treecut(
                list(tn.tensors), serial_ssa, k,
                steps=_env_int("BENCH_TREECUT_STEPS", 20000),
                patience=_env_int("BENCH_TREECUT_PATIENCE", 4000),
                seed=seed,
            )
            tc_sol = min(
                (
                    compute_solution_with_paths(
                        tn, tc.assignment, tc.local_paths,
                        communication_scheme=(
                            CommunicationScheme.WEIGHTED_BRANCH_BOUND
                        ),
                        rng=pyrandom.Random(seed),
                    ),
                    # the tree's own top region is a latency-aware fan-in
                    # by construction; sometimes it beats the re-derived
                    # schedule
                    compute_solution_with_paths(
                        tn, tc.assignment, tc.local_paths,
                        rng=pyrandom.Random(seed),
                        communication_path=tc.toplevel,
                    ),
                ),
                key=lambda s: s[2],
            )
            tc_rank, tc_detail = _rank_solution(tc_sol, hbm)
            log(
                f"[bench] treecut candidate: rank {tc_rank} "
                f"(critical {tc_sol[2]:.3e}, serial {tc_sol[3]:.3e})"
            )
            if tc_rank < chosen["rank"]:
                chosen = {
                    "strategy": "treecut",
                    "rank": tc_rank,
                    "solution": tc_sol,
                    "report": dict(sa_report, treecut=True),
                }
        except Exception as e:  # noqa: BLE001 — candidate is optional
            log(f"[bench] treecut candidate failed: {type(e).__name__}: {e}")

        # (c) slice-parallel SPMD: the serial plan, sliced into a
        # device-divisible slice set; every device runs its share, one
        # psum combines (tnc_tpu.parallel.sliced_parallel)
        try:
            from tnc_tpu.parallel.partitioned import global_slicing_target

            # same budget model as the partitioned pipeline (padded
            # split-complex working set), so the strategies rank under
            # one memory story
            target_elems = global_slicing_target(hbm)
            # slice-and-reconfigure re-paths under the sliced size
            # model — measured r5: greedy slicing of the UNCHANGED path
            # costs 355x overhead at 30q where reconfigure pays 1.9x
            replace_pairs = None
            psl = None
            try:
                from tnc_tpu.contractionpath.slicing import (
                    slice_and_reconfigure,
                )

                rec_pairs, rec_sl = slice_and_reconfigure(
                    list(tn.tensors), serial_ssa, target_elems,
                    max_slices=1 << 40,
                )
                if rec_sl.num_slices >= k and rec_sl.num_slices % k == 0:
                    replace_pairs, psl = rec_pairs, rec_sl
                else:
                    # keep the re-pathed plan AND its slicing; only add
                    # divisibility legs on top of it
                    psl = find_parallel_slicing(
                        list(tn.tensors), rec_pairs, k, base=rec_sl
                    )
                    if psl is not None:
                        replace_pairs = rec_pairs
            except Exception as e:  # noqa: BLE001 — reconfigure is optional
                log(
                    f"[bench] reconfigured slice-parallel plan failed "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"serial path's greedy slicing"
                )
            if psl is None:
                # last resort: greedy slicing of the unchanged serial path
                replace_pairs = _ssa_to_replace(serial_ssa)
                psl = find_parallel_slicing(
                    list(tn.tensors), replace_pairs, k,
                    target_size=target_elems,
                )
            if psl is not None:
                tot = sliced_flops(list(tn.tensors), replace_pairs, psl)
                sp_rank = (psl.num_slices // k, tot / k)
                log(
                    f"[bench] slice-parallel candidate: rank {sp_rank} "
                    f"({psl.num_slices} slices, total {tot:.3e}, "
                    f"overhead {tot/serial_flops:.2f}x, "
                    f"vs-best-serial {serial_flops/(tot/k):.2f}x)"
                )
                if strategy == "sliced" or (
                    strategy == "auto" and sp_rank < chosen["rank"]
                ):
                    chosen = {
                        "strategy": "sliced",
                        "rank": sp_rank,
                        "slicing": psl,
                        "replace_pairs": replace_pairs,
                        "total_flops": tot,
                        "report": {
                            "slice_overhead": round(tot / serial_flops, 3),
                            "speedup_vs_best_serial": float(
                                f"{serial_flops / (tot / k):.3g}"
                            ),
                        },
                    }
        except Exception as e:  # noqa: BLE001 — candidate is optional
            log(
                f"[bench] slice-parallel candidate failed: "
                f"{type(e).__name__}: {e}"
            )
        if strategy == "partitioned":
            if chosen["strategy"] != "partitioned":
                chosen = {
                    "strategy": "partitioned",
                    "rank": rank,
                    "solution": (ptn, ppath, parallel_cost, serial_cost),
                    "report": sa_report,
                }

    planning_s = time.monotonic() - t0
    log(f"[bench] strategy: {chosen['strategy']} (planned {planning_s:.1f}s)")

    if chosen["strategy"] == "sliced":
        from tnc_tpu.contractionpath.contraction_path import ContractionPath
        from tnc_tpu.parallel.sliced_parallel import (
            distributed_sliced_contraction,
            make_mesh,
        )

        psl = chosen["slicing"]
        tot = chosen["total_flops"]
        mesh = make_mesh(k)
        path_obj = ContractionPath.simple(chosen["replace_pairs"])
        rounds_total = psl.num_slices // k

        rounds_probe = max(1, min(probe, rounds_total))
        t0 = time.monotonic()
        distributed_sliced_contraction(
            tn, path_obj, psl, mesh=mesh, split_complex=split_complex,
            max_slices=rounds_probe * k,
        )  # warmup at the probe's own chunk: compile stays out of the
        # timed region (the SPMD executable is cached per chunk)
        warmup_s = time.monotonic() - t0
        log(f"[bench] warmup (incl. compile): {warmup_s:.1f}s")

        t0 = time.monotonic()
        out = distributed_sliced_contraction(
            tn, path_obj, psl, mesh=mesh, split_complex=split_complex,
            max_slices=rounds_probe * k,
        )
        subset_s = time.monotonic() - t0
        per_round = subset_s / rounds_probe
        total = per_round * rounds_total
        amp = complex(
            np.asarray(out.data.into_data()).reshape(-1)[0]
        )
        log(
            f"[bench] {rounds_probe}/{rounds_total} mesh rounds in "
            f"{subset_s:.1f}s -> extrapolated full {total:.1f}s; "
            f"partial amplitude {amp}"
        )
        critical_of_plan = tot / k
        # vs_baseline: speedup over the BEST SERIAL plan executed on one
        # device — the honest cross-strategy number. (The same-plan
        # ratio serial/critical is definitionally k for slice-parallel;
        # it is still recorded as plan_parallel_speedup with that
        # caveat in the field name's docs.)
        vs_serial = float(f"{serial_flops / max(critical_of_plan, 1):.3g}")
        extra = {
            "strategy": "sliced-parallel",
            "global_slices": psl.num_slices,
            "sliced_legs": len(psl.legs),
            "mesh_rounds": rounds_total,
            "serial_plan_flops": serial_flops,
            "plan_parallel_speedup": round(tot / max(critical_of_plan, 1), 2),
            "plan_parallel_speedup_note": "definitional k for slice-parallel",
            "planning_s": round(planning_s, 1),
        }
        if rounds_probe < rounds_total:
            extra["extrapolated_from_slices"] = rounds_probe * k
        extra.update(chosen["report"])
        return (
            f"sycamore{qubits}_m{depth}_partitioned{k}_wallclock",
            total,
            vs_serial,
            extra,
        )

    ptn, ppath, parallel_cost, serial_cost = chosen["solution"]
    sa_report = chosen["report"]

    t0 = time.monotonic()
    run, slicing, _meta = partitioned_sliced_executor(
        ptn, ppath, devices=devices[:k], split_complex=split_complex,
        hbm_bytes=hbm, plan_max_slices=1 << 40,
    )
    setup_s = time.monotonic() - t0
    log(
        f"[bench] global slicing: {len(slicing.legs)} legs, "
        f"{slicing.num_slices} slices (setup {setup_s:.1f}s)"
    )

    t0 = time.monotonic()
    run(max_slices=1)  # warmup: compiles every local + fan-in program
    warmup_s = time.monotonic() - t0
    log(f"[bench] warmup (incl. compile): {warmup_s:.1f}s")

    n_probe = max(1, min(probe, slicing.num_slices))
    t0 = time.monotonic()
    out = run(max_slices=n_probe)
    subset_s = time.monotonic() - t0
    per_slice = subset_s / n_probe
    total = per_slice * slicing.num_slices
    log(
        f"[bench] {n_probe} slices in {subset_s:.1f}s -> "
        f"{per_slice*1000:.1f} ms/slice, extrapolated full {total:.1f}s"
    )
    amp = complex(np.asarray(out).reshape(-1)[0])
    log(f"[bench] partial amplitude: {amp}")

    extra = {
        "strategy": chosen["strategy"],
        "global_slices": slicing.num_slices,
        "sliced_legs": len(slicing.legs),
        "plan_parallel_speedup": round(serial_cost / max(parallel_cost, 1), 2),
        "planning_s": round(planning_s, 1),
    }
    if serial_plan is not None:
        extra["serial_plan_flops"] = serial_plan[0]
        extra["speedup_vs_best_serial"] = round(
            serial_plan[0] / max(parallel_cost, 1), 2
        )
    if n_probe < slicing.num_slices:
        extra["extrapolated_from_slices"] = n_probe
    extra.update(sa_report)
    return (
        f"sycamore{qubits}_m{depth}_partitioned{k}_wallclock",
        total,
        serial_cost / max(parallel_cost, 1),
        extra,
    )


CONFIGS = {
    "sycamore_amplitude": bench_sycamore_amplitude,
    "ghz3": bench_ghz3,
    "random20": bench_random20,
    "qaoa30": bench_qaoa30,
    "sycamore_m20_partitioned": bench_sycamore_m20_partitioned,
}


def _parse_serve_mix(spec: str) -> dict:
    """``BENCH_SERVE_MIX`` parser: ``"amplitude:6,sample:1,
    expectation:1,approx_amplitude:2"`` → weight per query type (types
    absent from the spec get weight 0; unknown names are an error).
    ``approx_amplitude`` requests ride the fidelity-tiered approximate
    tier (``submit(..., rtol=BENCH_SERVE_RTOL)``)."""
    known = (
        "amplitude", "sample", "expectation", "marginal",
        "approx_amplitude",
    )
    weights = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        name = name.strip()
        if name not in known:
            raise ValueError(
                f"BENCH_SERVE_MIX: unknown query type {name!r} "
                f"(known: {known})"
            )
        weight = int(w) if w.strip() else 1
        if weight < 0:
            raise ValueError(
                f"BENCH_SERVE_MIX: weight for {name!r} must be >= 0"
            )
        weights[name] = weight
    if not any(w > 0 for w in weights.values()):
        raise ValueError("BENCH_SERVE_MIX selects no queries")
    return weights


def _serve_reuse_sweep(spec: str, backend, backend_name: str, ref_model) -> dict:
    """``BENCH_SERVE_SWEEP=angles:N`` — the parameter-sweep serving
    workload: one brickwork ansatz, N angle settings sharing the first
    ``BENCH_SERVE_SWEEP_PREFIX`` rounds' angles (default depth-1), so
    every setting's contraction tree contains the same-valued prefix
    subtrees. Two legs bind and evaluate one amplitude per setting
    through a fresh plan cache each: reuse OFF (cold, the control) and
    reuse ON (a shared :class:`IntermediateStore` contracts the prefix
    once store-wide). The block records measured wall/qps for both
    legs plus the pinned-reference-model speedup (total predicted
    seconds cold vs prefix-once + residual-per-setting — reproducible
    without hardware timing), the store's hit rate / bytes held /
    prefix-flops saved, a queue-level dedup mini-pass (duplicate
    riders through a real service window), and the off-vs-on numeric
    agreement. Cross-checked by scripts/perf_gate.py like the per-type
    rows."""
    import tempfile

    from tnc_tpu import obs
    from tnc_tpu.builders.random_circuit import brickwork_sweep
    from tnc_tpu.ops.program import steps_bytes, steps_flops
    from tnc_tpu.serve import (
        ContractionService,
        IntermediateStore,
        PlanCache,
        bind_circuit,
    )

    mode, _, arg = spec.partition(":")
    if mode != "angles":
        raise ValueError(
            f"unknown BENCH_SERVE_SWEEP mode {spec!r} (expected 'angles:N')"
        )
    settings = max(int(arg or "16"), 2)
    n = _env_int("BENCH_SERVE_QUBITS", 10)
    depth = _env_int("BENCH_SERVE_DEPTH", 6)
    prefix_depth = _env_int("BENCH_SERVE_SWEEP_PREFIX", max(depth - 1, 1))
    seed = _env_int("BENCH_SEED", 42)

    def sweep_circuits():
        # regenerated per leg from a pinned stream (offset so the main
        # serve bench's draws don't shift the sweep): both legs bind
        # value-identical circuits
        rng = np.random.default_rng(seed + 1)
        return brickwork_sweep(n, depth, prefix_depth, settings, rng)

    bits = "".join(np.random.default_rng(seed + 2).choice(["0", "1"], n))

    def run_leg(store):
        results = []
        model_s = 0.0
        with tempfile.TemporaryDirectory() as tmp:
            cache = PlanCache(tmp)
            t0 = time.monotonic()
            for circ in sweep_circuits():
                bound = bind_circuit(
                    circ, plan_cache=cache, reuse_store=store
                )
                results.append(
                    complex(bound.amplitudes_det([bits], backend)[0])
                )
                # reuse ON: bound.program is the residual, so this sums
                # exactly the per-request work the reuse path repays
                steps = bound.program.steps
                model_s += ref_model.op_seconds(
                    steps_flops(steps), steps_bytes(steps),
                    dispatches=max(len(steps), 1),
                )
        wall = time.monotonic() - t0
        return results, wall, model_s

    with obs.span("bench.serve.reuse", settings=settings, leg="off"):
        off_results, off_wall, off_model_s = run_leg(None)
    store = IntermediateStore(cost_model=ref_model)
    with obs.span("bench.serve.reuse", settings=settings, leg="on"):
        on_results, on_wall, on_model_s = run_leg(store)
    st = store.stats()
    # what the ON leg actually paid, in pinned-model seconds: the cold
    # prefix materializations (counted once store-wide) + each
    # setting's residual (already summed by run_leg). Materialization
    # bytes aren't tracked — flops + dispatches dominate these shapes.
    on_model_s += ref_model.op_seconds(
        st["flops_computed"], dispatches=max(st["steps_computed"], 1.0)
    )
    diffs = [abs(a - b) for a, b in zip(off_results, on_results)]

    # queue-level dedup mini-pass: duplicate amplitude riders through a
    # real micro-batching window must collapse to unique dispatch rows
    dedup_collapses = 0
    rng = np.random.default_rng(seed + 3)
    uniq = ["".join(rng.choice(["0", "1"], n)) for _ in range(4)]
    with ContractionService.from_circuit(
        sweep_circuits()[0], backend=backend, max_batch=32,
        max_wait_ms=50.0,
    ) as svc:
        svc.amplitude(uniq[0])  # warm the window so the burst co-batches
        futs = [svc.submit(uniq[i % len(uniq)]) for i in range(32)]
        for f in futs:
            f.result(timeout=600)
        dedup_collapses = int(svc.stats()["counts"]["deduped"])

    hits, misses = st["hit"], st["miss"]
    return {
        "mode": mode,
        "backend": backend_name,
        "settings": settings,
        "qubits": n,
        "depth": depth,
        "prefix_depth": prefix_depth,
        "wall_s_off": round(off_wall, 4),
        "wall_s_on": round(on_wall, 4),
        "qps_off": round(settings / off_wall, 1) if off_wall > 0 else 0.0,
        "qps_on": round(settings / on_wall, 1) if on_wall > 0 else 0.0,
        "speedup": (
            round(off_wall / on_wall, 3) if on_wall > 0 else None
        ),
        "model_speedup": (
            round(off_model_s / on_model_s, 3) if on_model_s > 0 else None
        ),
        "hit_rate": round(hits / max(hits + misses, 1), 4),
        "hits": hits,
        "misses": misses,
        "bytes_held": st["bytes_held"],
        "entries": st["entries"],
        "prefix_flops_saved": st["prefix_flops_saved"],
        "dedup_collapses": dedup_collapses,
        "max_abs_diff": float(max(diffs)) if diffs else 0.0,
        "bitwise_equal": bool(diffs) and max(diffs) == 0.0,
    }


def _serve_bench() -> dict:
    """``--serve``: throughput/latency of the in-process query service
    (docs/serving.md). A random circuit is bound once (plan+compile
    amortized), then BENCH_SERVE_QUERIES requests drawn from the
    BENCH_SERVE_MIX amplitude/sample/expectation/marginal mix are fired
    from a thread pool through the mixed micro-batching queue; the
    block reports overall queries/sec, the realized batch-size
    distribution, p50/p99 latency, the same per query type
    (``by_type``: requests, qps, p50/p99 ms — the per-type serving
    surface scripts/perf_gate.py cross-checks), and the ``slo`` block
    (burn rates, the drift detector's worst measured-vs-baseline
    dispatch ratio, fired alerts — gate-checked at 1.5x drift), plus
    the per-fidelity-tier block (``by_tier``: exact vs approx
    requests, qps, p50/p99, escalations, measured mean dispatch
    seconds next to the cost model's predicted seconds — the
    cheaper-tier evidence ``scripts/perf_gate.py`` cross-checks).
    Fidelity knobs: BENCH_SERVE_RTOL (0.05) is the approx requests'
    tolerance, BENCH_SERVE_CHI_CAP (64) the ladder's top rung."""
    import concurrent.futures

    from tnc_tpu import obs
    from tnc_tpu.builders.random_circuit import brickwork_circuit
    from tnc_tpu.obs.slo import BurnWindow, LatencyObjective, SLOConfig
    from tnc_tpu.serve import ContractionService

    n = _env_int("BENCH_SERVE_QUBITS", 10)
    depth = _env_int("BENCH_SERVE_DEPTH", 6)
    n_queries = _env_int("BENCH_SERVE_QUERIES", 256)
    max_batch = _env_int("BENCH_SERVE_BATCH", 32)
    wait_ms = float(os.environ.get("BENCH_SERVE_WAIT_MS", "2"))
    mix = _parse_serve_mix(
        os.environ.get(
            "BENCH_SERVE_MIX", "amplitude:6,sample:1,expectation:1"
        )
    )
    rng = np.random.default_rng(_env_int("BENCH_SEED", 42))
    circuit = brickwork_circuit(n, depth, rng)

    backend = None  # numpy oracle
    backend_name = os.environ.get("BENCH_SERVE_BACKEND", "jax")
    if backend_name == "jax":
        from tnc_tpu.ops.backends import JaxBackend

        backend = JaxBackend(dtype="complex64", donate=False)

    def rand_bits() -> str:
        return "".join(rng.choice(["0", "1"], n))

    # one marginal mask for the whole run (the mask is the structure;
    # serving traffic reuses it), half the qubits marginalized
    marginal_mask = ["?"] * (n - n // 2) + ["*"] * (n // 2)

    rtol = float(os.environ.get("BENCH_SERVE_RTOL", "0.05"))

    def make_query(kind: str):
        if kind in ("amplitude", "approx_amplitude"):
            return kind, rand_bits()
        if kind == "sample":
            return kind, {
                "n_samples": _env_int("BENCH_SERVE_SAMPLES", 1),
                "seed": int(rng.integers(2**31)),
            }
        if kind == "expectation":
            return kind, "".join(rng.choice(list("ixyz"), n))
        bits = rand_bits()
        return kind, "".join(
            b if m == "?" else "*" for b, m in zip(bits, marginal_mask)
        )

    # weighted round-robin over the mix, so types interleave in the
    # queue the way mixed fleet traffic would
    cycle = [k for k, w in mix.items() for _ in range(w)]
    queries = [make_query(cycle[i % len(cycle)]) for i in range(n_queries)]
    use_queries = any(
        k not in ("amplitude", "approx_amplitude") for k, _ in queries
    )
    use_approx = any(k == "approx_amplitude" for k, _ in queries)

    def submit(query):
        kind, payload = query
        if kind == "amplitude":
            return svc.submit(payload)
        if kind == "approx_amplitude":
            return svc.submit(payload, rtol=rtol)
        return svc.submit_query(kind, payload)

    # SLO engine riding the measured run: a deliberately loose latency
    # objective — the bench fires its whole query set as one burst, so
    # per-request latency includes queueing behind the burst and only a
    # deadline-scale stall should alert; drift (self-baselined per
    # bucket on the first measured dispatches) is the signal the perf
    # gate actually watches
    slo_cfg = SLOConfig(
        objectives=(
            LatencyObjective(
                "*",
                float(os.environ.get("BENCH_SERVE_SLO_MS", "30000")) / 1e3,
                target=0.99,
            ),
        ),
        windows=(BurnWindow(60.0, 300.0, 14.4),),
        drift_threshold=float(
            os.environ.get("BENCH_SERVE_DRIFT_THRESHOLD", "3.0")
        ),
        drift_baseline_samples=4,
        drift_min_samples=8,
    )
    # the reference model pricing the approx tier's rung ladder (and
    # the exact plan) in the record: pinned constants, planner_quality
    # style, so the predicted-seconds column is reproducible without a
    # hardware calibration pass
    from tnc_tpu.obs.calibrate import CalibratedCostModel

    ref_model = CalibratedCostModel(
        flops_per_s=float(os.environ.get("BENCH_SERVE_REF_FLOPS", "2e9")),
        dispatch_s=float(os.environ.get("BENCH_SERVE_REF_DISPATCH", "2e-6")),
        bytes_per_s=float(os.environ.get("BENCH_SERVE_REF_BYTES", "8e9")),
    )
    approx_options = {
        "chi_cap": _env_int("BENCH_SERVE_CHI_CAP", 64),
        "cost_model": ref_model,
    }
    with obs.span("bench.serve", queries=n_queries):
        with ContractionService.from_circuit(
            circuit,
            backend=backend,
            queries=use_queries,
            approx=use_approx,
            approx_options=approx_options if use_approx else None,
            max_batch=max_batch,
            max_wait_ms=wait_ms,
            max_queue=max(n_queries, 1024),
            # cost-truth loop on the reference constants: production
            # sampling + drift-triggered refit state rides the record's
            # serving.calibration block (in-process versions only — the
            # bench is one replica, no shared registry)
            cost_model=ref_model,
            cost_truth=True,
        ) as svc:
            # warmup outside the timed window: one singleton (the
            # batch-1 bucket) AND one full amplitude batch (the
            # max_batch bucket — the jax threaded path compiles one
            # executable per pow2 bucket), plus one request per
            # non-amplitude type in the mix so every query structure
            # plans/compiles before the clock starts
            warm_bits = rand_bits()
            svc.amplitude(warm_bits)
            warm = [svc.submit(warm_bits) for _ in range(max_batch)]
            for f in warm:
                f.result(timeout=600)
            for kind, weight in mix.items():
                if kind != "amplitude" and weight > 0:
                    submit(make_query(kind)).result(timeout=600)
            svc.reset_stats()  # warmup must not skew the published stats
            # SLO engine attaches AFTER warmup: compile-time requests
            # must neither burn the latency objective nor seed the
            # drift detector's per-bucket baselines
            svc.attach_slo(slo_cfg)
            t0 = time.monotonic()
            with concurrent.futures.ThreadPoolExecutor(16) as pool:
                futs = list(pool.map(submit, queries))
            for f in futs:
                f.result(timeout=600)
            wall = time.monotonic() - t0
        stats = svc.stats()
    by_type = {}
    for kind, row in stats["by_type"].items():
        completed = row["counts"]["completed"]
        if completed == 0 and mix.get(kind, 0) == 0:
            continue  # not part of this run's mix
        by_type[kind] = {
            "requests": completed,
            "qps": round(completed / wall, 1) if wall > 0 else 0.0,
            "p50_ms": round(row["latency_s"]["p50"] * 1e3, 3),
            "p99_ms": round(row["latency_s"]["p99"] * 1e3, 3),
        }
    # per-fidelity-tier rows: measured qps/latency/dispatch seconds
    # next to the reference model's predicted seconds per dispatch —
    # the "approx tier is measurably cheaper" evidence, cross-checked
    # by scripts/perf_gate.py like the per-type rows
    by_tier = {}
    router = svc.fidelity_router
    for tier, row in stats.get("by_tier", {}).items():
        completed = row["counts"]["completed"]
        if completed == 0:
            continue
        predicted_s = None
        if tier == "approx" and router is not None:
            predicted_s = router.quote_seconds("amplitude")
        elif tier == "exact":
            from tnc_tpu.ops.program import steps_flops, steps_bytes

            steps = svc.bound.program.steps
            predicted_s = ref_model.op_seconds(
                steps_flops(steps), steps_bytes(steps),
                dispatches=max(len(steps), 1),
            )
        by_tier[tier] = {
            "requests": completed,
            "qps": round(completed / wall, 1) if wall > 0 else 0.0,
            "p50_ms": round(row["latency_s"]["p50"] * 1e3, 3),
            "p99_ms": round(row["latency_s"]["p99"] * 1e3, 3),
            "escalated": row["counts"].get("escalated", 0),
            "escalation_capped": row["counts"].get("escalation_capped", 0),
            "dispatch_mean_s": row["dispatch"]["mean_s"],
            "predicted_s": (
                round(predicted_s, 6) if predicted_s is not None else None
            ),
        }
    ref_constants = {
        "flops_per_s": ref_model.flops_per_s,
        "dispatch_s": ref_model.dispatch_s,
        "bytes_per_s": ref_model.bytes_per_s,
    }
    slo_stats = stats.get("slo") or {}
    drift_ratios = [
        row["ratio"] for row in (slo_stats.get("drift") or {}).values()
        if row.get("n", 0) >= slo_cfg.drift_min_samples
        and row.get("ratio", 0) > 0
    ]
    slo_block = {
        "alerts": [a["key"] for a in slo_stats.get("alerts", [])],
        "alerts_total": slo_stats.get("alerts_total", 0),
        "drift_max_ratio": (
            round(max(max(drift_ratios), 1.0 / min(drift_ratios)), 4)
            if drift_ratios
            else None
        ),
        "burn": [
            {
                "type": obj["type"],
                "burn_short": w["burn_short"],
                "burn_long": w["burn_long"],
                "factor": w["factor"],
            }
            for obj in slo_stats.get("objectives", [])
            for w in obj.get("windows", [])
        ],
    }
    block = {
        "backend": backend_name,
        "qubits": n,
        "depth": depth,
        "queries": n_queries,
        "mix": mix,
        "wall_s": round(wall, 4),
        "qps": round(n_queries / wall, 1) if wall > 0 else 0.0,
        "batch_size": stats["batch_size"],
        "latency_s": stats["latency_s"],
        "counts": stats["counts"],
        "by_type": by_type,
        "by_tier": by_tier,
        "reference_model": ref_constants,
        "slo": slo_block,
    }
    # serving.calibration: the cost-truth loop's state at burst end —
    # the live model generation, sampler fill, and the refit /
    # publish / rollback ledger (scripts/perf_gate.py cross-checks
    # model_version consistency and fit staleness)
    cal_stats = stats.get("calibration")
    if cal_stats:
        block["calibration"] = {
            "model_version": cal_stats["model_version"],
            "model": cal_stats["model"],
            "fitted_unix": cal_stats["fitted_unix"],
            "sampler": {
                "offered": cal_stats["sampler"]["offered"],
                "kept": cal_stats["sampler"]["kept"],
            },
            "counts": cal_stats["counts"],
        }
    sweep_spec = os.environ.get("BENCH_SERVE_SWEEP")
    if sweep_spec:
        block["reuse"] = _serve_reuse_sweep(
            sweep_spec, backend, backend_name, ref_model
        )
        r = block["reuse"]
        log(
            f"[bench]   reuse sweep: {r['settings']} settings, "
            f"{r['qps_off']} -> {r['qps_on']} q/s "
            f"(model speedup {r['model_speedup']}x, "
            f"hit rate {r['hit_rate']}, "
            f"dedup collapses {r['dedup_collapses']}, "
            f"max |diff| {r['max_abs_diff']:.3g})"
        )
    log(
        f"[bench] serving: {block['qps']} q/s over {n_queries} queries "
        f"(mix {mix}, mean batch {stats['batch_size']['mean']:.1f}, "
        f"p50 {stats['latency_s']['p50'] * 1e3:.2f} ms, "
        f"p99 {stats['latency_s']['p99'] * 1e3:.2f} ms)"
    )
    for kind, row in sorted(by_type.items()):
        log(
            f"[bench]   {kind}: {row['requests']} reqs, {row['qps']} q/s, "
            f"p50 {row['p50_ms']:.2f} ms, p99 {row['p99_ms']:.2f} ms"
        )
    for tier, row in sorted(by_tier.items()):
        log(
            f"[bench]   tier {tier}: {row['requests']} reqs, "
            f"{row['qps']} q/s, p50 {row['p50_ms']:.2f} ms, "
            f"escalated {row['escalated']}, dispatch "
            f"{row['dispatch_mean_s'] * 1e3:.3f} ms measured / "
            f"{row['predicted_s']} s predicted"
        )
    log(
        f"[bench]   slo: drift_max_ratio {slo_block['drift_max_ratio']}, "
        f"alerts {slo_block['alerts'] or 'none'}"
    )
    if "calibration" in block:
        c = block["calibration"]
        log(
            f"[bench]   calibration: model v{c['model_version']}, "
            f"sampler {c['sampler']['kept']}/{c['sampler']['offered']} "
            f"kept, refits {c['counts']['refits']}, rollbacks "
            f"{c['counts']['rollbacks']}"
        )
    fleet_block = _serve_fleet_block()
    if fleet_block is not None:
        block["fleet"] = fleet_block
        log(
            f"[bench]   fleet: {fleet_block['replicas_live']} live / "
            f"{fleet_block['replicas_stale']} stale replicas, "
            f"max heartbeat gap {fleet_block['max_heartbeat_gap_s']} s, "
            f"dispatch attribution {fleet_block['attribution_share']}"
        )
    openloop_spec = os.environ.get("BENCH_SERVE_OPENLOOP")
    if openloop_spec:
        block["openloop"] = _serve_openloop_block(
            openloop_spec, backend, n, depth, max_batch, wait_ms
        )
        o = block["openloop"]
        log(
            f"[bench]   open-loop: offered {o['offered_qps']} q/s x "
            f"{o['duration_s']} s ({o['offered']} arrivals), completed "
            f"{o['completed_qps']} q/s, p99 "
            f"{o['latency_s']['p99'] * 1e3:.2f} ms, max "
            f"{o['latency_s']['max'] * 1e3:.2f} ms, rejected "
            f"{o['rejected']}, preempted {o['preempted']}, reassigned "
            f"{o['reassigned']}"
        )
    return block


def _serve_openloop_block(
    spec: str, backend, n: int, depth: int, max_batch: int, wait_ms: float
) -> dict:
    """``BENCH_SERVE_OPENLOOP=qps:duration`` — open-loop overload leg.

    Unlike the closed-loop headline run (a thread pool that can only
    have 16 requests in flight, so a slow service throttles its own
    offered load), arrivals here are fired at a FIXED rate for the
    duration regardless of completions — queueing delay lands in the
    tail percentiles instead of silently shrinking the load. The leg
    runs on a fresh elastic-enabled service (``submit(tenant=,
    priority=)``): every BENCH_SERVE_OPENLOOP_PRIO_EVERY-th (16)
    arrival rides the priority lane under a separate tenant, so
    weighted-fair ordering and (on sliced plans) checkpoint-boundary
    preemption are exercised under overload. The block records offered
    vs completed qps, admission rejections, failed requests, tail
    latency (p50/p90/p99/max), and the run's delta of the
    ``serve.elastic`` preemption/reassignment counters —
    ``scripts/perf_gate.py`` warn cross-checks the tail, the completed
    rate, and the failure/rejection shares."""
    import tempfile

    from tnc_tpu.builders.random_circuit import brickwork_circuit
    from tnc_tpu.serve import ContractionService, ElasticConfig, QueueFullError
    from tnc_tpu.serve import elastic as elastic_mod

    rate_s, _, dur_s = spec.partition(":")
    try:
        rate, duration = float(rate_s), float(dur_s)
    except ValueError:
        raise ValueError(
            f"BENCH_SERVE_OPENLOOP expects 'qps:duration', got {spec!r}"
        ) from None
    if rate <= 0 or duration <= 0:
        raise ValueError(
            f"BENCH_SERVE_OPENLOOP qps and duration must be > 0: {spec!r}"
        )
    prio_every = _env_int("BENCH_SERVE_OPENLOOP_PRIO_EVERY", 16)
    max_queue = _env_int("BENCH_SERVE_OPENLOOP_QUEUE", 256)
    rng = np.random.default_rng(_env_int("BENCH_SEED", 42) + 1)
    tick = 1.0 / rate
    # a Circuit converts to a network exactly once, and the closed-loop
    # leg already consumed the shared one — rebuild the same structure
    circuit = brickwork_circuit(
        n, depth, np.random.default_rng(_env_int("BENCH_SEED", 42))
    )

    with tempfile.TemporaryDirectory(prefix="tnc_bench_openloop_") as ckpt:
        with ContractionService.from_circuit(
            circuit,
            backend=backend,
            max_batch=max_batch,
            max_wait_ms=wait_ms,
            max_queue=max_queue,
        ) as svc:
            svc.enable_elastic(ElasticConfig(ckpt_dir=ckpt))
            # warmup: singleton + full batch buckets compile before the
            # clock starts, same as the closed-loop leg
            warm_bits = "".join(rng.choice(["0", "1"], n))
            svc.amplitude(warm_bits)
            for f in [svc.submit(warm_bits) for _ in range(max_batch)]:
                f.result(timeout=600)
            svc.reset_stats()
            before = dict(elastic_mod.counters())
            futs = []
            rejected = 0
            i = 0
            t0 = time.monotonic()
            deadline = t0 + duration
            while True:
                target = t0 + i * tick
                if target >= deadline:
                    break
                now = time.monotonic()
                if now >= deadline:
                    break
                if now < target:
                    time.sleep(target - now)
                prio = bool(prio_every) and i % prio_every == prio_every - 1
                try:
                    futs.append(
                        svc.submit(
                            "".join(rng.choice(["0", "1"], n)),
                            tenant="burst" if prio else "default",
                            priority=5 if prio else 0,
                        )
                    )
                except QueueFullError:
                    rejected += 1  # admission control under overload
                i += 1
            offered = i
            failed = 0
            for f in futs:
                try:
                    f.result(timeout=600)
                except Exception:
                    failed += 1
            wall = time.monotonic() - t0  # arrival window + drain
            stats = svc.stats()
            after = dict(elastic_mod.counters())
    delta = {
        k: after.get(k, 0) - before.get(k, 0) for k in set(after) | set(before)
    }
    completed = stats["counts"]["completed"]
    return {
        "offered_qps": rate,
        "duration_s": duration,
        "offered": offered,
        "rejected": rejected,
        "failed": failed,
        "completed": completed,
        "completed_qps": round(completed / wall, 1) if wall > 0 else 0.0,
        "drain_wall_s": round(wall, 4),
        "latency_s": stats["latency_s"],
        "preempted": delta.get("preempted", 0),
        "reassigned": delta.get("reassigned", 0),
    }


def _serve_fleet_block() -> dict | None:
    """``serving.fleet`` block for cluster runs: replica roster health
    (from the ``BENCH_SERVE_FLEET_DIR`` / ``TNC_TPU_FLEET_DIR``
    heartbeat registry) and the share of ``serve.dispatch`` wall
    attributed to rider ids in this process's trace. None on
    single-process runs with no registry configured — the block only
    means something when a fleet was involved."""
    from tnc_tpu import obs

    fleet_dir = os.environ.get("BENCH_SERVE_FLEET_DIR") or os.environ.get(
        "TNC_TPU_FLEET_DIR"
    )
    try:
        import jax

        n_proc = jax.process_count()
    except Exception:
        n_proc = 1
    if fleet_dir is None and n_proc <= 1:
        return None
    out: dict = {
        "processes": n_proc,
        "replicas_live": None,
        "replicas_stale": None,
        "stale_transitions": 0,
        "max_heartbeat_gap_s": None,
        "attribution_share": None,
        "dispatch_wall_ms": None,
    }
    if fleet_dir is not None:
        try:
            from tnc_tpu.obs.fleet import FleetRegistry

            roster = FleetRegistry(fleet_dir).roster()
            out["replicas_live"] = roster["live"]
            out["replicas_stale"] = roster["stale"]
            out["stale_transitions"] = roster["transitions"]["went_stale"]
            ages = [r["age_s"] for r in roster["replicas"]]
            if ages:
                out["max_heartbeat_gap_s"] = round(max(ages), 3)
            # per-replica cost-model generations: >1 distinct version
            # means the fleet was split across model generations during
            # the run (perf_gate warns — mixed pricing taints fleet-wide
            # comparisons)
            versions = sorted({
                r["payload"]["model_version"]
                for r in roster["replicas"]
                if isinstance(r.get("payload"), dict)
                and r["payload"].get("model_version") is not None
            })
            if versions:
                out["model_versions"] = versions
        except Exception as e:  # registry unreadable ≠ bench failure
            out["registry_error"] = f"{type(e).__name__}: {e}"
    if obs.enabled():
        from tnc_tpu.obs.export import chrome_trace_events, serve_trace_rollup

        rollup = serve_trace_rollup(chrome_trace_events(obs.get_registry()))
        if rollup["dispatch_wall_ms"] > 0:
            out["attribution_share"] = rollup["attributed_share"]
            out["dispatch_wall_ms"] = round(rollup["dispatch_wall_ms"], 3)
    return out


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (survives processes, including
    the retry-ladder subprocesses, which inherit the env var). Big
    whole-network programs take minutes to compile on a tunneled
    backend and heavy compiles are what wedges the tunnel
    (TPU_EVIDENCE_r03.md) — a warm cache removes both risks. Harmless
    when the backend doesn't support it."""
    cache_dir = os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".cache", "jax_cache"
        ),
    )
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # pragma: no cover - version-dependent knobs
        log(f"[bench] compile cache unavailable: {type(e).__name__}: {e}")


def _run_config(config: str) -> dict:
    import jax

    from tnc_tpu import obs

    _enable_compile_cache()
    device = jax.devices()[0]
    log(f"[bench] device: {device.platform} ({device.device_kind})")
    # bench always records spans/metrics (BENCH_OBS=0 opts out): the
    # per-phase breakdown and the Perfetto timeline replace the old
    # ad-hoc perf_counter bookkeeping. A fresh registry per config run
    # keeps the breakdown attributable to THIS run.
    if os.environ.get("BENCH_OBS", "1") != "0":
        obs.configure(enabled=True, registry=obs.MetricsRegistry())
    with obs.span("bench.config", config=config):
        out = CONFIGS[config]()
    metric, tpu_s, vs_baseline = out[0], out[1], out[2]
    extra = out[3] if len(out) > 3 else {}
    record = {
        "metric": metric,
        # when this record was measured: the anchor perf_gate's
        # calibration-staleness warning compares fitted_unix against
        "written_unix": time.time(),
        "value": round(tpu_s, 4) if tpu_s >= 0.001 else float(f"{tpu_s:.3g}"),
        "unit": "s",
        "vs_baseline": (
            round(vs_baseline, 2)
            if vs_baseline >= 0.01
            else float(f"{vs_baseline:.3g}")
        ),
        "device": f"{device.platform}:{device.device_kind}",
    }
    record.update(extra)
    if os.environ.get("BENCH_SERVE") == "1":
        try:
            record["serving"] = _serve_bench()
        except Exception as e:  # noqa: BLE001 — one-JSON-line contract
            log(f"[bench] serving bench failed: {type(e).__name__}: {e}")
            record["serving"] = {"error": f"{type(e).__name__}: {e}"}
    if obs.enabled():
        _attach_obs_breakdown(record, obs)
    return record


def _kernel_buckets_from_spans(obs) -> dict:
    """Measured per-shape-bucket throughput from the run's ``step[...]``
    spans: seconds, naive and mode-credited (*effective*) flops, the
    kernel-mode mix, and — when the device peak is known — per-bucket
    MFU computed from the effective flops, so a kernel that runs
    algorithmically fewer multiplies (gauss 0.75x, strassen 21/32x)
    doesn't inflate its bucket. One source only, device preferred —
    same rule as the calibration fit (host milliseconds say nothing
    about device MFU). Empty without per-step spans (device runs need
    ``TNC_TPU_STEP_TIME``)."""
    rows = [
        r
        for r in obs.get_registry().span_records()
        if r.name.startswith("step[") and "bucket" in r.args
    ]
    if not rows:
        return {}
    sources = {str(r.args.get("executor", "")) for r in rows}
    source = "jax" if "jax" in sources else sorted(sources)[0]
    peak = None
    try:
        import jax

        device = jax.devices()[0]
        if source == "jax" and device.platform != "cpu":
            peak = _device_peak_flops(device)
    except Exception:  # noqa: BLE001 — reporting only
        peak = None
    buckets: dict[str, dict] = {}
    for r in rows:
        if str(r.args.get("executor", "")) != source:
            continue
        b = buckets.setdefault(
            str(r.args["bucket"]),
            {
                "spans": 0,
                "seconds": 0.0,
                "flops": 0.0,
                "effective_flops": 0.0,
                "bytes": 0.0,
                "modes": {},
                "precision": {},
            },
        )
        b["spans"] += 1
        b["seconds"] += r.dur_ns / 1e9
        flops = float(r.args.get("flops", 0.0))
        b["flops"] += flops
        b["effective_flops"] += float(r.args.get("flops_effective", flops))
        b["bytes"] += float(r.args.get("bytes_in", 0.0)) + float(
            r.args.get("bytes_out", 0.0)
        )
        mode = str(r.args.get("mode", "default"))
        b["modes"][mode] = b["modes"].get(mode, 0) + 1
        # the dot-precision rung the step ran under — annotated so a
        # bucket's MFU row says whether bf16x3 was in play
        rung = str(r.args.get("precision", "default"))
        b["precision"][rung] = b["precision"].get(rung, 0) + 1
    for b in buckets.values():
        secs = b["seconds"]
        b["seconds"] = float(f"{secs:.4e}")
        b["flops"] = float(f"{b['flops']:.4e}")
        b["effective_flops"] = float(f"{b['effective_flops']:.4e}")
        b["bytes"] = float(f"{b['bytes']:.4e}")
        if secs > 0.0:
            achieved = b["effective_flops"] / secs
            b["achieved_flops_per_s"] = float(f"{achieved:.4e}")
            b["achieved_bytes_per_s"] = float(f"{b['bytes'] / secs:.4e}")
            if peak:
                b["mfu"] = round(achieved / peak, 4)
    return {"source": source, "buckets": buckets}


def _distributed_from_spans(obs) -> dict | None:
    """The ``distributed`` bench block: per-level fan-in wall time,
    bytes over the interconnect (ICI device-to-device on one host, DCN
    for cross-process pairs), and the dispatch-overlap ratio
    (pairs/levels — the scheduled concurrency of the reduce tree; 1.0
    means a fully serial chain). Read from the ``partitioned.fanin`` /
    ``partitioned.fanin_level`` spans the overlapped executors emit;
    ``scripts/perf_gate.py`` cross-checks it between records."""
    level_spans = [
        r for r in obs.get_registry().span_records()
        if r.name == "partitioned.fanin_level"
    ]
    if not level_spans:
        return None
    per_level: dict[int, dict] = {}
    for r in level_spans:
        li = int(r.args.get("level", 0))
        d = per_level.setdefault(
            li,
            {"level": li, "pairs": 0, "runs": 0, "wall_s": 0.0,
             "bytes": 0.0, "flops": 0.0},
        )
        d["runs"] += 1
        d["pairs"] = max(d["pairs"], int(r.args.get("pairs", 0)))
        d["wall_s"] += r.dur_ns / 1e9
        d["bytes"] += float(r.args.get("bytes", 0.0))
        d["flops"] += float(r.args.get("flops", 0.0))
    levels = [per_level[li] for li in sorted(per_level)]
    pairs = sum(d["pairs"] for d in levels)
    for d in levels:
        d["wall_s"] = round(d["wall_s"], 6)
    out = {
        "fanin_levels": len(levels),
        "fanin_pairs": pairs,
        "dispatch_overlap_ratio": round(pairs / max(len(levels), 1), 3),
        "fanin_wall_s": round(sum(d["wall_s"] for d in levels), 6),
        "interconnect_bytes": float(
            f"{sum(d['bytes'] for d in levels):.4e}"
        ),
        "per_level": levels,
    }
    cross = [
        r for r in obs.get_registry().span_records()
        if r.name == "partitioned.fanin" and "cross_pairs" in r.args
    ]
    if cross:
        out["cross_process_pairs"] = int(
            max(r.args["cross_pairs"] for r in cross)
        )
    return out


def _attach_obs_breakdown(record: dict, obs) -> None:
    """Per-phase wall-time breakdown (from the obs registry, the reads
    that replaced the old ad-hoc timing) + the Chrome-trace export.
    Best-effort: a reporting failure must never break the run."""
    try:
        # span depth is per-thread (worker-thread spans start at 0), so
        # pin the breakdown to the coordinating thread — the one that
        # ran the bench.config wrapper — or phase totals would double-
        # count the per-partition worker spans nested under them
        cfg = [
            r for r in obs.get_registry().span_records()
            if r.name == "bench.config"
        ]
        stats = obs.get_registry().span_stats(
            max_depth=1, tid=cfg[-1].tid if cfg else None
        )
        phases = {
            name: round(s["total_s"], 4)
            for name, s in sorted(stats.items())
            if name != "bench.config"
        }
        if phases:
            record["phases"] = phases
        counters = obs.get_registry().snapshot()["counters"]
        for key in ("jit_cache.hit", "jit_cache.miss"):
            if key in counters:
                record.setdefault("jit_cache", {})[
                    key.split(".")[1]
                ] = int(counters[key])
        # per-rep timing spread, one entry per timed region: the perf
        # gate's noise model (scripts/perf_gate.py) reads the
        # within-region spread — regions deliberately differ in level
        # (probe vs full run), so they must not share one histogram
        hists = obs.get_registry().histograms()
        rep_stats = {}
        for (name, labels), h in sorted(hists.items()):
            if name != "bench.rep_s":
                continue
            region = dict(labels).get("region", "run")
            rep_stats[region] = {
                "count": int(h["count"]),
                "min_s": round(h["min"], 6),
                "max_s": round(h["max"], 6),
                "mean_s": round(h["sum"] / max(h["count"], 1), 6),
            }
        if rep_stats:
            record["rep_stats"] = rep_stats
        # cost-model calibration: fitted device model + prediction-error
        # distribution from whatever per-step spans the run recorded
        # (numpy-oracle steps always; device steps under TNC_TPU_STEP_TIME)
        from tnc_tpu.obs import calibrate as _calibrate

        cal = _calibrate.calibration_report()
        if cal is not None:
            record["calibration"] = cal
            log("[bench] cost-model calibration:")
            log(_calibrate.format_calibration_table(cal))
        # per-bucket measured throughput under the kernel promotion
        # ladder (effective-flop-credited; scripts/perf_gate.py gates
        # the bucket MFUs like it gates the calibrated throughput)
        kb = _kernel_buckets_from_spans(obs)
        if kb:
            record["kernel_buckets"] = kb
        # kernel fallback visibility: why fused/chain didn't fire
        # (ops.fused_fallback{reason=...}, ops.fused_chain_fallback)
        kernel_counters = obs.counters_by_prefix("ops.")
        if kernel_counters:
            record["kernel_counters"] = kernel_counters
        # distributed fan-in breakdown (overlapped-reduce runs only):
        # per-level wall time, interconnect bytes, overlap ratio — the
        # reduce phase also surfaces in the phases block (it nests
        # under the executor spans, so span_stats(max_depth=1) alone
        # would never show it)
        dist = _distributed_from_spans(obs)
        if dist:
            record["distributed"] = dist
            record.setdefault("phases", {})[
                "partitioned.fanin"
            ] = dist["fanin_wall_s"]
        # resilience activity (retries, degradation rungs, checkpoint
        # saves/resumes, fired faults): read BEFORE the trace export so
        # an unwritable trace path cannot drop the recovery record of
        # exactly the run that needed recovering
        resilience = obs.counters_by_prefix("resilience.")
        if resilience:
            record["resilience"] = resilience
        trace_out = (
            os.environ.get("BENCH_TRACE_JSON")
            or obs.trace_path()
            or os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "bench_trace.json",
            )
        )
        obs.export_chrome_trace(trace_out)
        record["trace_path"] = trace_out
        rows = obs.trace_summary(obs.load_trace_events(trace_out))
        log("[bench] per-stage trace summary "
            f"(full timeline: {trace_out}, load in ui.perfetto.dev):")
        log(obs.format_summary_table(rows))
    except Exception as e:  # noqa: BLE001 — observability is best-effort
        log(f"[bench] obs breakdown unavailable: {type(e).__name__}: {e}")


def main() -> None:
    if "--serve" in sys.argv[1:]:
        # carried by env, not argv: the virtual-mesh and retry-ladder
        # relaunches re-exec this file without the caller's flags
        os.environ["BENCH_SERVE"] = "1"
    if "--resume" in sys.argv[1:]:
        # arm slice-range checkpointing (docs/resilience.md): the chunked
        # executor persists accumulator+cursor under this directory and a
        # rerun resumes mid-range; retry-ladder subprocesses inherit it
        os.environ.setdefault(
            "TNC_TPU_CKPT",
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                ".cache", "bench_ckpt",
            ),
        )
        log(f"[bench] --resume: checkpoints in {os.environ['TNC_TPU_CKPT']}")
    config = os.environ.get("BENCH_CONFIG", "sycamore_amplitude")
    if config not in CONFIGS:
        _emit(
            {
                "metric": config,
                "value": 0.0,
                "unit": "s",
                "vs_baseline": 0.0,
                "error": f"unknown BENCH_CONFIG; one of {sorted(CONFIGS)}",
            }
        )
        raise SystemExit(2)

    if config == "sycamore_m20_partitioned" and os.environ.get("BENCH_VIRTUAL8") != "1":
        # Config #5 needs 8 devices; a single chip can't host it, so run
        # on the virtual 8-CPU mesh in a subprocess (the dryrun analogue).
        log("[bench] config #5: launching on the virtual 8-CPU mesh")
        env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU"))
        }
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
        env["BENCH_VIRTUAL8"] = "1"
        env["BENCH_NO_RETRY"] = "1"
        env.setdefault("TNC_TPU_HBM_BYTES", str(1 << 30))
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                timeout=3000,
            )
            sys.stderr.write(r.stderr)
            line = [
                l for l in r.stdout.splitlines() if l.strip().startswith("{")
            ]
            if line:
                record = json.loads(line[-1])
                record.setdefault("device", "virtual-8-cpu-mesh")
                record["note"] = "8-way composed run on the virtual CPU mesh"
                _emit(record)
                raise SystemExit(0 if r.returncode == 0 else 1)
        except subprocess.TimeoutExpired:
            pass
        _emit(
            {
                "metric": config,
                "value": 0.0,
                "unit": "s",
                "vs_baseline": 0.0,
                "error": "virtual-mesh subprocess failed",
            }
        )
        raise SystemExit(1)

    forced_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    if os.environ.get("BENCH_VIRTUAL8") == "1":
        forced_cpu = True
    if forced_cpu:
        _pin_cpu()
        platform = "cpu"
    else:
        platform = _probe_backend()
        if platform is None:
            log("[bench] accelerator unavailable; falling back to CPU")
            _pin_cpu()
            platform = "cpu-fallback"
    if platform in ("cpu", "cpu-fallback") and config == "sycamore_amplitude":
        # The full 2^16-slice north-star is accelerator-scale work; on a
        # CPU host, time a slice subset and extrapolate (marked in JSON).
        # 2 slices: each 2^29-target slice is minutes of single-core
        # work; the extrapolation is marked in the JSON either way.
        # Parity drops to 2 slices too — the DEVICE side of the parity
        # comparison is serial and ~2 min/slice on this path. (Prewarm
        # runs do host-oracle work only and keep the 16-slice default.)
        os.environ.setdefault("BENCH_MAX_SLICES", "2")
        os.environ.setdefault("BENCH_REPS", "1")
        if os.environ.get("BENCH_PREWARM") != "1":
            os.environ.setdefault("BENCH_PARITY_SLICES", "2")

    try:
        record = _run_config(config)
        if platform == "cpu-fallback":
            record["device"] = "cpu-fallback"
            record["note"] = "accelerator init failed; measured on CPU"
            _attach_last_hw_record(record, config)
        _emit(record)
        if platform not in ("cpu", "cpu-fallback"):
            # Skip interpreter teardown: a wedged tunnel client can hang
            # in atexit/destructors AFTER the JSON line is out, turning a
            # good run into a timeout kill (rc!=0) for the caller.
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(0)
        return
    except Exception as e:  # noqa: BLE001 — contract: one JSON line, always
        log(f"[bench] run failed on {platform}: {type(e).__name__}: {e}")
        if platform in ("cpu", "cpu-fallback"):
            _emit(
                {
                    "metric": config,
                    "value": 0.0,
                    "unit": "s",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
            raise SystemExit(1)

    if os.environ.get("BENCH_NO_RETRY") == "1":
        raise SystemExit(1)

    # Accelerator run died mid-config. Before abandoning the hardware,
    # climb the on-accelerator retry ladder in fresh subprocesses (this
    # process may hold a poisoned backend): smaller slice batch → deeper
    # slicing → the other executor. Only then fall back to CPU.
    target = _current_target_log2()
    cur_exec = _current_exec()
    ladder: list[tuple[str, dict]] = []
    if config == "sycamore_amplitude":
        ladder = [
            ("batch=1", {"BENCH_BATCH": "1"}),
            (
                f"target_log2={target - 2:g}",
                {"BENCH_TARGET_LOG2_PEAK": f"{target - 2:g}", "BENCH_BATCH": "4"},
            ),
            (
                "exec=chunked" if cur_exec == "loop" else "exec=loop",
                {"BENCH_EXEC": "chunked" if cur_exec == "loop" else "loop"},
            ),
        ]
        if os.environ.get("BENCH_HOIST", "1") != "0":
            # a hoist-specific compile/runtime failure shouldn't cost
            # the hardware window: one stage retries with the naive loop
            ladder.append(("hoist=0", {"BENCH_HOIST": "0"}))
    ladder.append(("cpu", {"BENCH_FORCE_CPU": "1"}))

    for stage, overrides in ladder:
        cpu_stage = "BENCH_FORCE_CPU" in overrides
        log(f"[bench] retrying in a subprocess: {stage}")
        env = dict(os.environ)
        if cpu_stage:
            env = {
                k: v
                for k, v in env.items()
                if not k.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU"))
            }
        env.update(overrides)
        env["BENCH_NO_RETRY"] = "1"
        # retry stages run degraded configs: one timed rep keeps a
        # legitimate full-slice run (<= BENCH_FULL_SECONDS, twice: one
        # warmup + one rep) inside the stage timeout, which otherwise
        # bounds a wedged-tunnel stage (~25 min vs 1 h each)
        env.setdefault("BENCH_REPS", "1")
        full_limit = float(os.environ.get("BENCH_FULL_SECONDS", "900"))
        stage_timeout = float(
            os.environ.get("BENCH_STAGE_TIMEOUT", str(1500 + 2 * full_limit))
        )
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                timeout=stage_timeout,
            )
            sys.stderr.write(r.stderr)
            line = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
            if r.returncode == 0 and line:
                record = json.loads(line[-1])
                if cpu_stage:
                    record["device"] = "cpu-fallback"
                    record["note"] = "accelerator run failed; measured on CPU"
                    _attach_last_hw_record(record, config)
                else:
                    record["retry_stage"] = stage
                _emit(record)
                return
        except subprocess.TimeoutExpired:
            log(f"[bench] retry stage {stage}: timed out")
    _emit(
        {
            "metric": config,
            "value": 0.0,
            "unit": "s",
            "vs_baseline": 0.0,
            "error": "accelerator run failed and every retry failed",
        }
    )
    raise SystemExit(1)


if __name__ == "__main__":
    main()
