#!/usr/bin/env python
"""Benchmark driver: Sycamore-53 depth-14 single-amplitude contraction.

The north-star config from BASELINE.md (#3): build the Sycamore-53
depth-14 amplitude network, plan a path with the native hyper-optimizer,
slice it to fit single-chip HBM, and execute on the JAX backend (TPU when
available). Prints ONE JSON line:

    {"metric": ..., "value": <wall-clock seconds>, "unit": "s",
     "vs_baseline": <speedup vs the CPU (numpy/BLAS) oracle>}

Methodology mirrors the reference benchmark's ``time_to_solution``
(``benchmark/src/main.rs:365-405``): path optimization is excluded from
the timed region; the contraction itself — all slices — is timed after a
warmup run that triggers XLA compilation. The CPU baseline runs the SAME
sliced program on a subset of slices with numpy and extrapolates linearly
(slices are identical work by construction), because running every slice
on CPU would take hours.

Configurable via env:
  BENCH_QUBITS (53), BENCH_DEPTH (14), BENCH_SEED (42),
  BENCH_TARGET_LOG2_PEAK (28), BENCH_NTRIALS (16),
  BENCH_CPU_SLICES (2), BENCH_REPS (3)
"""

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    qubits = int(os.environ.get("BENCH_QUBITS", "53"))
    depth = int(os.environ.get("BENCH_DEPTH", "14"))
    seed = int(os.environ.get("BENCH_SEED", "42"))
    target_log2 = float(os.environ.get("BENCH_TARGET_LOG2_PEAK", "28"))
    ntrials = int(os.environ.get("BENCH_NTRIALS", "16"))
    cpu_slices = int(os.environ.get("BENCH_CPU_SLICES", "2"))
    reps = int(os.environ.get("BENCH_REPS", "3"))

    import jax

    from tnc_tpu.builders.sycamore_circuit import sycamore_circuit
    from tnc_tpu.contractionpath.paths.hyper import Hyperoptimizer
    from tnc_tpu.contractionpath.slicing import sliced_flops
    from tnc_tpu.ops.backends import JaxBackend
    from tnc_tpu.ops.program import flat_leaf_tensors
    from tnc_tpu.ops.sliced import build_sliced_program

    device = jax.devices()[0]
    log = lambda *a: print(*a, file=sys.stderr)  # noqa: E731
    log(f"[bench] device: {device.platform} ({device.device_kind})")

    # -- build network ------------------------------------------------------
    from tnc_tpu.tensornetwork.simplify import simplify_network

    rng = np.random.default_rng(seed)
    circuit = sycamore_circuit(qubits, depth, rng)
    raw, _ = circuit.into_amplitude_network("0" * qubits)
    tn = simplify_network(raw)
    log(
        f"[bench] network: {len(raw)} tensors -> {len(tn)} cores after host "
        f"simplification (sycamore-{qubits} m={depth})"
    )

    # -- plan (excluded from timing, like the reference's Sweep phase) ------
    from tnc_tpu.contractionpath.contraction_path import ContractionPath
    from tnc_tpu.contractionpath.slicing import slice_and_reconfigure

    target = 2.0**target_log2
    t0 = time.monotonic()
    result = Hyperoptimizer(
        ntrials=ntrials, seed=seed, target_size=target
    ).find_path(tn)
    plan_s = time.monotonic() - t0
    log(
        f"[bench] path: flops={result.flops:.3e} "
        f"peak=2^{np.log2(max(result.size, 1)):.1f} (planned in {plan_s:.1f}s)"
    )

    inputs = list(tn.tensors)
    t0 = time.monotonic()
    replace_pairs, slicing = slice_and_reconfigure(
        inputs, result.ssa_path.toplevel, target
    )
    replace = ContractionPath.simple(replace_pairs)
    total_flops = sliced_flops(inputs, replace.toplevel, slicing)
    log(
        f"[bench] slicing: {len(slicing.legs)} legs, {slicing.num_slices} slices, "
        f"total flops {total_flops:.3e} "
        f"(slice+reconfigure in {time.monotonic() - t0:.1f}s)"
    )

    sp = build_sliced_program(tn, replace, slicing)
    leaves = flat_leaf_tensors(tn)
    arrays = [leaf.data.into_data() for leaf in leaves]

    # -- TPU/accelerator timing --------------------------------------------
    backend = JaxBackend(dtype="complex64")
    t0 = time.monotonic()
    amp_warm = backend.execute_sliced(sp, arrays)  # includes compile
    compile_s = time.monotonic() - t0
    log(f"[bench] warmup (incl. compile): {compile_s:.2f}s")

    times = []
    for _ in range(reps):
        t0 = time.monotonic()
        amp = backend.execute_sliced(sp, arrays)
        times.append(time.monotonic() - t0)
    tpu_s = float(np.median(times))
    amplitude = complex(np.asarray(amp).reshape(-1)[0])
    log(f"[bench] amplitude: {amplitude} | runs: {[round(t, 3) for t in times]}")

    # -- CPU baseline: same program, subset of slices, extrapolated --------
    from tnc_tpu.ops.sliced import execute_sliced_numpy

    n_sub = max(1, min(cpu_slices, slicing.num_slices))
    t0 = time.monotonic()
    execute_sliced_numpy(sp, arrays, dtype=np.complex64, max_slices=n_sub)
    cpu_sub_s = time.monotonic() - t0
    cpu_s = cpu_sub_s * (slicing.num_slices / n_sub)
    log(
        f"[bench] cpu oracle: {cpu_sub_s:.2f}s for {n_sub}/{slicing.num_slices} "
        f"slices -> {cpu_s:.1f}s extrapolated"
    )

    vs_baseline = cpu_s / tpu_s if tpu_s > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": f"sycamore{qubits}_m{depth}_amplitude_wallclock",
                "value": round(tpu_s, 4),
                "unit": "s",
                "vs_baseline": round(vs_baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
