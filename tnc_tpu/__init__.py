"""tnc_tpu — a TPU-native tensor-network contraction framework.

A from-scratch rebuild of the capabilities of qc-tum/TNC (reference:
``/root/reference``), designed TPU-first:

- Tensor metadata (legs, bond dimensions, nesting) lives host-side in light
  Python objects with the same leg set-algebra as the reference
  (``tnc/src/tensornetwork/tensor.rs``).
- Execution is a pluggable contractor: a NumPy CPU oracle and a JAX/XLA
  backend that compiles a whole contraction path into a single jitted
  program with static shapes, so every pairwise einsum lands on the MXU
  and intermediates stay in HBM (reference hot loop:
  ``tnc/src/tensornetwork/contraction.rs:52-57`` dispatches to TBLIS).
- Path planning (greedy / optimal / branch-and-bound / hyper-optimization,
  partitioning, simulated-annealing repartitioning) is pure host-side work,
  exactly as in the reference, and only the emitted replace-format path is
  shipped to the executor.
- The distributed fan-in reduce (reference: ``tnc/src/mpi/communication.rs``)
  is expressed as collectives over a ``jax.sharding.Mesh`` instead of MPI
  point-to-point sends.
"""

__version__ = "0.1.0"

from tnc_tpu.utils.logging_config import (
    configure_from_env as _configure_logging,
    pin_platform_from_env as _pin_platform,
)

_configure_logging()
_pin_platform()

from tnc_tpu.tensornetwork.tensor import (  # noqa: F401
    CompositeTensor,
    LeafTensor,
    Tensor,
)
from tnc_tpu.tensornetwork.tensordata import TensorData  # noqa: F401
from tnc_tpu.contractionpath.contraction_path import (  # noqa: F401
    ContractionPath,
    path,
)
