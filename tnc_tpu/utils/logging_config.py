"""Library-wide structured logging.

The reference logs structured debug records throughout the library with
the ``log`` crate (``mpi/communication.rs:132``,
``tensornetwork/contraction.rs:36,58``) and lets the application pick the
sink. Here every module logs through the std :mod:`logging` hierarchy
under the ``tnc_tpu`` root logger; by default records propagate to
whatever handlers the application configured.

``TNC_TPU_LOG=<level>`` (debug/info/warning/...) attaches a stderr
handler to the ``tnc_tpu`` logger at import time — the zero-setup way to
watch the pipeline stages (compile, execute, partition, scatter, fan-in)
of a run, mirroring the reference benchmark's ``flexi_logger``
duplication to stdout (``benchmark/src/utils.rs:12-24``).
"""

from __future__ import annotations

import logging
import os

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def pin_platform_from_env() -> None:
    """Honor ``TNC_TPU_PLATFORM=<cpu|tpu|...>`` by pinning JAX's platform
    via ``jax.config`` at package import.

    Plain ``JAX_PLATFORMS`` env vars can be overridden by interpreter
    startup hooks that pre-wire JAX at an accelerator; ``jax.config``
    wins as long as no backend has been initialized yet. This gives
    examples and scripts one reliable knob
    (``TNC_TPU_PLATFORM=cpu python examples/local_contraction.py``).
    """
    platform = os.environ.get("TNC_TPU_PLATFORM")
    if not platform:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platform)
    except Exception:
        logging.getLogger("tnc_tpu").warning(
            "could not pin platform %r (backend already initialized?)",
            platform,
        )


def configure_from_env() -> None:
    """Attach a stderr handler at ``TNC_TPU_LOG``'s level, if set.

    >>> import os
    >>> os.environ.pop("TNC_TPU_LOG", None) and None
    >>> configure_from_env()   # unset: no handler attached, no error
    """
    level_name = os.environ.get("TNC_TPU_LOG")
    if not level_name:
        return
    level = getattr(logging, level_name.upper(), None)
    if not isinstance(level, int):
        return
    root = logging.getLogger("tnc_tpu")
    if any(getattr(h, "_tnc_tpu_env", False) for h in root.handlers):
        return
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(_FORMAT))
    handler._tnc_tpu_env = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.setLevel(level)
