from tnc_tpu.utils.datastructures import UnionFind  # noqa: F401
