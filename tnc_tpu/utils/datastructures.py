"""Shared data structures.

``UnionFind`` with path-splitting + union-by-rank, the connectivity helper
used by ``Tensor.is_connected`` (reference:
``tnc/src/utils/datastructures.rs:9-80``).
"""

from __future__ import annotations


class UnionFind:
    """Path-halving union-find (``utils/datastructures.rs``).

    >>> uf = UnionFind(4)
    >>> uf.union(0, 1), uf.union(2, 3), uf.union(1, 2)
    (True, True, True)
    >>> uf.union(0, 3)   # already one set
    False
    >>> uf.find(0) == uf.find(3)
    True
    """

    __slots__ = ("parent", "rank")

    def __init__(self, n: int) -> None:
        self.parent = list(range(n))
        self.rank = [0] * n

    def find(self, x: int) -> int:
        parent = self.parent
        while parent[x] != x:
            parent[x], x = parent[parent[x]], parent[x]
        return x

    def union(self, a: int, b: int) -> bool:
        """Union the sets of ``a`` and ``b``; returns True if they were disjoint."""
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return False
        if self.rank[ra] < self.rank[rb]:
            ra, rb = rb, ra
        self.parent[rb] = ra
        if self.rank[ra] == self.rank[rb]:
            self.rank[ra] += 1
        return True

    def num_sets(self) -> int:
        return sum(1 for i, p in enumerate(self.parent) if self.find(i) == i)
