"""Canonical structure digests.

One hashing discipline for every on-disk artifact keyed by program /
plan / circuit state: the benchmark artifact cache
(:mod:`tnc_tpu.benchmark.cache`), slice-range checkpoints
(:mod:`tnc_tpu.resilience.checkpoint`), and the serving plan cache
(:mod:`tnc_tpu.serve.plancache`). Each used to hash its own way
(``repr``-of-tuple here, raw sha256 there), which desyncs silently and
— worse — ``repr`` of dicts/sets depends on insertion order and Python
hash seeds, so "the same plan" could digest differently across
processes.

:func:`canonical_bytes` encodes a value tree deterministically:

- containers are length-prefixed and type-tagged; dict items are sorted
  by their *encoded key bytes* (not hash order), sets likewise;
- dataclasses (e.g. :class:`~tnc_tpu.ops.program.PairStep`,
  :class:`~tnc_tpu.contractionpath.slicing.Slicing`) encode as their
  class name + field name/value pairs;
- floats encode as IEEE-754 big-endian doubles, ints as decimal text,
  enums as class + value — never ``repr``.

The encoding is stable across Python hash seeds, dict insertion
orders, and interpreter versions (for the types above).

>>> stable_digest((1, "a", 2.5)) == stable_digest((1, "a", 2.5))
True
>>> stable_digest({"b": 1, "a": 2}) == stable_digest({"a": 2, "b": 1})
True
>>> stable_digest(1) == stable_digest("1")
False
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import struct
from typing import Any


def _encode(obj: Any, out: list[bytes]) -> None:
    if obj is None:
        out.append(b"N")
    elif isinstance(obj, bool):  # before int: bool subclasses int
        out.append(b"T" if obj else b"F")
    elif isinstance(obj, int):
        body = str(obj).encode()
        out.append(b"i%d:" % len(body) + body)
    elif isinstance(obj, float):
        out.append(b"f" + struct.pack("!d", obj))
    elif isinstance(obj, str):
        body = obj.encode("utf-8")
        out.append(b"s%d:" % len(body) + body)
    elif isinstance(obj, bytes):
        out.append(b"b%d:" % len(obj) + obj)
    elif isinstance(obj, enum.Enum):
        _encode((type(obj).__name__, obj.value), out)
        out.append(b"E")
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        fields = tuple(
            (f.name, getattr(obj, f.name)) for f in dataclasses.fields(obj)
        )
        _encode((type(obj).__name__, fields), out)
        out.append(b"D")
    elif isinstance(obj, (list, tuple)):
        out.append(b"l" if isinstance(obj, list) else b"t")
        out.append(b"%d:" % len(obj))
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, (set, frozenset)):
        items = sorted(canonical_bytes(item) for item in obj)
        out.append(b"S%d:" % len(items))
        out.extend(items)
    elif isinstance(obj, dict):
        items = sorted(
            (canonical_bytes(k), canonical_bytes(v)) for k, v in obj.items()
        )
        out.append(b"d%d:" % len(items))
        for k, v in items:
            out.append(k)
            out.append(v)
    else:
        # numpy scalars and other number-likes: fold to the plain type
        # BY NUMERIC KIND, not by value (dtype-qualified reprs differ
        # across versions, and value-based folding would make
        # np.float32(2.0) digest as an int while 2.0 digests as a
        # float — the same parameter arriving with a different type
        # must not change an on-disk signature)
        import numbers

        if isinstance(obj, numbers.Integral):
            _encode(int(obj), out)
        elif isinstance(obj, numbers.Real):
            _encode(float(obj), out)
        elif isinstance(obj, numbers.Complex):
            _encode((float(obj.real), float(obj.imag)), out)
            out.append(b"C")
        else:
            raise TypeError(
                f"stable_digest cannot canonically encode "
                f"{type(obj).__name__!r}"
            )


def canonical_bytes(obj: Any) -> bytes:
    """Deterministic byte encoding of a value tree (see module doc)."""
    out: list[bytes] = []
    _encode(obj, out)
    return b"".join(out)


def stable_digest(*parts: Any) -> str:
    """Hex sha256 over the canonical encoding of ``parts``.

    The one digest helper shared by the benchmark artifact cache, the
    checkpoint signatures, and the serving plan cache.
    """
    return hashlib.sha256(canonical_bytes(tuple(parts))).hexdigest()
