"""Quantum gate library.

Mirror of ``tnc/src/gates.rs``: a global registry of named gates, each a
function of angles returning a complex tensor. One-qubit gates are ``(2,2)``
matrices ``[out, in]``; two-qubit gates are stored shape ``(2,2,2,2)`` =
``(out_a, out_b, in_a, in_b)`` (``gates.rs:419-427``). The default adjoint
is the conjugate-transpose with the half-dims-swap convention
(``gates.rs:112-126``); rotation-like gates specialize it by negating
angles.

The 18 built-ins match ``gates.rs:17-38``: x, y, z, h, t, u, sx, sy, sz,
rx, ry, rz, cx, cz, swap, cp, iswap, fsim. User gates are registered with
:func:`register_gate` (lowercase names enforced, ``gates.rs:41-47``).
"""

from __future__ import annotations

import cmath
import math
from typing import Callable, Sequence

import numpy as np

from tnc_tpu.tensornetwork.tensordata import matrix_adjoint

GateFn = Callable[..., np.ndarray]

_C = np.complex128


def _check_angles(name: str, angles: Sequence[float], n: int) -> None:
    if len(angles) != n:
        raise ValueError(f"Gate '{name}': expected {n} angles, but got {len(angles)}.")


def _two_qubit(matrix: np.ndarray) -> np.ndarray:
    """Reshape a 4x4 matrix to the (2,2,2,2) storage layout."""
    return matrix.reshape(2, 2, 2, 2)


# -- gate definitions (gates.rs:150-555) -----------------------------------

_SQ2 = 1.0 / math.sqrt(2.0)


def _gate_x(angles: Sequence[float]) -> np.ndarray:
    _check_angles("x", angles, 0)
    return np.array([[0, 1], [1, 0]], dtype=_C)


def _gate_y(angles: Sequence[float]) -> np.ndarray:
    _check_angles("y", angles, 0)
    return np.array([[0, -1j], [1j, 0]], dtype=_C)


def _gate_z(angles: Sequence[float]) -> np.ndarray:
    _check_angles("z", angles, 0)
    return np.array([[1, 0], [0, -1]], dtype=_C)


def _gate_h(angles: Sequence[float]) -> np.ndarray:
    _check_angles("h", angles, 0)
    return np.array([[_SQ2, _SQ2], [_SQ2, -_SQ2]], dtype=_C)


def _gate_t(angles: Sequence[float]) -> np.ndarray:
    _check_angles("t", angles, 0)
    return np.array([[1, 0], [0, complex(_SQ2, _SQ2)]], dtype=_C)


def _gate_u(angles: Sequence[float]) -> np.ndarray:
    """OpenQASM-3 u(theta, phi, lambda) (gates.rs:252-272)."""
    _check_angles("u", angles, 3)
    theta, phi, lam = angles
    s, c = math.sin(theta / 2.0), math.cos(theta / 2.0)
    return np.array(
        [
            [c, -cmath.exp(1j * lam) * s],
            [cmath.exp(1j * phi) * s, cmath.exp(1j * (phi + lam)) * c],
        ],
        dtype=_C,
    )


def _gate_sx(angles: Sequence[float]) -> np.ndarray:
    _check_angles("sx", angles, 0)
    a, b = complex(0.5, 0.5), complex(0.5, -0.5)
    return np.array([[a, b], [b, a]], dtype=_C)


def _gate_sy(angles: Sequence[float]) -> np.ndarray:
    _check_angles("sy", angles, 0)
    a, b = complex(0.5, 0.5), complex(-0.5, -0.5)
    return np.array([[a, b], [a, a]], dtype=_C)


def _gate_sz(angles: Sequence[float]) -> np.ndarray:
    _check_angles("sz", angles, 0)
    return np.array([[1, 0], [0, 1j]], dtype=_C)


def _gate_rx(angles: Sequence[float]) -> np.ndarray:
    _check_angles("rx", angles, 1)
    s, c = math.sin(angles[0] / 2.0), math.cos(angles[0] / 2.0)
    return np.array([[c, -1j * s], [-1j * s, c]], dtype=_C)


def _gate_ry(angles: Sequence[float]) -> np.ndarray:
    _check_angles("ry", angles, 1)
    s, c = math.sin(angles[0] / 2.0), math.cos(angles[0] / 2.0)
    return np.array([[c, -s], [s, c]], dtype=_C)


def _gate_rz(angles: Sequence[float]) -> np.ndarray:
    _check_angles("rz", angles, 1)
    theta = angles[0]
    return np.array(
        [[cmath.exp(-1j * theta / 2.0), 0], [0, cmath.exp(1j * theta / 2.0)]], dtype=_C
    )


def _gate_cx(angles: Sequence[float]) -> np.ndarray:
    _check_angles("cx", angles, 0)
    m = np.array(
        [[1, 0, 0, 0], [0, 1, 0, 0], [0, 0, 0, 1], [0, 0, 1, 0]], dtype=_C
    )
    return _two_qubit(m)


def _gate_cz(angles: Sequence[float]) -> np.ndarray:
    _check_angles("cz", angles, 0)
    m = np.diag(np.array([1, 1, 1, -1], dtype=_C))
    return _two_qubit(m)


def _gate_swap(angles: Sequence[float]) -> np.ndarray:
    _check_angles("swap", angles, 0)
    m = np.array(
        [[1, 0, 0, 0], [0, 0, 1, 0], [0, 1, 0, 0], [0, 0, 0, 1]], dtype=_C
    )
    return _two_qubit(m)


def _gate_cp(angles: Sequence[float]) -> np.ndarray:
    _check_angles("cp", angles, 1)
    m = np.diag(np.array([1, 1, 1, cmath.exp(1j * angles[0])], dtype=_C))
    return _two_qubit(m)


def _gate_iswap(angles: Sequence[float]) -> np.ndarray:
    _check_angles("iswap", angles, 0)
    m = np.array(
        [[1, 0, 0, 0], [0, 0, 1j, 0], [0, 1j, 0, 0], [0, 0, 0, 1]], dtype=_C
    )
    return _two_qubit(m)


def _gate_fsim(angles: Sequence[float]) -> np.ndarray:
    """FSIM(theta, phi) as in cirq (gates.rs:530-548)."""
    _check_angles("fsim", angles, 2)
    theta, phi = angles
    a = complex(math.cos(theta), 0.0)
    b = complex(0.0, -math.sin(theta))
    c = cmath.exp(complex(0.0, -phi))
    m = np.array(
        [[1, 0, 0, 0], [0, a, b, 0], [0, b, a, 0], [0, 0, 0, c]], dtype=_C
    )
    return _two_qubit(m)


def _negated_angles_adjoint(fn: GateFn) -> GateFn:
    """Adjoint by negating all angles (rotation-like gates)."""

    def adjoint(angles: Sequence[float]) -> np.ndarray:
        return fn([-a for a in angles])

    return adjoint


def _conjugate_adjoint(fn: GateFn) -> GateFn:
    """Adjoint by elementwise conjugation (symmetric matrices)."""

    def adjoint(angles: Sequence[float]) -> np.ndarray:
        return np.conj(fn(angles))

    return adjoint


class Gate:
    """A named gate: compute(angles) -> tensor, adjoint(angles) -> tensor.

    ``arity`` (qubit count) is optional; when set, frontends validate the
    number of qubit arguments at call sites.
    """

    __slots__ = ("name", "compute", "_adjoint", "arity")

    def __init__(
        self,
        name: str,
        compute: GateFn,
        adjoint: GateFn | None = None,
        arity: int | None = None,
    ):
        self.name = name
        self.compute = compute
        self._adjoint = adjoint
        self.arity = arity

    def adjoint(self, angles: Sequence[float]) -> np.ndarray:
        if self._adjoint is not None:
            return self._adjoint(angles)
        return matrix_adjoint(self.compute(angles))


def _u_adjoint(angles: Sequence[float]) -> np.ndarray:
    _check_angles("u", angles, 3)
    theta, phi, lam = angles
    s, c = math.sin(theta / 2.0), math.cos(theta / 2.0)
    return np.array(
        [
            [c, cmath.exp(-1j * phi) * s],
            [-cmath.exp(-1j * lam) * s, cmath.exp(-1j * (phi + lam)) * c],
        ],
        dtype=_C,
    )


_GATES: dict[str, Gate] = {}


def register_gate(gate: Gate) -> None:
    """Register a gate; name must be lowercase (``gates.rs:41-47``)."""
    if gate.name != gate.name.lower():
        raise ValueError(f"Gate names must be lowercase, got '{gate.name}'")
    if gate.name in _GATES:
        raise ValueError(f"Gate '{gate.name}' is already registered")
    _GATES[gate.name] = gate


def _register_builtins() -> None:
    builtins = [
        Gate("x", _gate_x, _gate_x, 1),
        Gate("y", _gate_y, _gate_y, 1),
        Gate("z", _gate_z, _gate_z, 1),
        Gate("h", _gate_h, _gate_h, 1),
        Gate("t", _gate_t, _conjugate_adjoint(_gate_t), 1),
        Gate("u", _gate_u, _u_adjoint, 1),
        Gate("sx", _gate_sx, _conjugate_adjoint(_gate_sx), 1),
        # sy is asymmetric: generic conjugate-transpose adjoint
        Gate("sy", _gate_sy, None, 1),
        Gate("sz", _gate_sz, _conjugate_adjoint(_gate_sz), 1),
        Gate("rx", _gate_rx, _negated_angles_adjoint(_gate_rx), 1),
        Gate("ry", _gate_ry, _negated_angles_adjoint(_gate_ry), 1),
        Gate("rz", _gate_rz, _negated_angles_adjoint(_gate_rz), 1),
        Gate("cx", _gate_cx, _gate_cx, 2),
        Gate("cz", _gate_cz, _gate_cz, 2),
        Gate("swap", _gate_swap, _gate_swap, 2),
        Gate("cp", _gate_cp, _negated_angles_adjoint(_gate_cp), 2),
        Gate("iswap", _gate_iswap, _conjugate_adjoint(_gate_iswap), 2),
        Gate("fsim", _gate_fsim, _negated_angles_adjoint(_gate_fsim), 2),
    ]
    for g in builtins:
        register_gate(g)


_register_builtins()


def is_gate_known(name: str) -> bool:
    """Is ``name`` in the registry (``gates.rs:70-74``)?

    >>> is_gate_known("h"), is_gate_known("nonsense")
    (True, False)
    """
    return name in _GATES


def load_gate(name: str, angles: Sequence[float] = ()) -> np.ndarray:
    """Materialize a registered gate's matrix (``gates.rs:51-57``).

    >>> import numpy as np
    >>> np.allclose(load_gate("x"), [[0, 1], [1, 0]])
    True
    >>> load_gate("rz", [0.0]).shape
    (2, 2)
    """
    if name not in _GATES:
        raise KeyError(f"Gate '{name}' not found.")
    return _GATES[name].compute(angles)


def load_gate_adjoint(name: str, angles: Sequence[float] = ()) -> np.ndarray:
    if name not in _GATES:
        raise KeyError(f"Gate '{name}' not found.")
    return _GATES[name].adjoint(angles)


def gate_arity(name: str) -> int | None:
    """Qubit count of a registered gate, if declared."""
    gate = _GATES.get(name)
    return gate.arity if gate is not None else None


def gate_names() -> list[str]:
    return sorted(_GATES)
