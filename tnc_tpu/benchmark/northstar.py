"""Shared north-star plan-cache identifiers.

Single source of truth for the plan cache key and the plan-content
fingerprint, imported by ``bench.py``, ``scripts/oracle_status.py``, and
``scripts/stamp_oracle_fp.py`` — hand-copied key construction desyncs
silently on the next version bump, and a desynced status probe makes a
live hardware window redo cached oracle work.
"""

from __future__ import annotations

import hashlib
import pickle

from tnc_tpu.benchmark.cache import cache_key

#: bump when planner/slicer behavior changes invalidate old plans
PLAN_SCHEME = "northstar-plan-v2"


def northstar_plan_key(
    qubits: int, depth: int, seed: int, ntrials: int, target_log2: float
) -> str:
    """Stable cache key for the north-star plan.

    >>> northstar_plan_key(53, 14, 42, 128, 29.0) == northstar_plan_key(
    ...     53, 14, 42, 128, 29.0)
    True
    >>> northstar_plan_key(53, 14, 42, 128, 29.0).endswith("hyper-target2^29")
    True
    """
    return cache_key(
        PLAN_SCHEME,
        f"sycamore-{qubits}-m{depth}-seed{seed}-trials{ntrials}",
        seed,
        1,
        f"hyper-target2^{target_log2:g}",
    )


def oracle_key(plan_key: str) -> str:
    return plan_key.replace("northstar-plan", "northstar-oracle")


def plan_fingerprint(sp) -> str:
    """Content fingerprint of a sliced plan (the compiled program +
    slicing signature): oracle artifacts are valid only for the exact
    plan they were computed from."""
    return hashlib.sha256(pickle.dumps((sp.signature(),))).hexdigest()[:16]
