"""Sweep / Run phases (``benchmark/src/main.rs:267-353,355-405``)."""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from tnc_tpu.benchmark.cache import ArtifactCache, cache_key
from tnc_tpu.benchmark.methods import METHODS, MethodContext
from tnc_tpu.benchmark.protocol import Protocol
from tnc_tpu.benchmark.results import (
    OptimizationResult,
    ResultWriter,
    RunResult,
)
from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_cost import (
    communication_path_op_costs,
    compute_memory_requirements,
    contract_path_cost,
    contract_size_tensors_bytes,
)
from tnc_tpu.contractionpath.paths import Greedy, OptMethod
from tnc_tpu.tensornetwork.contraction import contract_tensor_network
from tnc_tpu.tensornetwork.tensor import CompositeTensor

log = logging.getLogger("tnc_tpu.benchmark")


@dataclass
class Scenario:
    """One (circuit, partitions, seed, method) cell of a sweep."""

    circuit_name: str
    circuit_text: str  # QASM source (hashed into the cache key)
    partitions: int
    seed: int
    method: str
    scheme: str = "greedy"

    @property
    def run_id(self) -> str:
        return (
            f"{self.method}_{self.circuit_name}_p{self.partitions}"
            f"_s{self.seed}"
        )

    def key(self) -> str:
        return cache_key(
            self.scheme, self.circuit_text, self.seed, self.partitions,
            self.method,
        )


def _serial_cost(tn: CompositeTensor) -> tuple[float, float]:
    """Greedy single-device baseline (memoized upstream in the reference,
    ``main.rs:246-264``)."""
    result = Greedy(OptMethod.GREEDY).find_path(tn)
    return result.flops, result.size


def do_sweep(
    scenario: Scenario,
    tn: CompositeTensor,
    cache: ArtifactCache,
    writer: ResultWriter,
    protocol: Protocol,
    time_budget: float = 600.0,
) -> OptimizationResult | None:
    """Optimize one scenario, cache the artifact, append the record.

    >>> import tempfile, os, numpy as np
    >>> from tnc_tpu.builders.connectivity import ConnectivityLayout
    >>> from tnc_tpu.builders.random_circuit import random_circuit
    >>> d = tempfile.mkdtemp()
    >>> tn = random_circuit(6, 4, 0.5, 0.5, np.random.default_rng(0),
    ...                     ConnectivityLayout.LINE)
    >>> sc = Scenario("toy", "toy-circuit", 2, 1, "greedy")
    >>> r = do_sweep(sc, tn, ArtifactCache(os.path.join(d, "cache")),
    ...     ResultWriter(os.path.join(d, "r.jsonl")),
    ...     Protocol(os.path.join(d, "p.jsonl")), time_budget=5.0)
    >>> r.method, r.flops > 0
    ('greedy', True)
    >>> do_sweep(sc, tn, ArtifactCache(os.path.join(d, "cache")),
    ...     ResultWriter(os.path.join(d, "r.jsonl")),
    ...     Protocol(os.path.join(d, "p.jsonl")), time_budget=5.0) is None
    True

    Returns None when the protocol says this cell already ran (or
    crashed last time) — the crash-resume behavior of the reference.
    """
    run_id = "sweep/" + scenario.run_id
    if not protocol.should_run(run_id):
        log.info("skipping %s (already done or failed)", run_id)
        return None
    protocol.trying(run_id)

    method = METHODS[scenario.method]
    serial_flops, serial_memory = _serial_cost(tn)

    ctx = MethodContext(
        tn=tn,
        partitions=scenario.partitions,
        seed=scenario.seed,
        time_budget=time_budget,
        communication_scheme=CommunicationScheme.GREEDY,
    )
    t0 = time.monotonic()
    out_tn, out_path = method.run(ctx)
    optimization_time = time.monotonic() - t0

    # characterize: critical-path + sum cost, memory
    if out_path.nested:
        latency = {}
        for i, local in out_path.nested.items():
            cost, _ = contract_path_cost(out_tn[i].tensors, local, True)
            latency[i] = cost
        externals = [child.external_tensor() for child in out_tn.tensors]
        costs = [latency.get(i, 0.0) for i in range(len(externals))]
        (flops, flops_sum), _ = communication_path_op_costs(
            externals, out_path.toplevel, True, costs
        )
    else:
        flops, _ = contract_path_cost(out_tn.tensors, out_path, True)
        flops_sum = flops
    memory = compute_memory_requirements(
        out_tn.tensors, out_path, contract_size_tensors_bytes
    )

    cache.store(scenario.key(), out_tn, out_path)
    record = OptimizationResult(
        id=run_id,
        method=scenario.method,
        circuit=scenario.circuit_name,
        partitions=scenario.partitions,
        seed=scenario.seed,
        serial_flops=serial_flops,
        serial_memory=serial_memory,
        flops=flops,
        flops_sum=flops_sum,
        memory=memory,
        optimization_time=optimization_time,
    )
    writer.write(record)
    protocol.done(run_id)
    log.info(
        "sweep %s: flops %.3g (serial %.3g), %.1fs",
        run_id, flops, serial_flops, optimization_time,
    )
    return record


def do_run(
    scenario: Scenario,
    cache: ArtifactCache,
    writer: ResultWriter,
    protocol: Protocol,
    backend: str = "jax",
    distributed: bool = False,
    repeats: int = 1,
    checkpoint_dir=None,
) -> RunResult | None:
    """Contract a cached artifact, timing only the contraction (the
    reference barriers before timing, ``main.rs:365-405``).

    With ``checkpoint_dir``, the cell runs under a per-cell
    ``TNC_TPU_CKPT`` (``tnc_tpu.resilience.checkpoint``): a crash
    mid-slice-range leaves a checkpoint, the protocol requeues the cell
    on restart, and the rerun resumes from the persisted cursor."""
    import contextlib
    import os

    run_id = f"run-{backend}/" + scenario.run_id
    if not protocol.should_run(run_id):
        log.info("skipping %s (already done or failed)", run_id)
        return None
    # a requeued cell resumes mid-range: its wall time is NOT a full
    # contraction time and the record must say so
    resumed = run_id in protocol.resumable
    loaded = cache.load(scenario.key())
    if loaded is None:
        raise FileNotFoundError(
            f"no cached artifact for {scenario.key()}; run the sweep first"
        )
    protocol.trying(run_id)
    tn, path = loaded

    @contextlib.contextmanager
    def _cell_ckpt_env():
        if checkpoint_dir is None:
            yield
            return
        from tnc_tpu.benchmark.protocol import cell_checkpoint_dir

        cell = cell_checkpoint_dir(checkpoint_dir, run_id)
        prev = os.environ.get("TNC_TPU_CKPT")
        os.environ["TNC_TPU_CKPT"] = str(cell)
        try:
            yield
        finally:
            if prev is None:
                os.environ.pop("TNC_TPU_CKPT", None)
            else:
                os.environ["TNC_TPU_CKPT"] = prev

    times = []
    with _cell_ckpt_env():
        for _ in range(max(1, repeats)):
            t0 = time.monotonic()
            if distributed and path.nested:
                from tnc_tpu.parallel import (
                    distributed_partitioned_contraction,
                )

                distributed_partitioned_contraction(tn, path)
            else:
                contract_tensor_network(tn, path, backend=backend)
            times.append(time.monotonic() - t0)

    record = RunResult(
        id=run_id,
        method=scenario.method,
        circuit=scenario.circuit_name,
        partitions=scenario.partitions,
        seed=scenario.seed,
        time_to_solution=min(times),
        backend=backend,
        resumed=resumed,
    )
    writer.write(record)
    protocol.done(run_id)
    log.info("run %s: %.4fs", run_id, record.time_to_solution)
    return record
