"""Research benchmark driver — the reference ``benchmark`` crate rebuilt.

Two phases decoupled across job submissions, exactly as in the reference
(``benchmark/src/main.rs:195-219,267-353``):

- **Sweep** — run a path/partitioning optimizer on a circuit, record an
  :class:`~tnc_tpu.benchmark.results.OptimizationResult` (predicted
  serial/parallel flops, memory, optimization time) and cache the
  optimized partitioned network + path as a compressed artifact.
- **Run** — load the cached artifact and contract it (single device or
  distributed over the mesh), recording ``time_to_solution``.

Crash-resume comes from the :class:`~tnc_tpu.benchmark.protocol.Protocol`
journal (``Trying``/``Done`` records; stale ``Trying`` entries become
``Error`` on restart and are skipped — ``benchmark/src/protocol.rs:22-66``).
"""

from tnc_tpu.benchmark.cache import ArtifactCache  # noqa: F401
from tnc_tpu.benchmark.methods import METHODS, MethodRun  # noqa: F401
from tnc_tpu.benchmark.protocol import Protocol  # noqa: F401
from tnc_tpu.benchmark.results import (  # noqa: F401
    OptimizationResult,
    ResultWriter,
    RunResult,
)
