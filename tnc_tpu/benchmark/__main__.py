import sys

from tnc_tpu.benchmark.cli import main

sys.exit(main())
