"""Optimization methods for the sweep phase.

Mirror of the reference's ``MethodRun`` trait and its method set
(``benchmark/src/main.rs:131-149,407-859``): Generic (plain
partition+greedy), the SA repartitioning models, greedy tree balancing,
and the tree-refinement finders. Every method maps a flat network to a
(partitioned network, nested path) pair under a time budget.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Callable

from tnc_tpu.contractionpath.balancing import (
    BalanceSettings,
    balance_partitions_iter,
)
from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.paths import TreeAnnealing, TreeTempering
from tnc_tpu.contractionpath.paths.hyper import Hyperoptimizer
from tnc_tpu.contractionpath.repartitioning import compute_solution
from tnc_tpu.contractionpath.repartitioning.genetic import (
    balance_partitions as genetic_balance,
)
from tnc_tpu.contractionpath.repartitioning.simulated_annealing import (
    IntermediatePartitioningModel,
    LeafPartitioningModel,
    NaiveIntermediatePartitioningModel,
    NaivePartitioningModel,
    balance_partitions,
)
from tnc_tpu.tensornetwork.partitioning import find_partitioning
from tnc_tpu.tensornetwork.tensor import CompositeTensor


@dataclass
class MethodRun:
    """A named sweep method (cf. the reference's ``MethodRun`` trait)."""

    name: str
    run: Callable[
        ["MethodContext"], tuple[CompositeTensor, ContractionPath]
    ]


@dataclass
class MethodContext:
    tn: CompositeTensor  # flat network
    partitions: int
    seed: int
    time_budget: float  # seconds (reference default: 10 min)
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY

    @property
    def rng(self) -> random.Random:
        return random.Random(self.seed)

    def initial_partitioning(self) -> list[int]:
        return find_partitioning(self.tn, self.partitions, seed=self.seed)


def _solution_for(
    ctx: MethodContext, partitioning: list[int]
) -> tuple[CompositeTensor, ContractionPath]:
    partitioned, path, _, _ = compute_solution(
        ctx.tn, partitioning, ctx.communication_scheme, ctx.rng
    )
    return partitioned, path


def _generic(ctx: MethodContext):
    """Partition + greedy local paths, no refinement (``Generic``)."""
    return _solution_for(ctx, ctx.initial_partitioning())


def _sa(model_cls):
    def run(ctx: MethodContext):
        needs_k = model_cls in (
            NaivePartitioningModel,
            NaiveIntermediatePartitioningModel,
        )
        if needs_k:
            model = model_cls(
                ctx.tn, ctx.partitions, ctx.communication_scheme
            )
        else:
            model = model_cls(ctx.tn, ctx.communication_scheme)
        initial = model.initial_solution(ctx.initial_partitioning())
        best, _ = balance_partitions(
            model, initial, ctx.rng, max_time=ctx.time_budget
        )
        partitioning = best if isinstance(best, list) else list(best[0])
        return _solution_for(ctx, partitioning)

    return run


def _genetic(ctx: MethodContext):
    best, _ = genetic_balance(
        ctx.tn,
        ctx.initial_partitioning(),
        ctx.partitions,
        ctx.rng,
        ctx.communication_scheme,
        max_time=ctx.time_budget,
    )
    return _solution_for(ctx, list(best))


def _greedy_balance(ctx: MethodContext):
    settings = BalanceSettings(communication_scheme=ctx.communication_scheme)
    _, tn, path, _ = balance_partitions_iter(
        ctx.tn, ctx.initial_partitioning(), settings, ctx.rng
    )
    return tn, path


def _flat_finder(make_finder):
    """Methods that skip partitioning: one flat refined path (the
    reference's Cotengra* methods are flat too)."""

    def run(ctx: MethodContext):
        finder = make_finder(ctx)
        result = finder.find_path(ctx.tn)
        return ctx.tn, result.replace_path()

    return run


def methods_example():
    """The sweep-method registry mirrors the reference's method set.

    >>> sorted(METHODS)[:4]
    ['genetic', 'greedy', 'greedy-balance', 'hyper']
    >>> import numpy as np
    >>> from tnc_tpu.builders.connectivity import ConnectivityLayout
    >>> from tnc_tpu.builders.random_circuit import random_circuit
    >>> tn = random_circuit(6, 4, 0.5, 0.5, np.random.default_rng(0),
    ...                     ConnectivityLayout.LINE)
    >>> ctx = MethodContext(tn, partitions=2, seed=1, time_budget=2.0)
    >>> ptn, path = METHODS["greedy"].run(ctx)
    >>> len(ptn) >= 1 and path.toplevel is not None
    True
    """


METHODS: dict[str, MethodRun] = {
    m.name: m
    for m in [
        MethodRun("greedy", _generic),
        MethodRun("sa-naive", _sa(NaivePartitioningModel)),
        MethodRun("sa-naive-intermediate", _sa(NaiveIntermediatePartitioningModel)),
        MethodRun("sa-leaf", _sa(LeafPartitioningModel)),
        MethodRun("sa-intermediate", _sa(IntermediatePartitioningModel)),
        MethodRun("genetic", _genetic),
        MethodRun("greedy-balance", _greedy_balance),
        MethodRun(
            "tree-anneal",
            _flat_finder(lambda ctx: TreeAnnealing(seed=ctx.seed)),
        ),
        MethodRun(
            "tree-temper",
            _flat_finder(lambda ctx: TreeTempering(seed=ctx.seed)),
        ),
        MethodRun(
            "hyper",
            _flat_finder(lambda ctx: Hyperoptimizer(seed=ctx.seed)),
        ),
    ]
}
