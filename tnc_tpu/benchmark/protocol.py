"""Crash-resumable experiment journal (``benchmark/src/protocol.rs:22-66``).

Before each unit of work the driver appends ``Trying(id)``; after success
it appends ``Done(id)``. On restart, ``Trying`` entries without a matching
``Done`` mean the process died mid-run: they are recorded as ``Error`` and
skipped, so a crashing configuration cannot wedge a sweep loop.

Within-cell resume: with a ``checkpoint_dir``, a crashed cell whose
slice-range checkpoint survives (``tnc_tpu.resilience.checkpoint``;
the executors write it under ``TNC_TPU_CKPT``) is **requeued** instead
of marked failed — re-running it resumes mid-range from the persisted
accumulator rather than redoing (or abandoning) hours of slices. The
reference can only restart whole cells; this is the finer-grained layer
under it. Requeues are bounded (``max_resumes``, default 3): a cell
that keeps crashing *after* its first checkpoint would otherwise be
requeued on every restart forever, re-wedging exactly the sweep loop
this journal exists to protect.
"""

from __future__ import annotations

import json
from pathlib import Path


def cell_checkpoint_dir(checkpoint_dir: str | Path, run_id: str) -> Path:
    """Per-cell checkpoint directory (the value to export as
    ``TNC_TPU_CKPT`` while running that cell). Slashes in run ids become
    ``_`` so every cell stays one directory level."""
    return Path(checkpoint_dir) / run_id.replace("/", "_")


class Protocol:
    """Append-only Trying/Done/Error journal.

    >>> import tempfile, os
    >>> p = os.path.join(tempfile.mkdtemp(), "journal.jsonl")
    >>> proto = Protocol(p)
    >>> proto.should_run("cell-1")
    True
    >>> proto.trying("cell-1"); proto.done("cell-1")
    >>> proto.should_run("cell-1")
    False
    >>> proto.trying("cell-2")  # crash here: no Done follows
    >>> resumed = Protocol(p)   # restart marks cell-2 as Error
    >>> resumed.should_run("cell-2"), sorted(resumed.failed)
    (False, ['cell-2'])

    With a ``checkpoint_dir``, a crashed cell that left a checkpoint is
    requeued for a mid-range resume instead of failed:

    >>> d = tempfile.mkdtemp()
    >>> p2 = os.path.join(d, "journal.jsonl")
    >>> proto = Protocol(p2, checkpoint_dir=os.path.join(d, "ckpt"))
    >>> proto.trying("cell-3")  # crash mid-range...
    >>> ck = cell_checkpoint_dir(os.path.join(d, "ckpt"), "cell-3")
    >>> ck.mkdir(parents=True); _ = (ck / "ckpt_abc.npz").write_bytes(b"x")
    >>> back = Protocol(p2, checkpoint_dir=os.path.join(d, "ckpt"))
    >>> back.should_run("cell-3"), sorted(back.resumable)
    (True, ['cell-3'])
    """

    def __init__(
        self,
        path: str | Path,
        checkpoint_dir: str | Path | None = None,
        max_resumes: int = 3,
    ):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.checkpoint_dir = (
            Path(checkpoint_dir) if checkpoint_dir is not None else None
        )
        self.max_resumes = max_resumes
        self._done: set[str] = set()
        self._error: set[str] = set()
        self._resumable: set[str] = set()
        self._load()

    def _has_checkpoint(self, run_id: str) -> bool:
        if self.checkpoint_dir is None:
            return False
        cell = cell_checkpoint_dir(self.checkpoint_dir, run_id)
        if cell.is_file():
            return True
        return cell.is_dir() and any(cell.glob("ckpt_*.npz"))

    def _load(self) -> None:
        trying: set[str] = set()
        resumes: dict[str, int] = {}
        if self.path.exists():
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    state, run_id = rec["state"], rec["id"]
                    if state == "trying":
                        trying.add(run_id)
                    elif state == "done":
                        trying.discard(run_id)
                        self._done.add(run_id)
                    elif state == "error":
                        # discard from trying too: an errored cell must
                        # not be re-processed (and re-journaled) as a
                        # stale Trying entry on every later load
                        trying.discard(run_id)
                        self._error.add(run_id)
                    elif state == "resuming":
                        resumes[run_id] = resumes.get(run_id, 0) + 1
        # stale Trying entries: resumable when a slice-range checkpoint
        # survives (the rerun picks up mid-range) and the resume budget
        # isn't spent; Error otherwise — a cell that crashed past its
        # first checkpoint on max_resumes straight resume attempts is
        # crashing deterministically, and must not wedge the sweep loop.
        # The budget counts "resuming" records, appended by :meth:`trying`
        # only when the cell actually re-runs — merely loading the
        # journal (e.g. a sweep filtered to other scenarios) spends
        # nothing.
        for run_id in sorted(trying):
            if (
                self._has_checkpoint(run_id)
                and resumes.get(run_id, 0) < self.max_resumes
            ):
                self._resumable.add(run_id)
                continue
            self._error.add(run_id)
            self._append("error", run_id)

    def _append(self, state: str, run_id: str) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps({"state": state, "id": run_id}) + "\n")

    def should_run(self, run_id: str) -> bool:
        """False for runs already done or known to crash (cells with a
        surviving checkpoint stay runnable — they resume mid-range)."""
        return run_id not in self._done and run_id not in self._error

    def trying(self, run_id: str) -> None:
        if run_id in self._resumable:
            # an actual resume attempt starts now — spend one unit of
            # the max_resumes budget in the journal
            self._append("resuming", run_id)
        self._append("trying", run_id)

    def done(self, run_id: str) -> None:
        self._done.add(run_id)
        self._resumable.discard(run_id)
        self._append("done", run_id)

    @property
    def completed(self) -> set[str]:
        return set(self._done)

    @property
    def failed(self) -> set[str]:
        return set(self._error)

    @property
    def resumable(self) -> set[str]:
        """Cells that crashed but left a checkpoint to resume from."""
        return set(self._resumable)
