"""Crash-resumable experiment journal (``benchmark/src/protocol.rs:22-66``).

Before each unit of work the driver appends ``Trying(id)``; after success
it appends ``Done(id)``. On restart, ``Trying`` entries without a matching
``Done`` mean the process died mid-run: they are recorded as ``Error`` and
skipped, so a crashing configuration cannot wedge a sweep loop.
"""

from __future__ import annotations

import json
from pathlib import Path


class Protocol:
    """Append-only Trying/Done/Error journal.

    >>> import tempfile, os
    >>> p = os.path.join(tempfile.mkdtemp(), "journal.jsonl")
    >>> proto = Protocol(p)
    >>> proto.should_run("cell-1")
    True
    >>> proto.trying("cell-1"); proto.done("cell-1")
    >>> proto.should_run("cell-1")
    False
    >>> proto.trying("cell-2")  # crash here: no Done follows
    >>> resumed = Protocol(p)   # restart marks cell-2 as Error
    >>> resumed.should_run("cell-2"), sorted(resumed.failed)
    (False, ['cell-2'])
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._done: set[str] = set()
        self._error: set[str] = set()
        self._load()

    def _load(self) -> None:
        trying: set[str] = set()
        if self.path.exists():
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    state, run_id = rec["state"], rec["id"]
                    if state == "trying":
                        trying.add(run_id)
                    elif state == "done":
                        trying.discard(run_id)
                        self._done.add(run_id)
                    elif state == "error":
                        self._error.add(run_id)
        # stale Trying entries -> Error (the run crashed last time)
        for run_id in sorted(trying):
            self._error.add(run_id)
            self._append("error", run_id)

    def _append(self, state: str, run_id: str) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps({"state": state, "id": run_id}) + "\n")

    def should_run(self, run_id: str) -> bool:
        """False for runs already done or known to crash."""
        return run_id not in self._done and run_id not in self._error

    def trying(self, run_id: str) -> None:
        self._append("trying", run_id)

    def done(self, run_id: str) -> None:
        self._done.add(run_id)
        self._append("done", run_id)

    @property
    def completed(self) -> set[str]:
        return set(self._done)

    @property
    def failed(self) -> set[str]:
        return set(self._error)
