"""Result records, mirroring ``benchmark/src/results.rs:5-26``.

Results are append-only JSON lines so concurrent/restarted sweeps never
clobber earlier records (the reference appends serde-JSON records the
same way).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from pathlib import Path


@dataclass
class OptimizationResult:
    """Per-sweep record (``results.rs:5-16``)."""

    id: str
    method: str
    circuit: str
    partitions: int
    seed: int
    serial_flops: float
    serial_memory: float
    flops: float  # critical-path (parallel) cost
    flops_sum: float  # sum cost over all partitions
    memory: float  # bytes
    optimization_time: float  # seconds


@dataclass
class RunResult:
    """Per-run record (``results.rs:19-26``)."""

    id: str
    method: str
    circuit: str
    partitions: int
    seed: int
    time_to_solution: float  # seconds, contraction only
    backend: str = "jax"
    # the run resumed a crashed cell from a slice-range checkpoint: its
    # wall time covers only the REMAINING range, not a full contraction —
    # comparisons must not read it as a clean-run time
    resumed: bool = False


class ResultWriter:
    """Append-only JSON-lines writer.

    >>> import tempfile, os
    >>> w = ResultWriter(os.path.join(tempfile.mkdtemp(), "results.jsonl"))
    >>> w.write(RunResult("id1", "sa", "ghz", 4, 7, 1.5))
    >>> [r["kind"] for r in w.read_all()]
    ['RunResult']
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def write(self, record: OptimizationResult | RunResult) -> None:
        payload = dataclasses.asdict(record)
        payload["kind"] = type(record).__name__
        with open(self.path, "a") as f:
            f.write(json.dumps(payload) + "\n")

    def read_all(self) -> list[dict]:
        if not self.path.exists():
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
