"""Structured JSON logging, one file per process.

Mirror of the reference's flexi_logger setup — JSON records, a log file
discriminated per MPI rank, Info+ duplicated to stderr
(``benchmark/src/utils.rs:12-24``). Here the discriminant is the jax
process index (multi-host) or the PID.

Contract (matching ``utils/logging_config.py``): :func:`setup_logging`
is **idempotent and additive** — calling it twice attaches nothing
twice, and handlers the application installed on the ``tnc_tpu`` logger
are left alone (records keep flowing to them). :class:`JsonFormatter`
serializes ``extra=`` structured fields, so metric records emitted by
:func:`tnc_tpu.obs.emit_metrics` land in the JSONL sink with their
payload intact.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from pathlib import Path

# Attributes every LogRecord carries (plus formatter-injected ones);
# anything else on a record came in via ``extra=`` and belongs in the
# JSON payload.
_RESERVED = set(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for key, value in record.__dict__.items():
            if key in _RESERVED or key.startswith("_") or key in payload:
                continue
            payload[key] = value
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


def setup_logging(log_dir: str | Path | None = None, level=logging.INFO) -> None:
    """Configure the ``tnc_tpu`` logger tree: JSON file per process plus
    human-readable stderr. Idempotent (re-runs replace only the handlers
    this function installed) and additive (application handlers stay)."""
    root = logging.getLogger("tnc_tpu")
    root.setLevel(level)
    # replace only LIBRARY-installed handlers: this function's own
    # (_tnc_tpu_bench) and the TNC_TPU_LOG import-time stderr handler
    # (_tnc_tpu_env, utils/logging_config.py) — the latter would
    # duplicate every record on stderr next to the one installed below.
    # Application handlers are left alone.
    for handler in [
        h for h in root.handlers
        if getattr(h, "_tnc_tpu_bench", False)
        or getattr(h, "_tnc_tpu_env", False)
    ]:
        root.removeHandler(handler)
        handler.close()

    stream = logging.StreamHandler(sys.stderr)
    stream.setFormatter(
        logging.Formatter("[%(asctime)s %(levelname)s %(name)s] %(message)s")
    )
    stream._tnc_tpu_bench = True  # type: ignore[attr-defined]
    root.addHandler(stream)

    if log_dir is not None:
        try:
            import jax

            discriminant = f"proc{jax.process_index()}"
        except Exception:
            discriminant = f"pid{os.getpid()}"
        path = Path(log_dir)
        path.mkdir(parents=True, exist_ok=True)
        fh = logging.FileHandler(path / f"benchmark_{discriminant}.jsonl")
        fh.setFormatter(JsonFormatter())
        fh._tnc_tpu_bench = True  # type: ignore[attr-defined]
        root.addHandler(fh)
