"""Structured JSON logging, one file per process.

Mirror of the reference's flexi_logger setup — JSON records, a log file
discriminated per MPI rank, Info+ duplicated to stderr
(``benchmark/src/utils.rs:12-24``). Here the discriminant is the jax
process index (multi-host) or the PID.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from pathlib import Path


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def setup_logging(log_dir: str | Path | None = None, level=logging.INFO) -> None:
    """Configure the ``tnc_tpu`` logger tree: JSON file per process plus
    human-readable stderr."""
    root = logging.getLogger("tnc_tpu")
    root.setLevel(level)
    root.handlers.clear()

    stream = logging.StreamHandler(sys.stderr)
    stream.setFormatter(
        logging.Formatter("[%(asctime)s %(levelname)s %(name)s] %(message)s")
    )
    root.addHandler(stream)

    if log_dir is not None:
        try:
            import jax

            discriminant = f"proc{jax.process_index()}"
        except Exception:
            discriminant = f"pid{os.getpid()}"
        path = Path(log_dir)
        path.mkdir(parents=True, exist_ok=True)
        fh = logging.FileHandler(path / f"benchmark_{discriminant}.jsonl")
        fh.setFormatter(JsonFormatter())
        root.addHandler(fh)
