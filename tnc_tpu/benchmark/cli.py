"""Benchmark CLI (``benchmark/src/cli.rs:9-24``).

    python -m tnc_tpu.benchmark sweep --circuits-dir circuits/ \
        --partitions 4 8 --seeds 0 1 2 --methods greedy sa-intermediate \
        --cache-dir cache/ --out results.jsonl
    python -m tnc_tpu.benchmark run --circuits-dir circuits/ ...

Circuits are ``.qasm`` files in ``--circuits-dir``; every
(circuit x partitions x seed x method) cell is one scenario.
``--include``/``--exclude`` filter by scenario index ranges, as in the
reference.
"""

from __future__ import annotations

import argparse
import logging
import sys
from pathlib import Path

from tnc_tpu.benchmark.cache import ArtifactCache
from tnc_tpu.benchmark.driver import Scenario, do_run, do_sweep
from tnc_tpu.benchmark.logging_util import setup_logging
from tnc_tpu.benchmark.methods import METHODS
from tnc_tpu.benchmark.protocol import Protocol
from tnc_tpu.benchmark.results import ResultWriter

log = logging.getLogger("tnc_tpu.benchmark")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tnc_tpu.benchmark")
    p.add_argument("mode", choices=["sweep", "run"])
    p.add_argument("--circuits-dir", required=True, type=Path)
    p.add_argument("--cache-dir", default=Path("bench_cache"), type=Path)
    p.add_argument("--out", default=Path("results.jsonl"), type=Path)
    p.add_argument("--protocol", default=Path("protocol.jsonl"), type=Path)
    p.add_argument(
        "--checkpoint-dir", default=None, type=Path,
        help="slice-range checkpoint root (tnc_tpu.resilience): run cells "
        "with per-cell TNC_TPU_CKPT, and requeue crashed cells whose "
        "checkpoint survives (mid-range resume) instead of failing them",
    )
    p.add_argument("--log-dir", default=None, type=Path)
    p.add_argument("--partitions", nargs="+", type=int, default=[4])
    p.add_argument("--seeds", nargs="+", type=int, default=[0])
    p.add_argument(
        "--methods", nargs="+", default=["greedy"],
        choices=sorted(METHODS),
    )
    p.add_argument("--time-budget", type=float, default=600.0)
    p.add_argument("--backend", default="jax")
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--repeats", type=int, default=1)
    p.add_argument("--include", nargs=2, type=int, metavar=("LO", "HI"),
                   help="only scenario indices in [LO, HI)")
    p.add_argument("--exclude", nargs=2, type=int, metavar=("LO", "HI"))
    return p


def enumerate_scenarios(args) -> list[Scenario]:
    circuits = sorted(args.circuits_dir.glob("*.qasm"))
    if not circuits:
        raise SystemExit(f"no .qasm circuits in {args.circuits_dir}")
    scenarios = []
    for circuit in circuits:
        text = circuit.read_text()
        for partitions in args.partitions:
            for seed in args.seeds:
                for method in args.methods:
                    scenarios.append(
                        Scenario(
                            circuit_name=circuit.stem,
                            circuit_text=text,
                            partitions=partitions,
                            seed=seed,
                            method=method,
                        )
                    )
    indexed = list(enumerate(scenarios))
    if args.include:
        lo, hi = args.include
        indexed = [(i, s) for i, s in indexed if lo <= i < hi]
    if args.exclude:
        lo, hi = args.exclude
        indexed = [(i, s) for i, s in indexed if not (lo <= i < hi)]
    return [s for _, s in indexed]


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(args.log_dir)

    from tnc_tpu.io.qasm import import_qasm

    cache = ArtifactCache(args.cache_dir)
    writer = ResultWriter(args.out)
    protocol = Protocol(args.protocol, checkpoint_dir=args.checkpoint_dir)

    scenarios = enumerate_scenarios(args)
    log.info("%d scenarios in %s mode", len(scenarios), args.mode)

    circuits_cache: dict[str, object] = {}
    for scenario in scenarios:
        try:
            if args.mode == "sweep":
                if scenario.circuit_name not in circuits_cache:
                    circuit = import_qasm(scenario.circuit_text)
                    tn, _ = circuit.into_statevector_network()
                    circuits_cache[scenario.circuit_name] = tn
                do_sweep(
                    scenario,
                    circuits_cache[scenario.circuit_name],
                    cache, writer, protocol,
                    time_budget=args.time_budget,
                )
            else:
                do_run(
                    scenario, cache, writer, protocol,
                    backend=args.backend,
                    distributed=args.distributed,
                    repeats=args.repeats,
                    checkpoint_dir=args.checkpoint_dir,
                )
        except Exception:
            log.exception("scenario %s failed", scenario.run_id)
    return 0


if __name__ == "__main__":
    sys.exit(main())
