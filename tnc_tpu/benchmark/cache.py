"""Artifact cache: optimized partitioned network + path, compressed.

Mirror of the reference's bincode+zlib cache
(``benchmark/src/main.rs:184-187,223-242``): the expensive Sweep phase
writes its result keyed by ``{scheme}_{circuit_hash}_{seed}_{partitions}_
{method}``, and the Run phase — possibly a separate job submission on
different hardware — loads it back without re-optimizing.

Tensor *data* is not stored: leaf tensors carry symbolic
:class:`TensorData` (gates / file refs), so artifacts stay small and the
Run phase materializes data on its own device, just as the reference
scatters metadata and lets ranks materialize.
"""

from __future__ import annotations

import pickle
import zlib
from pathlib import Path

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.tensornetwork.tensor import CompositeTensor
from tnc_tpu.utils.digest import stable_digest


def cache_key(
    scheme: str, circuit_text: str, seed: int, partitions: int, method: str
) -> str:
    """Deterministic artifact key (circuit text hashed, params inline).

    >>> key = cache_key("greedy", "OPENQASM 2.0;", 7, 4, "sa")
    >>> key == cache_key("greedy", "OPENQASM 2.0;", 7, 4, "sa")
    True
    >>> key.startswith("greedy_") and key.endswith("_7_4_sa")
    True
    """
    digest = stable_digest(circuit_text)[:16]
    return f"{scheme}_{digest}_{seed}_{partitions}_{method}"


class ArtifactCache:
    """Keyed compressed artifact store with atomic writes.

    >>> import tempfile
    >>> cache = ArtifactCache(tempfile.mkdtemp())
    >>> cache.store_obj("k", {"plan": [1, 2]})
    >>> cache.load_obj("k")
    {'plan': [1, 2]}
    >>> cache.load_obj("missing") is None
    True
    """

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        return self.directory / key

    def store(
        self, key: str, tn: CompositeTensor, path: ContractionPath
    ) -> None:
        blob = zlib.compress(pickle.dumps((tn, path)), level=6)
        target = self._path(key)
        tmp = target.with_suffix(".tmp")
        tmp.write_bytes(blob)
        tmp.replace(target)  # atomic: concurrent runs see all or nothing

    def load(self, key: str) -> tuple[CompositeTensor, ContractionPath] | None:
        target = self._path(key)
        if not target.exists():
            return None
        tn, path = pickle.loads(zlib.decompress(target.read_bytes()))
        return tn, path

    def has(self, key: str) -> bool:
        return self._path(key).exists()

    def store_obj(self, key: str, obj: object) -> None:
        """Generic artifact (e.g. a sliced plan): same zlib+pickle wire
        format and atomic-replace discipline as :meth:`store`."""
        blob = zlib.compress(pickle.dumps(obj), level=6)
        target = self._path(key)
        tmp = target.with_suffix(".tmp")
        tmp.write_bytes(blob)
        tmp.replace(target)

    def load_obj(self, key: str) -> object | None:
        target = self._path(key)
        if not target.exists():
            return None
        try:
            return pickle.loads(zlib.decompress(target.read_bytes()))
        except Exception:
            return None  # corrupt/partial artifact: replan rather than die
