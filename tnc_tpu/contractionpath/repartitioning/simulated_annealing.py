"""Simulated-annealing repartitioning.

Mirror of ``tnc/src/contractionpath/repartitioning/simulated_annealing.rs``:
an SA engine with a wall-clock budget, log-interpolated temperature
(2.0 → 0.05), restart-after-stale, multi-chain trial generation, and
acceptance probability ``exp(-log2(score/current) / T)``
(``simulated_annealing.rs:122-127``), plus four move models:

- :class:`NaivePartitioningModel` — random tensor → random partition.
- :class:`NaiveIntermediatePartitioningModel` — random *subtree* of a
  partition's local path → random partition.
- :class:`LeafPartitioningModel` — random tensor → the partition whose
  external tensor shrinks the most.
- :class:`IntermediatePartitioningModel` — random subtree → best
  partition (the reference book calls this the best method).

Scores are the critical-path (parallel) cost from
:func:`~tnc_tpu.contractionpath.repartitioning.compute_solution`;
exceeding a memory limit scores infinity
(``simulated_annealing.rs:171-199``).

Divergence: the reference evaluates 48 rayon chains in parallel
(``PROCESSING_THREADS = 48``); chains here run sequentially (Python), so
``n_trials`` defaults lower. Seeded determinism is preserved.
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass
from typing import Sequence

from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_cost import (
    compute_memory_requirements,
    contract_size_tensors_bytes,
)
from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
from tnc_tpu.contractionpath.repartitioning import compute_solution
from tnc_tpu.tensornetwork.partitioning import partition_tensor_network
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


def evaluate_partitioning(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    communication_scheme: CommunicationScheme,
    memory_limit: float | None,
    rng: random.Random,
) -> float:
    partitioned, path, parallel_cost, _ = compute_solution(
        tensor, partitioning, communication_scheme, rng
    )
    if memory_limit is not None:
        mem = compute_memory_requirements(
            partitioned.tensors, path, contract_size_tensors_bytes
        )
        if mem > memory_limit:
            return math.inf
    return parallel_cost


class OptModel:
    """Trial-generation + scoring interface (``simulated_annealing.rs:38-51``)."""

    def generate_trial_solution(self, current, rng: random.Random):
        raise NotImplementedError

    def evaluate(self, solution, rng: random.Random) -> float:
        raise NotImplementedError

    def _require_multiple_partitions(self) -> None:
        # A 1-partition model has no moves: the trial loops that pick a
        # different target partition would spin forever.
        if self.num_partitions < 2:
            raise ValueError(
                f"{type(self).__name__} needs num_partitions >= 2, "
                f"got {self.num_partitions}"
            )


@dataclass
class NaivePartitioningModel(OptModel):
    tensor: CompositeTensor
    num_partitions: int
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def __post_init__(self) -> None:
        self._require_multiple_partitions()

    def initial_solution(self, partitioning: Sequence[int]) -> list[int]:
        return list(partitioning)

    def generate_trial_solution(self, current: list[int], rng: random.Random):
        solution = list(current)
        index = rng.randrange(len(solution))
        current_partition = solution[index]
        while True:
            b = rng.randrange(self.num_partitions)
            if b != current_partition:
                break
        solution[index] = b
        return solution

    def evaluate(self, solution: list[int], rng: random.Random) -> float:
        return evaluate_partitioning(
            self.tensor, solution, self.communication_scheme, self.memory_limit, rng
        )


def _local_greedy_path(tensors: list) -> list[tuple[int, int]]:
    tn = CompositeTensor(tensors)
    if len(tn) <= 1:
        return []
    return Greedy(OptMethod.GREEDY).find_path(tn).replace_path().toplevel


def _subtree_leaves(
    local_path: list[tuple[int, int]], pair_index: int
) -> set[int]:
    """Leaves contributing to the contraction at ``pair_index``
    (``simulated_annealing.rs:279-292``): walk earlier pairs backwards,
    collecting partners of already-included results."""
    i, j = local_path[pair_index]
    leaves = {i, j}
    for a, b in reversed(local_path[:pair_index]):
        if a in leaves:
            leaves.add(b)
    return leaves


def _pick_subtree_and_indices(
    partitioning: list[int],
    local_paths: list[list[tuple[int, int]]],
    rng: random.Random,
) -> tuple[int, list[int]] | None:
    """Pick a source partition with >=3 local pairs and a random subtree;
    return (source partition, global tensor indices to move)."""
    viable = [p for p, path in enumerate(local_paths) if len(path) >= 3]
    if not viable:
        return None
    source = rng.choice(viable)
    pair_index = rng.randrange(len(local_paths[source]) - 1)
    leaves = _subtree_leaves(local_paths[source], pair_index)

    shifted_global: list[int] = []
    local_index = 0
    for global_index, partition in enumerate(partitioning):
        if partition != source:
            continue
        if local_index in leaves:
            shifted_global.append(global_index)
        local_index += 1
    return source, shifted_global


def _recompute_two_paths(
    tensor: CompositeTensor,
    partitioning: list[int],
    local_paths: list[list[tuple[int, int]]],
    source: int,
    target: int,
) -> None:
    from_tensors = []
    to_tensors = []
    for partition, t in zip(partitioning, tensor.tensors):
        if partition == source:
            from_tensors.append(t)
        elif partition == target:
            to_tensors.append(t)
    local_paths[source] = _local_greedy_path(from_tensors)
    local_paths[target] = _local_greedy_path(to_tensors)


@dataclass
class NaiveIntermediatePartitioningModel(OptModel):
    """Moves a random subtree to a random partition."""

    tensor: CompositeTensor
    num_partitions: int
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def __post_init__(self) -> None:
        self._require_multiple_partitions()

    def initial_solution(
        self, partitioning: Sequence[int]
    ) -> tuple[list[int], list[list[tuple[int, int]]]]:
        partitioned = partition_tensor_network(
            CompositeTensor(list(self.tensor.tensors)), partitioning
        )
        paths = [_local_greedy_path(list(child.tensors)) for child in partitioned]
        return list(partitioning), paths

    def generate_trial_solution(self, current, rng: random.Random):
        partitioning, local_paths = current
        partitioning = list(partitioning)
        local_paths = [list(p) for p in local_paths]

        picked = _pick_subtree_and_indices(partitioning, local_paths, rng)
        if picked is None:
            return partitioning, local_paths
        source, shifted = picked
        while True:
            target = rng.randrange(self.num_partitions)
            if target != source:
                break
        for index in shifted:
            partitioning[index] = target
        _recompute_two_paths(self.tensor, partitioning, local_paths, source, target)
        return partitioning, local_paths

    def evaluate(self, solution, rng: random.Random) -> float:
        return evaluate_partitioning(
            self.tensor, solution[0], self.communication_scheme, self.memory_limit, rng
        )


@dataclass
class LeafPartitioningModel(OptModel):
    """Moves a random tensor to the partition maximizing size reduction."""

    tensor: CompositeTensor
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def initial_solution(
        self, partitioning: Sequence[int]
    ) -> tuple[list[int], list[LeafTensor]]:
        partitioned = partition_tensor_network(
            CompositeTensor(list(self.tensor.tensors)), partitioning
        )
        externals = [child.external_tensor() for child in partitioned]
        return list(partitioning), externals

    def generate_trial_solution(self, current, rng: random.Random):
        partitioning, partition_tensors = current
        partitioning = list(partitioning)
        partition_tensors = [t.copy() for t in partition_tensors]

        index = rng.randrange(len(partitioning))
        shifted = self.tensor.tensors[index]
        source = partitioning[index]

        best_target = -1
        best_score = math.inf
        for p, external in enumerate(partition_tensors):
            if p == source:
                continue
            score = (shifted ^ external).size() - external.size()
            if score < best_score:
                best_score = score
                best_target = p
        if best_target < 0:
            return partitioning, partition_tensors

        partitioning[index] = best_target
        partition_tensors[source] = partition_tensors[source] ^ shifted
        partition_tensors[best_target] = partition_tensors[best_target] ^ shifted
        return partitioning, partition_tensors

    def evaluate(self, solution, rng: random.Random) -> float:
        return evaluate_partitioning(
            self.tensor, solution[0], self.communication_scheme, self.memory_limit, rng
        )


@dataclass
class IntermediatePartitioningModel(OptModel):
    """Moves a random subtree to the partition maximizing size reduction
    (the reference's best-performing model, ``book/src/partitioning.md``)."""

    tensor: CompositeTensor
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def initial_solution(
        self,
        partitioning: Sequence[int],
        initial_paths: list[list[tuple[int, int]]] | None = None,
    ):
        partitioned = partition_tensor_network(
            CompositeTensor(list(self.tensor.tensors)), partitioning
        )
        externals = [child.external_tensor() for child in partitioned]
        paths = initial_paths or [
            _local_greedy_path(list(child.tensors)) for child in partitioned
        ]
        return list(partitioning), externals, paths

    def generate_trial_solution(self, current, rng: random.Random):
        partitioning, partition_tensors, local_paths = current
        partitioning = list(partitioning)
        partition_tensors = [t.copy() for t in partition_tensors]
        local_paths = [list(p) for p in local_paths]

        picked = _pick_subtree_and_indices(partitioning, local_paths, rng)
        if picked is None:
            return partitioning, partition_tensors, local_paths
        source, shifted_indices = picked

        shifted = LeafTensor()
        for index in shifted_indices:
            shifted = shifted ^ self.tensor.tensors[index]

        best_target = -1
        best_score = math.inf
        for p, external in enumerate(partition_tensors):
            if p == source:
                continue
            score = (shifted ^ external).size() - external.size()
            if score < best_score:
                best_score = score
                best_target = p
        if best_target < 0:
            return partitioning, partition_tensors, local_paths

        for index in shifted_indices:
            partitioning[index] = best_target
        partition_tensors[source] = partition_tensors[source] ^ shifted
        partition_tensors[best_target] = partition_tensors[best_target] ^ shifted
        _recompute_two_paths(
            self.tensor, partitioning, local_paths, source, best_target
        )
        return partitioning, partition_tensors, local_paths

    def evaluate(self, solution, rng: random.Random) -> float:
        return evaluate_partitioning(
            self.tensor, solution[0], self.communication_scheme, self.memory_limit, rng
        )


@dataclass
class SimulatedAnnealingOptimizer:
    """SA engine (``simulated_annealing.rs:54-167``)."""

    n_trials: int = 8
    max_time: float = 10.0
    n_steps: int = 80
    restart_iter: int = 50
    initial_temperature: float = 2.0
    final_temperature: float = 0.05

    def optimize(self, model: OptModel, initial_solution, rng: random.Random):
        current_score = model.evaluate(initial_solution, rng)
        current_solution = initial_solution
        best_solution = current_solution
        best_score = current_score
        last_improvement = 0
        steps_per_chain = -(-self.n_steps // self.n_trials)

        log_start = math.log2(self.initial_temperature)
        log_end = math.log2(self.final_temperature)
        temperature = self.initial_temperature
        chain_rngs = [
            random.Random(rng.getrandbits(64)) for _ in range(self.n_trials)
        ]
        start = time.monotonic()
        end_time = start + self.max_time

        while True:
            best_chain = None
            for chain_rng in chain_rngs:
                trial_score = current_score
                trial_solution = current_solution
                for _ in range(steps_per_chain):
                    solution = model.generate_trial_solution(trial_solution, chain_rng)
                    score = model.evaluate(solution, chain_rng)
                    if score <= 0 or trial_score <= 0:
                        accept = score < trial_score
                    else:
                        diff = math.log2(score / trial_score)
                        accept = math.exp(-diff / temperature) >= chain_rng.random()
                    if accept:
                        trial_solution = solution
                        trial_score = score
                if best_chain is None or trial_score < best_chain[0]:
                    best_chain = (trial_score, trial_solution)
            assert best_chain is not None
            current_score, current_solution = best_chain

            if current_score < best_score:
                best_solution = current_solution
                best_score = current_score
                last_improvement = 0
            last_improvement += 1
            if last_improvement == self.restart_iter:
                current_solution = best_solution
                current_score = best_score

            now = time.monotonic()
            if now > end_time:
                break
            progress = 1.0 - (end_time - now) / self.max_time
            temperature = 2.0 ** (log_start + (log_end - log_start) * progress)

        return best_solution, best_score


def balance_partitions(
    model: OptModel,
    initial_solution,
    rng: random.Random,
    max_time: float = 10.0,
    n_trials: int = 8,
):
    """Run SA with the reference's engine settings
    (``simulated_annealing.rs:576-595``)."""
    optimizer = SimulatedAnnealingOptimizer(
        n_trials=n_trials,
        max_time=max_time,
        n_steps=n_trials * 10,
        restart_iter=50,
        initial_temperature=2.0,
        final_temperature=0.05,
    )
    return optimizer.optimize(model, initial_solution, rng)
