"""Simulated-annealing repartitioning.

Mirror of ``tnc/src/contractionpath/repartitioning/simulated_annealing.rs``:
an SA engine with a wall-clock budget, log-interpolated temperature
(2.0 → 0.05), restart-after-stale, multi-chain trial generation, and
acceptance probability ``exp(-log2(score/current) / T)``
(``simulated_annealing.rs:122-127``), plus four move models:

- :class:`NaivePartitioningModel` — random tensor → random partition.
- :class:`NaiveIntermediatePartitioningModel` — random *subtree* of a
  partition's local path → random partition.
- :class:`LeafPartitioningModel` — random tensor → the partition whose
  external tensor shrinks the most.
- :class:`IntermediatePartitioningModel` — random subtree → best
  partition (the reference book calls this the best method).

Scores are the critical-path (parallel) cost from
:func:`~tnc_tpu.contractionpath.repartitioning.compute_solution`;
exceeding a memory limit scores infinity
(``simulated_annealing.rs:171-199``).

Parallel search: like the reference's fixed 48 rayon chains
(``PROCESSING_THREADS = 48``, ``simulated_annealing.rs:33-35,113-135``),
chains are pure functions of (model, seed, start state, temperature) and
can be evaluated concurrently by a process pool — results are identical
whether chains run inline or pooled, so seeded determinism is preserved
at any worker count. Workers default to the host's CPU count
(``TNC_TPU_SA_WORKERS`` overrides).

Evaluation is incremental: models that carry per-partition local paths
score trials with :func:`compute_solution_with_paths`, skipping the
all-partitions Greedy re-run (the reference re-paths only the two
touched partitions per move, ``simulated_annealing.rs:457-562``).
"""

from __future__ import annotations

import logging
import math
import os
import random
import time
from dataclasses import dataclass
from typing import Sequence

logger = logging.getLogger(__name__)

from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_cost import (
    communication_path_op_costs,
    compute_memory_requirements,
    contract_path_cost,
    contract_size_tensors_bytes,
)
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
from tnc_tpu.contractionpath.repartitioning import (
    compute_solution,
    compute_solution_with_paths,
)
from tnc_tpu.resilience.retry import pool_map_with_retry
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


def evaluate_partitioning(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    communication_scheme: CommunicationScheme,
    memory_limit: float | None,
    rng: random.Random,
) -> float:
    partitioned, path, parallel_cost, _ = compute_solution(
        tensor, partitioning, communication_scheme, rng
    )
    if memory_limit is not None:
        mem = compute_memory_requirements(
            partitioned.tensors, path, contract_size_tensors_bytes
        )
        if mem > memory_limit:
            return math.inf
    return parallel_cost


def evaluate_partitioning_with_paths(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    local_paths: Sequence[Sequence[tuple[int, int]]],
    communication_scheme: CommunicationScheme,
    memory_limit: float | None,
    rng: random.Random,
) -> float:
    """Incremental score: reuse the solution's per-partition paths."""
    partitioned, path, parallel_cost, _ = compute_solution_with_paths(
        tensor, partitioning, local_paths, communication_scheme, rng
    )
    if memory_limit is not None:
        mem = compute_memory_requirements(
            partitioned.tensors, path, contract_size_tensors_bytes
        )
        if mem > memory_limit:
            return math.inf
    return parallel_cost


def _evaluate_cached(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    local_paths: Sequence[Sequence[tuple[int, int]]],
    externals: Sequence[LeafTensor],
    local_costs: Sequence[float],
    communication_scheme: CommunicationScheme,
    memory_limit: float | None,
    rng: random.Random,
) -> float:
    """Score a solution from its per-block caches: only the fan-in
    schedule is recomputed (the per-block paths, externals, and local
    costs were maintained by the move that produced the solution). This
    is the hot function of the SA loop."""
    if memory_limit is not None:
        # memory accounting needs the full path; take the slower route
        return evaluate_partitioning_with_paths(
            tensor,
            partitioning,
            local_paths,
            communication_scheme,
            memory_limit,
            rng,
        )
    present_set = set(partitioning)
    present = sorted(present_set)
    children_tensors = [externals[b] for b in present]
    latency_map = {i: local_costs[b] for i, b in enumerate(present)}
    communication_path = communication_scheme.communication_path(
        children_tensors, latency_map, rng
    )
    tensor_costs = [latency_map[i] for i in range(len(children_tensors))]
    (parallel_cost, _), _ = communication_path_op_costs(
        children_tensors, communication_path, True, tensor_costs
    )
    return parallel_cost


class OptModel:
    """Trial-generation + scoring interface (``simulated_annealing.rs:38-51``)."""

    def generate_trial_solution(self, current, rng: random.Random):
        raise NotImplementedError

    def evaluate(self, solution, rng: random.Random) -> float:
        raise NotImplementedError

    def _require_multiple_partitions(self) -> None:
        # A 1-partition model has no moves: the trial loops that pick a
        # different target partition would spin forever.
        if self.num_partitions < 2:
            raise ValueError(
                f"{type(self).__name__} needs num_partitions >= 2, "
                f"got {self.num_partitions}"
            )


@dataclass
class NaivePartitioningModel(OptModel):
    """Move-one-leaf trial model scored by naive serial cost.

    >>> import random
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [2, 2]),
    ...     LeafTensor([1, 2], [2, 2]), LeafTensor([2, 3], [2, 2]),
    ...     LeafTensor([3, 0], [2, 2])])
    >>> model = NaivePartitioningModel(tn, 2)
    >>> best, score = balance_partitions(
    ...     model, model.initial_solution([0, 0, 1, 1]),
    ...     random.Random(0), max_time=0.5, n_trials=4, n_workers=0)
    >>> len(best), score > 0
    (4, True)
    """

    tensor: CompositeTensor
    num_partitions: int
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def __post_init__(self) -> None:
        self._require_multiple_partitions()

    def initial_solution(self, partitioning: Sequence[int]) -> list[int]:
        return list(partitioning)

    def generate_trial_solution(self, current: list[int], rng: random.Random):
        solution = list(current)
        index = rng.randrange(len(solution))
        current_partition = solution[index]
        while True:
            b = rng.randrange(self.num_partitions)
            if b != current_partition:
                break
        solution[index] = b
        return solution

    def evaluate(self, solution: list[int], rng: random.Random) -> float:
        return evaluate_partitioning(
            self.tensor, solution, self.communication_scheme, self.memory_limit, rng
        )


def _local_greedy_path(tensors: list) -> list[tuple[int, int]]:
    tn = CompositeTensor(tensors)
    if len(tn) <= 1:
        return []
    return Greedy(OptMethod.GREEDY).find_path(tn).replace_path().toplevel


def _blocks_by_id(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    num_partitions: int | None = None,
) -> list[list]:
    """Tensors grouped by partition *id* (possibly-empty blocks kept, so
    per-id caches stay aligned with the ids moves use)."""
    k = num_partitions if num_partitions is not None else max(partitioning) + 1
    blocks: list[list] = [[] for _ in range(k)]
    for t, b in zip(tensor.tensors, partitioning):
        blocks[b].append(t)
    return blocks


def _external_of(tensors: list) -> LeafTensor:
    out = LeafTensor()
    for t in tensors:
        out = out ^ t
    return out


def _subtree_leaves(
    local_path: list[tuple[int, int]], pair_index: int
) -> set[int]:
    """Leaves contributing to the contraction at ``pair_index``
    (``simulated_annealing.rs:279-292``): walk earlier pairs backwards,
    collecting partners of already-included results."""
    i, j = local_path[pair_index]
    leaves = {i, j}
    for a, b in reversed(local_path[:pair_index]):
        if a in leaves:
            leaves.add(b)
    return leaves


def _pick_subtree_and_indices(
    partitioning: list[int],
    local_paths: list[list[tuple[int, int]]],
    rng: random.Random,
) -> tuple[int, list[int]] | None:
    """Pick a source partition with >=3 local pairs and a random subtree;
    return (source partition, global tensor indices to move)."""
    viable = [p for p, path in enumerate(local_paths) if len(path) >= 3]
    if not viable:
        return None
    source = rng.choice(viable)
    pair_index = rng.randrange(len(local_paths[source]) - 1)
    leaves = _subtree_leaves(local_paths[source], pair_index)

    shifted_global: list[int] = []
    local_index = 0
    for global_index, partition in enumerate(partitioning):
        if partition != source:
            continue
        if local_index in leaves:
            shifted_global.append(global_index)
        local_index += 1
    return source, shifted_global


def _local_path_cost(tensors: list, path: list[tuple[int, int]]) -> float:
    if len(tensors) <= 1 or not path:
        return 0.0
    cost, _ = contract_path_cost(tensors, ContractionPath.simple(path), True)
    return cost


def _recompute_two_paths(
    tensor: CompositeTensor,
    partitioning: list[int],
    local_paths: list[list[tuple[int, int]]],
    source: int,
    target: int,
    local_costs: list[float] | None = None,
) -> None:
    """Re-path (and re-cost) only the two partitions a move touched
    (``simulated_annealing.rs:457-562``)."""
    from_tensors = []
    to_tensors = []
    for partition, t in zip(partitioning, tensor.tensors):
        if partition == source:
            from_tensors.append(t)
        elif partition == target:
            to_tensors.append(t)
    local_paths[source] = _local_greedy_path(from_tensors)
    local_paths[target] = _local_greedy_path(to_tensors)
    if local_costs is not None:
        local_costs[source] = _local_path_cost(from_tensors, local_paths[source])
        local_costs[target] = _local_path_cost(to_tensors, local_paths[target])


@dataclass
class NaiveIntermediatePartitioningModel(OptModel):
    """Moves a random subtree to a random partition.

    Solution: (partitioning, local_paths, externals, local_costs) — the
    last two are per-block caches so :func:`_evaluate_cached` only has to
    redo the fan-in schedule.
    """

    tensor: CompositeTensor
    num_partitions: int
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def __post_init__(self) -> None:
        self._require_multiple_partitions()

    def initial_solution(self, partitioning: Sequence[int]):
        blocks = _blocks_by_id(self.tensor, partitioning, self.num_partitions)
        paths = [_local_greedy_path(block) for block in blocks]
        externals = [_external_of(block) for block in blocks]
        costs = [_local_path_cost(b, p) for b, p in zip(blocks, paths)]
        return list(partitioning), paths, externals, costs

    def generate_trial_solution(self, current, rng: random.Random):
        partitioning, local_paths, externals, local_costs = current
        partitioning = list(partitioning)
        local_paths = [list(p) for p in local_paths]
        externals = list(externals)
        local_costs = list(local_costs)

        picked = _pick_subtree_and_indices(partitioning, local_paths, rng)
        if picked is None:
            return partitioning, local_paths, externals, local_costs
        source, shifted = picked
        while True:
            target = rng.randrange(self.num_partitions)
            if target != source:
                break
        shifted_external = LeafTensor()
        for index in shifted:
            partitioning[index] = target
            shifted_external = shifted_external ^ self.tensor.tensors[index]
        externals[source] = externals[source] ^ shifted_external
        externals[target] = externals[target] ^ shifted_external
        _recompute_two_paths(
            self.tensor, partitioning, local_paths, source, target, local_costs
        )
        return partitioning, local_paths, externals, local_costs

    def evaluate(self, solution, rng: random.Random) -> float:
        return _evaluate_cached(
            self.tensor,
            solution[0],
            solution[1],
            solution[2],
            solution[3],
            self.communication_scheme,
            self.memory_limit,
            rng,
        )


@dataclass
class LeafPartitioningModel(OptModel):
    """Moves a random tensor to the partition maximizing size reduction."""

    tensor: CompositeTensor
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def initial_solution(
        self, partitioning: Sequence[int]
    ) -> tuple[list[int], list[LeafTensor]]:
        blocks = _blocks_by_id(self.tensor, partitioning)
        externals = [_external_of(block) for block in blocks]
        return list(partitioning), externals

    def generate_trial_solution(self, current, rng: random.Random):
        partitioning, partition_tensors = current
        partitioning = list(partitioning)
        partition_tensors = [t.copy() for t in partition_tensors]

        index = rng.randrange(len(partitioning))
        shifted = self.tensor.tensors[index]
        source = partitioning[index]

        best_target = -1
        best_score = math.inf
        for p, external in enumerate(partition_tensors):
            if p == source:
                continue
            score = (shifted ^ external).size() - external.size()
            if score < best_score:
                best_score = score
                best_target = p
        if best_target < 0:
            return partitioning, partition_tensors

        partitioning[index] = best_target
        partition_tensors[source] = partition_tensors[source] ^ shifted
        partition_tensors[best_target] = partition_tensors[best_target] ^ shifted
        return partitioning, partition_tensors

    def evaluate(self, solution, rng: random.Random) -> float:
        return evaluate_partitioning(
            self.tensor, solution[0], self.communication_scheme, self.memory_limit, rng
        )


@dataclass
class IntermediatePartitioningModel(OptModel):
    """Moves a random subtree to the partition maximizing size reduction
    (the reference's best-performing model, ``book/src/partitioning.md``)."""

    tensor: CompositeTensor
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None

    def initial_solution(
        self,
        partitioning: Sequence[int],
        initial_paths: list[list[tuple[int, int]]] | None = None,
    ):
        blocks = _blocks_by_id(self.tensor, partitioning)
        externals = [_external_of(block) for block in blocks]
        paths = initial_paths or [_local_greedy_path(block) for block in blocks]
        costs = [_local_path_cost(b, p) for b, p in zip(blocks, paths)]
        return list(partitioning), externals, paths, costs

    def generate_trial_solution(self, current, rng: random.Random):
        partitioning, partition_tensors, local_paths, local_costs = current
        partitioning = list(partitioning)
        partition_tensors = [t.copy() for t in partition_tensors]
        local_paths = [list(p) for p in local_paths]
        local_costs = list(local_costs)

        picked = _pick_subtree_and_indices(partitioning, local_paths, rng)
        if picked is None:
            return partitioning, partition_tensors, local_paths, local_costs
        source, shifted_indices = picked

        shifted = LeafTensor()
        for index in shifted_indices:
            shifted = shifted ^ self.tensor.tensors[index]

        best_target = -1
        best_score = math.inf
        for p, external in enumerate(partition_tensors):
            if p == source:
                continue
            score = (shifted ^ external).size() - external.size()
            if score < best_score:
                best_score = score
                best_target = p
        if best_target < 0:
            return partitioning, partition_tensors, local_paths, local_costs

        for index in shifted_indices:
            partitioning[index] = best_target
        partition_tensors[source] = partition_tensors[source] ^ shifted
        partition_tensors[best_target] = partition_tensors[best_target] ^ shifted
        _recompute_two_paths(
            self.tensor, partitioning, local_paths, source, best_target, local_costs
        )
        return partitioning, partition_tensors, local_paths, local_costs

    def evaluate(self, solution, rng: random.Random) -> float:
        return _evaluate_cached(
            self.tensor,
            solution[0],
            solution[2],
            solution[1],
            solution[3],
            self.communication_scheme,
            self.memory_limit,
            rng,
        )


def _run_chain(model, seed, steps, temperature, solution, score):
    """One SA chain: pure function of its arguments — identical results
    inline or in a worker process (the reference's reproducibility
    rationale for a fixed chain count, ``simulated_annealing.rs:33-35``)."""
    chain_rng = random.Random(seed)
    trial_solution, trial_score = solution, score
    for _ in range(steps):
        candidate = model.generate_trial_solution(trial_solution, chain_rng)
        candidate_score = model.evaluate(candidate, chain_rng)
        if candidate_score <= 0 or trial_score <= 0:
            accept = candidate_score < trial_score
        else:
            diff = math.log2(candidate_score / trial_score)
            accept = math.exp(-diff / temperature) >= chain_rng.random()
        if accept:
            trial_solution = candidate
            trial_score = candidate_score
    return trial_score, trial_solution


_POOL_MODEL: OptModel | None = None


def _pool_init(model: OptModel) -> None:
    global _POOL_MODEL
    _POOL_MODEL = model


def _pool_chain(args):
    seed, steps, temperature, solution, score = args
    return _run_chain(_POOL_MODEL, seed, steps, temperature, solution, score)


def spawn_safe() -> bool:
    """Whether a spawn-context pool can work here: spawn re-imports the
    parent's ``__main__``, which crash-loops when that module has no
    importable file (stdin scripts, embedded interpreters)."""
    import __main__

    main_file = getattr(__main__, "__file__", None)
    if main_file is None:
        return True  # interactive/pytest-style __main__: spawn handles it
    return os.path.exists(main_file)


@dataclass
class SimulatedAnnealingOptimizer:
    """SA engine (``simulated_annealing.rs:54-167``).

    ``n_workers``: process count for chain evaluation (None = min of
    ``n_trials`` and the CPU count; ``TNC_TPU_SA_WORKERS`` overrides).
    Workers are spawned with ``JAX_PLATFORMS=cpu`` so they can never
    touch an accelerator; scoring is pure host math.
    """

    n_trials: int = 8
    max_time: float = 10.0
    n_steps: int = 80
    restart_iter: int = 50
    initial_temperature: float = 2.0
    final_temperature: float = 0.05
    n_workers: int | None = None
    # Work-bounded mode: run exactly this many rounds with a round-indexed
    # temperature schedule — fully deterministic at any worker count
    # (wall-clock budgets make round counts machine-dependent).
    max_rounds: int | None = None

    def _resolve_workers(self) -> int:
        env = os.environ.get("TNC_TPU_SA_WORKERS")
        if env is not None:
            return max(1, int(env))
        if self.n_workers is not None:
            return max(1, self.n_workers)
        return max(1, min(self.n_trials, os.cpu_count() or 1))

    def _make_pool(self, model: OptModel):
        import multiprocessing as mp

        workers = self._resolve_workers()
        if workers <= 1 or not spawn_safe():
            return None
        prev = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"  # children stay off accelerators
        try:
            ctx = mp.get_context("spawn")
            return ctx.Pool(workers, initializer=_pool_init, initargs=(model,))
        except Exception:
            return None
        finally:
            if prev is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = prev

    def optimize(self, model: OptModel, initial_solution, rng: random.Random):
        current_score = model.evaluate(initial_solution, rng)
        current_solution = initial_solution
        best_solution = current_solution
        best_score = current_score
        last_improvement = 0
        steps_per_chain = -(-self.n_steps // self.n_trials)

        log_start = math.log2(self.initial_temperature)
        log_end = math.log2(self.final_temperature)
        temperature = self.initial_temperature
        start = time.monotonic()
        end_time = start + self.max_time
        pool = self._make_pool(model)
        pool_timeout = max(300.0, 10.0 * self.max_time)
        rounds = 0

        try:
            while True:
                # Fresh per-round, per-chain seeds from the master rng:
                # chain results depend only on (seed, state, temperature),
                # never on worker scheduling.
                jobs = [
                    (
                        rng.getrandbits(64),
                        steps_per_chain,
                        temperature,
                        current_solution,
                        current_score,
                    )
                    for _ in range(self.n_trials)
                ]
                # transient pool failures get ONE retry on a FRESH pool;
                # other failures log the real worker error with the
                # decision and fall back to serial chains for the rest
                # of the run — see resilience.retry.pool_map_with_retry
                results, pool = pool_map_with_retry(
                    pool,
                    lambda p: p.map_async(_pool_chain, jobs).get(
                        timeout=pool_timeout
                    ),
                    lambda: self._make_pool(model),
                    logger,
                    "simulated-annealing chain pool",
                )
                if results is None:
                    results = [_run_chain(model, *job) for job in jobs]

                best_chain = None
                for trial_score, trial_solution in results:
                    if best_chain is None or trial_score < best_chain[0]:
                        best_chain = (trial_score, trial_solution)
                assert best_chain is not None
                current_score, current_solution = best_chain

                if current_score < best_score:
                    best_solution = current_solution
                    best_score = current_score
                    last_improvement = 0
                last_improvement += 1
                if last_improvement == self.restart_iter:
                    current_solution = best_solution
                    current_score = best_score

                rounds += 1
                if self.max_rounds is not None:
                    if rounds >= self.max_rounds:
                        break
                    progress = rounds / self.max_rounds
                else:
                    now = time.monotonic()
                    if now > end_time:
                        break
                    progress = 1.0 - (end_time - now) / self.max_time
                temperature = 2.0 ** (log_start + (log_end - log_start) * progress)
        finally:
            if pool is not None:
                pool.terminate()
                pool.join()

        return best_solution, best_score


def balance_partitions(
    model: OptModel,
    initial_solution,
    rng: random.Random,
    max_time: float = 10.0,
    n_trials: int = 48,
    n_workers: int | None = None,
    max_rounds: int | None = None,
):
    """Run SA with the reference's engine settings: 48 chains x 10 steps
    per round (``simulated_annealing.rs:33-35,576-595``). Pass
    ``max_rounds`` for a work-bounded, machine-independent run."""
    optimizer = SimulatedAnnealingOptimizer(
        n_trials=n_trials,
        max_time=max_time,
        n_steps=n_trials * 10,
        restart_iter=50,
        initial_temperature=2.0,
        final_temperature=0.05,
        n_workers=n_workers,
        max_rounds=max_rounds,
    )
    return optimizer.optimize(model, initial_solution, rng)
