"""Genetic-algorithm repartitioning.

Mirror of ``tnc/src/contractionpath/repartitioning/genetic.rs``: evolve
partition-assignment chromosomes with single-gene mutation, uniform
crossover, and tournament selection (the reference uses the
``genetic_algorithm`` crate with population 100, stale limit 100,
``MutateSingleGene(0.2)``; this is a self-contained equivalent). Fitness
is evaluated by a process pool when cores are available, like the
reference's ``.with_par_fitness(true)`` (``genetic.rs:103``); scoring is
a pure function of the chromosome so results are worker-count invariant.
"""

from __future__ import annotations

import logging
import os
import random
from dataclasses import dataclass
from typing import Sequence

from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.repartitioning.simulated_annealing import (
    evaluate_partitioning,
)
from tnc_tpu.resilience.retry import pool_map_with_retry
from tnc_tpu.tensornetwork.tensor import CompositeTensor

logger = logging.getLogger(__name__)

_POOL_CTX = None


def _fitness_init(tensor, scheme, memory_limit):
    global _POOL_CTX
    _POOL_CTX = (tensor, scheme, memory_limit)


def _fitness_worker(args):
    seed, chromosome = args
    tensor, scheme, memory_limit = _POOL_CTX
    return evaluate_partitioning(
        tensor, chromosome, scheme, memory_limit, random.Random(seed)
    )


def _make_fitness_pool(tensor, scheme, memory_limit, population_size):
    import multiprocessing as mp

    from tnc_tpu.contractionpath.repartitioning.simulated_annealing import (
        spawn_safe,
    )

    env = os.environ.get("TNC_TPU_SA_WORKERS")
    workers = (
        max(1, int(env))
        if env is not None
        else max(1, min(population_size, os.cpu_count() or 1))
    )
    if workers <= 1 or not spawn_safe():
        return None
    prev = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        ctx = mp.get_context("spawn")
        return ctx.Pool(
            workers,
            initializer=_fitness_init,
            initargs=(tensor, scheme, memory_limit),
        )
    except Exception:
        return None
    finally:
        if prev is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev


@dataclass
class GeneticSettings:
    population_size: int = 100
    mutation_probability: float = 0.2
    tournament_size: int = 4
    stale_limit: int = 100
    max_generations: int = 1000


def balance_partitions(
    tensor: CompositeTensor,
    initial_partitioning: Sequence[int],
    num_partitions: int,
    rng: random.Random,
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY,
    memory_limit: float | None = None,
    settings: GeneticSettings | None = None,
    max_time: float | None = None,
) -> tuple[list[int], float]:
    """Evolve the partitioning; returns (best chromosome, best score).

    >>> import random
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [2, 2]),
    ...     LeafTensor([1, 2], [2, 2]), LeafTensor([2, 3], [2, 2]),
    ...     LeafTensor([3, 0], [2, 2])])
    >>> best, score = balance_partitions(
    ...     tn, [0, 0, 1, 1], 2, random.Random(0),
    ...     settings=GeneticSettings(population_size=4, max_generations=2))
    >>> len(best), score > 0
    (4, True)
    """
    import time

    settings = settings or GeneticSettings()
    deadline = time.monotonic() + max_time if max_time else None
    pool = _make_fitness_pool(
        tensor, communication_scheme, memory_limit, settings.population_size
    )

    def score_population(population: list[list[int]]) -> list[tuple[float, list[int]]]:
        nonlocal pool
        jobs = [(rng.getrandbits(64), c) for c in population]
        # transient pool failures (a worker lost to a timeout/preemption)
        # get ONE retry on a FRESH pool; anything else logs the real
        # worker error and falls back to serial evaluation (identical
        # results, slower) — see resilience.retry.pool_map_with_retry
        scores, pool = pool_map_with_retry(
            pool,
            lambda p: p.map_async(_fitness_worker, jobs).get(timeout=600.0),
            lambda: _make_fitness_pool(
                tensor, communication_scheme, memory_limit,
                settings.population_size,
            ),
            logger,
            "genetic fitness pool",
        )
        if scores is not None:
            return list(zip(scores, population))
        return [
            (
                evaluate_partitioning(
                    tensor,
                    c,
                    communication_scheme,
                    memory_limit,
                    random.Random(seed),
                ),
                c,
            )
            for seed, c in jobs
        ]

    def mutate(chromosome: list[int]) -> list[int]:
        out = list(chromosome)
        if rng.random() < settings.mutation_probability:
            gene = rng.randrange(len(out))
            out[gene] = rng.randrange(num_partitions)
        return out

    def crossover(a: list[int], b: list[int]) -> list[int]:
        return [x if rng.random() < 0.5 else y for x, y in zip(a, b)]

    def tournament(scored: list[tuple[float, list[int]]]) -> list[int]:
        picks = [scored[rng.randrange(len(scored))] for _ in range(settings.tournament_size)]
        return min(picks, key=lambda p: p[0])[1]

    population = [list(initial_partitioning)]
    for _ in range(settings.population_size - 1):
        population.append(mutate(list(initial_partitioning)))

    try:
        scored = score_population(population)
        best_score, best = min(scored, key=lambda p: p[0])
        stale = 0

        for _generation in range(settings.max_generations):
            if stale >= settings.stale_limit:
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            next_population = [best]  # elitism
            while len(next_population) < settings.population_size:
                child = mutate(crossover(tournament(scored), tournament(scored)))
                next_population.append(child)
            population = next_population
            scored = score_population(population)
            gen_best_score, gen_best = min(scored, key=lambda p: p[0])
            if gen_best_score < best_score:
                best_score, best = gen_best_score, gen_best
                stale = 0
            else:
                stale += 1
    finally:
        if pool is not None:
            pool.terminate()
            pool.join()

    return best, best_score
