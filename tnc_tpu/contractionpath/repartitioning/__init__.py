"""Partitioning refinement: evaluate and improve a partition assignment.

Mirror of ``tnc/src/contractionpath/repartitioning.rs``:
:func:`compute_solution` is the shared evaluation kernel — partition the
network, find greedy local paths per partition, schedule the fan-in with a
communication scheme using the local costs as latencies, and return the
critical-path (parallel) and sum (serial) costs.
"""

from __future__ import annotations

import random
from typing import Sequence

from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_cost import (
    communication_path_op_costs,
    contract_path_cost,
)
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
from tnc_tpu.tensornetwork.partitioning import partition_tensor_network
from tnc_tpu.tensornetwork.tensor import CompositeTensor


def _fanin_cost_function(cost_model):
    """Per-pair fan-in cost in the latency domain: predicted seconds
    under a calibrated model, naive op counts otherwise (None selects
    the default inside :func:`communication_path_op_costs`)."""
    if cost_model is None:
        return None
    from tnc_tpu.contractionpath.contraction_cost import CalibratedObjective

    return CalibratedObjective(cost_model).pair_cost


def compute_solution(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY,
    rng: random.Random | None = None,
    cost_model=None,
) -> tuple[CompositeTensor, ContractionPath, float, float]:
    """(partitioned network, full path, parallel cost, serial cost)
    for a partition assignment (``repartitioning.rs:25-76``).

    ``cost_model`` (a :class:`~tnc_tpu.obs.calibrate.
    CalibratedCostModel`) moves the whole evaluation into the seconds
    domain: per-partition latencies become predicted local completion
    times (dispatch overhead charged per local step), the scheme
    schedules against them, and the returned parallel/serial costs are
    predicted seconds instead of op counts."""
    partitioned = partition_tensor_network(
        CompositeTensor(list(tensor.tensors)), partitioning
    )

    result = Greedy(OptMethod.GREEDY).find_path(partitioned)
    path = result.replace_path()

    latency_map = {i: 0.0 for i in range(len(partitioned))}
    local_steps = {i: 0.0 for i in range(len(partitioned))}
    for i, local_path in path.nested.items():
        child = partitioned[i]
        local_cost, _ = contract_path_cost(child.tensors, local_path, True)
        latency_map[i] = local_cost
        local_steps[i] = float(len(local_path.toplevel))
    if cost_model is not None:
        from tnc_tpu.contractionpath.communication_schemes import (
            calibrated_latency_map,
        )

        latency_map = calibrated_latency_map(
            latency_map, cost_model, local_steps
        )

    children_tensors = [child.external_tensor() for child in partitioned]
    communication_path = communication_scheme.communication_path(
        children_tensors, latency_map, rng, cost_model=cost_model
    )
    tensor_costs = [latency_map[i] for i in range(len(children_tensors))]
    (parallel_cost, sum_cost), _ = communication_path_op_costs(
        children_tensors, communication_path, True, tensor_costs,
        cost_function=_fanin_cost_function(cost_model),
    )

    final_path = ContractionPath(path.nested, communication_path)
    return partitioned, final_path, parallel_cost, sum_cost


def compute_solution_with_paths(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    local_paths: Sequence[Sequence[tuple[int, int]]],
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY,
    rng: random.Random | None = None,
    communication_path: Sequence[tuple[int, int]] | None = None,
    cost_model=None,
) -> tuple[CompositeTensor, ContractionPath, float, float]:
    """Like :func:`compute_solution`, but reuses caller-maintained local
    paths instead of re-running Greedy on every partition.

    This is the incremental evaluation kernel for the SA models
    (mirroring ``simulated_annealing.rs:457-562``, where a trial move
    re-paths only the two touched partitions): ``local_paths[b]`` is the
    replace-path over block ``b``'s tensors in original order. Empty
    blocks are dropped and blocks ordered by id, exactly as
    :func:`~tnc_tpu.tensornetwork.partitioning.partition_tensor_network`
    does.

    ``communication_path``: a caller-supplied replace-format fan-in
    over COMPACTED block positions (blocks sorted by id after dropping
    empties — identical to raw ids only for dense assignments, which
    tree-cut plans guarantee) — skips the scheme. The path is validated
    fully: exactly ``k-1`` pairs forming a replace-left sequence over
    the ``k`` compacted blocks, every referenced slot still live.

    ``cost_model``: as in :func:`compute_solution` — latencies and the
    returned costs move to predicted seconds.
    """
    blocks: dict[int, list] = {}
    for t, b in zip(tensor.tensors, partitioning):
        blocks.setdefault(b, []).append(t)
    present = sorted(blocks)

    nested: dict[int, ContractionPath] = {}
    latency_map: dict[int, float] = {}
    local_steps: dict[int, float] = {}
    children = []
    children_tensors = []
    for idx, b in enumerate(present):
        child = CompositeTensor(blocks[b])
        children.append(child)
        children_tensors.append(child.external_tensor())
        local = ContractionPath.simple(list(local_paths[b]))
        nested[idx] = local
        local_cost, _ = contract_path_cost(child.tensors, local, True)
        latency_map[idx] = local_cost
        local_steps[idx] = float(len(local.toplevel))
    if cost_model is not None:
        from tnc_tpu.contractionpath.communication_schemes import (
            calibrated_latency_map,
        )

        latency_map = calibrated_latency_map(
            latency_map, cost_model, local_steps
        )

    if communication_path is None:
        communication_path = communication_scheme.communication_path(
            children_tensors, latency_map, rng, cost_model=cost_model
        )
    else:
        communication_path = list(communication_path)
        k = len(children_tensors)
        # full replace-left validation: the fan-in must contract k blocks
        # down to one, so it is exactly k-1 pairs over live compacted
        # block positions (the result replaces slot ``a``; slot ``b`` is
        # consumed). Bounds checks alone let a stale plan reference a
        # consumed slot and silently contract garbage.
        if len(communication_path) != k - 1:
            raise ValueError(
                f"communication_path has {len(communication_path)} pairs; "
                f"a fan-in over {k} compacted blocks needs exactly {k - 1}"
            )
        live = set(range(k))
        for a, b in communication_path:
            if not (0 <= a < k and 0 <= b < k):
                raise ValueError(
                    f"communication_path index ({a}, {b}) outside the "
                    f"compacted block space of {k} blocks"
                )
            if a == b:
                raise ValueError(
                    f"communication_path pair ({a}, {b}) contracts a slot "
                    "with itself"
                )
            if a not in live or b not in live:
                dead = a if a not in live else b
                raise ValueError(
                    f"communication_path pair ({a}, {b}) references slot "
                    f"{dead}, already consumed by an earlier pair"
                )
            live.discard(b)
    tensor_costs = [latency_map[i] for i in range(len(children_tensors))]
    (parallel_cost, sum_cost), _ = communication_path_op_costs(
        children_tensors, communication_path, True, tensor_costs,
        cost_function=_fanin_cost_function(cost_model),
    )

    partitioned = CompositeTensor(children)
    final_path = ContractionPath(nested, communication_path)
    return partitioned, final_path, parallel_cost, sum_cost
