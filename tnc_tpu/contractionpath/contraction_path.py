"""Contraction path types and format conversions.

Mirror of ``tnc/src/contractionpath.rs``: a (possibly nested)
``ContractionPath`` holds per-child nested paths for composite tensors plus
a flat ``toplevel`` pair list. In a partitioned/distributed network, the
``toplevel`` path doubles as the inter-device communication schedule
(``mpi/communication.rs:199-249``).

Three path formats (``book/src/pathfinding_and_contraction.md``):

- **SSA**: each contraction output gets the next fresh id (``n``, ``n+1``,
  ...); inputs are referenced by ssa id.
- **replace-left**: the output replaces the *left* input's position; no
  positions are compacted (executor keeps a list of optionals).
- **linear/opt-einsum**: not used internally; see :func:`ssa_ordering` for
  converting optimizer triple output.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

SimplePath = list  # list[tuple[int, int]]
# read-only view alias (``SimplePathRef``, ``contractionpath.rs:22``) —
# Python callers accept any sequence of pairs where Rust takes a slice
SimplePathRef = Sequence  # Sequence[tuple[int, int]]


@dataclass
class ContractionPath:
    """A nested contraction path (``contractionpath.rs:30-35``)."""

    nested: dict[int, "ContractionPath"] = field(default_factory=dict)
    toplevel: list[tuple[int, int]] = field(default_factory=list)

    @classmethod
    def simple(cls, toplevel: Sequence[tuple[int, int]]) -> "ContractionPath":
        """A flat (un-nested) path.

        >>> p = ContractionPath.simple([(0, 1), (0, 2)])
        >>> p.is_simple(), len(p)
        (True, 2)
        """
        return cls({}, list(toplevel))

    def is_simple(self) -> bool:
        return not self.nested

    def __len__(self) -> int:
        return len(self.toplevel)

    def total_len(self) -> int:
        return len(self.toplevel) + sum(p.total_len() for p in self.nested.values())

    def to_obj(self) -> list[list[int]]:
        """JSON-able form of a *flat* path (plan serialization — the
        serving plan cache stores paths as plain JSON). Nested paths
        are an in-memory planning artifact and are not serialized here.
        """
        if self.nested:
            raise ValueError("only flat paths serialize to_obj")
        return [[int(i), int(j)] for i, j in self.toplevel]

    @classmethod
    def from_obj(cls, obj) -> "ContractionPath":
        """Inverse of :meth:`to_obj`.

        >>> ContractionPath.from_obj([[0, 1], [0, 2]]).toplevel
        [(0, 1), (0, 2)]
        """
        return cls.simple([(int(i), int(j)) for i, j in obj])


def path(*items) -> ContractionPath:
    """Convenience constructor mirroring the reference's ``path!`` macro.

    ``path((0, 1), (3, 2))`` builds a simple path; nested children are given
    as ``path({2: path((0, 1))}, (0, 1))`` — a leading dict maps child index
    to its nested path.
    """
    nested: dict[int, ContractionPath] = {}
    toplevel: list[tuple[int, int]] = []
    for item in items:
        if isinstance(item, dict):
            nested.update(item)
        else:
            toplevel.append((int(item[0]), int(item[1])))
    return ContractionPath(nested, toplevel)


def ssa_ordering(triples: Sequence[tuple[int, int, int]], n: int) -> ContractionPath:
    """Convert optimizer triple output ``(in1, in2, out)`` with arbitrary
    intermediate ids into strict SSA format (``contractionpath.rs:180-192``).
    """
    remap: dict[int, int] = {}
    next_id = n
    ssa_path = []
    for u1, u2, u3 in triples:
        t1 = remap[u1] if u1 >= n else u1
        t2 = remap[u2] if u2 >= n else u2
        if u3 not in remap:
            remap[u3] = next_id
        next_id += 1
        ssa_path.append((t1, t2))
    return ContractionPath.simple(ssa_path)


def ssa_replace_ordering(
    ssa: ContractionPath, num_inputs: int | None = None
) -> ContractionPath:
    """SSA → replace-left, recursing into nested paths
    (``contractionpath.rs:197-215``). ``num_inputs`` defaults to
    ``len(toplevel) + 1`` (a fully-contracting path).

    >>> ssa = ContractionPath.simple([(0, 1), (3, 2), (4, 5)])
    >>> ssa_replace_ordering(ssa, num_inputs=4).toplevel
    [(0, 1), (3, 2), (0, 3)]
    """
    nested = {i: ssa_replace_ordering(p) for i, p in ssa.nested.items()}
    n = num_inputs if num_inputs is not None else len(ssa.toplevel) + 1
    position: dict[int, int] = {}
    toplevel = []
    for step, (t0, t1) in enumerate(ssa.toplevel):
        new_t0 = position.get(t0, t0)
        new_t1 = position.get(t1, t1)
        position[n + step] = new_t0
        toplevel.append((new_t0, new_t1))
    return ContractionPath(nested, toplevel)


def replace_ssa_ordering(
    replace: Sequence[tuple[int, int]], num_inputs: int
) -> list[tuple[int, int]]:
    """Replace-left → SSA pairs (inverse of :func:`ssa_replace_ordering`
    for a flat path): slot ``a`` holds a fresh ssa id after each step
    that writes it.

    >>> replace_ssa_ordering([(0, 1), (3, 2), (0, 3)], 4)
    [(0, 1), (3, 2), (4, 5)]
    """
    current = list(range(num_inputs))
    out: list[tuple[int, int]] = []
    nxt = num_inputs
    for a, b in replace:
        out.append((current[a], current[b]))
        current[a] = nxt
        nxt += 1
    return out


def validate_path(path_: ContractionPath, num_tensors: int) -> bool:
    """Sanity-check a replace-left path fully contracts ``num_tensors``
    tensors into one (``paths.rs:87-100``): every step consumes a live
    position and exactly one survivor remains.
    """
    alive = set(range(num_tensors))
    for i, j in path_.toplevel:
        if i not in alive or j not in alive or i == j:
            return False
        alive.discard(j)
    return len(alive) == 1 or (num_tensors == 1 and not path_.toplevel)
