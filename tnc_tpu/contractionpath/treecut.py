"""Fan-in-aware partitioning by cutting a descent-refined contraction tree.

The hypergraph partitioners (``tnc_tpu.tensornetwork.partitioning``,
mirroring ``tnc/src/tensornetwork/partitioning.rs:31-160``) optimize a
*cut* objective (km1 / communication volume) that is blind to how the
contraction work distributes over partitions: on deep circuit networks a
min-cut assignment routinely leaves one partition holding essentially
all the flops (measured round 4: critical path == serial sum, plan
speedup 1.00), and simulated-annealing rebalancing of the *assignment*
converged to ~1.85 of an ideal 8 — the objective, not the search, was
the limit. Worse, the partition-then-path pipeline pays a large total-
work penalty: on the config-5 instance the best SA-rebalanced plan
summed to 3.4e10 flops while a single good serial tree (the native
hyper-optimizer's) needs only 4.6e9 (measured round 5).

This module takes the opposite route — the VERDICT-r4 #5 suggestion of
cutting the contraction **tree** top-down so fan-in latencies balance:

1. Start from one good *serial* tree over the whole network (the caller
   brings the path — greedy or the hyper-optimizer).
2. A partition plan is a **frontier**: ``k`` disjoint subtrees covering
   every leaf, found by repeatedly splitting the frontier node with the
   most accumulated contraction cost. Each device contracts one
   subtree exactly as the serial plan would have; the tree *above* the
   frontier is the fan-in schedule.
3. The plan's cost model is its critical path: ``time(node) =
   node_cost + max(time(children))`` above the frontier, ``time =
   subtree cost`` at it. Randomized strict-descent local search over
   the standard tree rotations (the
   :mod:`~tnc_tpu.contractionpath.paths.tree_refine` move set)
   minimizes THIS — rotations migrate work across the future cut,
   trading serial-optimal association for frontier balance the global
   objective actually pays for. (Metropolis acceptance was measured to
   random-walk away from the narrow improving region on real circuit
   trees — log2-cost plateaus dominate the move space — so descent
   accepts strictly-improving rotations only.)

Because partitions are contiguous pieces of one serial tree, the cut
tensors are intermediates the serial plan would have formed anyway
(no min-cut-style leg explosion), and the per-block local paths come
from the tree itself — no lossy greedy re-pathing of each block
(measured: greedy re-pathing a 126-tensor block of a 4.6e9-flop tree
costs 4.9e11, a 100x regression this module's ``local_paths`` avoid).
"""

from __future__ import annotations

import heapq
import random
from dataclasses import dataclass
from typing import Sequence

from tnc_tpu.contractionpath.contraction_path import (
    ContractionPath,
    ssa_replace_ordering,
)
from tnc_tpu.contractionpath.contraction_tree import ContractionTree
from tnc_tpu.contractionpath.paths.tree_refine import (
    _apply_rotation,
    _rotation_candidates,
)
from tnc_tpu.tensornetwork.tensor import LeafTensor


def _to_replace(ssa_pairs, num_inputs: int) -> list[tuple[int, int]]:
    """SSA → replace-left via the canonical converter."""
    return ssa_replace_ordering(
        ContractionPath.simple(list(ssa_pairs)), num_inputs
    ).toplevel


@dataclass
class TreecutPlan:
    """A k-way plan cut from a serial contraction tree.

    ``assignment``: partition id per input tensor (dense, ordered by
    first appearance — the ``partition_tensor_network`` convention).
    ``local_paths``: per-block replace-format path over the block's
    tensors in original input order (the
    :func:`~tnc_tpu.contractionpath.repartitioning.compute_solution_with_paths`
    contract).
    ``toplevel``: the serial tree's top region as a replace-format
    fan-in over block indices — a latency-aware communication schedule
    by construction (pass to ``compute_solution_with_paths``'s
    ``communication_path``).
    ``critical_estimate`` / ``serial_estimate``: the tree cost model's
    critical-path and total flops (naive op counts, same units as
    ``ContractionTree.total_cost``).
    """

    assignment: list[int]
    local_paths: list[list[tuple[int, int]]]
    toplevel: list[tuple[int, int]]
    critical_estimate: float
    serial_estimate: float

    @property
    def speedup_estimate(self) -> float:
        return self.serial_estimate / max(self.critical_estimate, 1.0)


def _subtree_ssa(tree, top, base_of, num_bases):
    """Post-order SSA pairs over the region below ``top``, stopping at
    nodes present in ``base_of`` (their values are the SSA base ids);
    returns replace-format pairs over ``num_bases`` inputs."""
    ssa_of: dict[int, int] = {}
    next_id = num_bases
    ssa: list[tuple[int, int]] = []
    stack = [(top, False)]
    while stack:
        i, expanded = stack.pop()
        if i in base_of:
            ssa_of[i] = base_of[i]
            continue
        nd = tree.nodes[i]
        if expanded:
            ssa.append((ssa_of[nd.left], ssa_of[nd.right]))
            ssa_of[i] = next_id
            next_id += 1
            continue
        stack.append((i, True))
        stack.append((nd.right, False))
        stack.append((nd.left, False))
    return _to_replace(ssa, num_bases)


def _frontier_critical(
    tree: ContractionTree, k: int
) -> tuple[float, list[int]]:
    """(critical-path cost, frontier node ids) of the best k-frontier
    found by heaviest-first splitting."""
    weights = tree.tree_weights()
    frontier: list[tuple[float, int]] = [(-weights[tree.root], tree.root)]
    atoms: list[tuple[float, int]] = []
    while frontier and len(frontier) + len(atoms) < k:
        w, i = heapq.heappop(frontier)
        nd = tree.nodes[i]
        if nd.is_leaf:
            atoms.append((w, i))
            continue
        heapq.heappush(frontier, (-weights[nd.left], nd.left))
        heapq.heappush(frontier, (-weights[nd.right], nd.right))
    pieces = [i for _, i in frontier + atoms]
    cut = set(pieces)

    # critical path of the fan-in above the frontier: post-order over
    # the top region only
    time: dict[int, float] = {i: weights[i] for i in cut}
    stack = [(tree.root, False)]
    while stack:
        i, expanded = stack.pop()
        if i in time:
            continue
        nd = tree.nodes[i]
        if expanded:
            time[i] = tree.node_cost(i) + max(time[nd.left], time[nd.right])
            continue
        stack.append((i, True))
        stack.append((nd.left, False))
        stack.append((nd.right, False))
    return time[tree.root], pieces


def plan_treecut(
    inputs: Sequence[LeafTensor],
    ssa_pairs: Sequence[tuple[int, int]],
    k: int,
    steps: int = 4000,
    seed: int = 0,
    patience: int = 1000,
) -> TreecutPlan:
    """Cut (and descent-refine) the contraction tree of ``ssa_pairs``
    into a ``k``-device plan minimizing the fan-in critical path.
    ``patience``: stop after this many consecutive rotation PROPOSALS
    without improvement (scaled up to the tree size, so small patience
    cannot starve big trees).

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> ts = [LeafTensor.from_const([0, 1], 4), LeafTensor.from_const([1, 2], 4),
    ...       LeafTensor.from_const([2, 3], 4), LeafTensor.from_const([3, 0], 4)]
    >>> plan = plan_treecut(ts, [(0, 1), (2, 3), (4, 5)], 2, steps=0)
    >>> sorted(set(plan.assignment)), plan.speedup_estimate > 1.0
    ([0, 1], True)
    """
    n = len(inputs)
    if k <= 1:
        # one block holding everything: the local path IS the serial
        # path (replace-format), both estimates the tree total
        tree = ContractionTree.from_ssa_path(inputs, ssa_pairs)
        total = tree.total_cost()[0]
        return TreecutPlan(
            [0] * n, [_to_replace(ssa_pairs, n)], [], total, total
        )
    if n <= k:
        # every tensor its own single-leaf block: no local steps, the
        # whole tree is fan-in
        tree = ContractionTree.from_ssa_path(inputs, ssa_pairs)
        critical, _ = _frontier_critical(tree, n)
        return TreecutPlan(
            list(range(n)),
            [[] for _ in range(n)],
            _to_replace(ssa_pairs, n),
            max(critical, 1.0),
            max(tree.total_cost()[0], 1.0),
        )

    tree = ContractionTree.from_ssa_path(inputs, ssa_pairs)
    rng = random.Random(seed)

    score, _ = _frontier_critical(tree, k)
    internal = [i for i, nd in enumerate(tree.nodes) if not nd.is_leaf]
    # non-moves (unreachable picks, candidate-less nodes) count toward
    # patience, so scale it with the proposal space: a fixed cutoff
    # would starve large trees long before `steps`
    patience = max(patience, 8 * len(internal))
    since_improve = 0
    for _step in range(steps):
        if since_improve >= patience:
            break
        p = internal[rng.randrange(len(internal))]
        if not tree._reachable(p):
            since_improve += 1
            continue
        candidates = list(_rotation_candidates(tree, p))
        if not candidates:
            since_improve += 1
            continue
        x, a, b, c = candidates[rng.randrange(len(candidates))]
        keep, other = (a, b) if rng.random() < 0.5 else (b, a)
        _apply_rotation(tree, p, x, keep, other, c)
        new_score, _ = _frontier_critical(tree, k)
        if new_score < score:
            score = new_score
            since_improve = 0
        else:  # revert: the rotation is its own inverse modulo naming
            _apply_rotation(tree, p, x, keep, c, other)
            since_improve += 1
    critical, pieces = _frontier_critical(tree, k)
    serial = tree.total_cost()[0]

    # leaves under each frontier piece -> assignment (dense ids by
    # first appearance over original input order)
    piece_of: dict[int, int] = {}
    for pi, top in enumerate(pieces):
        stack = [top]
        while stack:
            i = stack.pop()
            nd = tree.nodes[i]
            if nd.is_leaf:
                piece_of[i] = pi
            else:
                stack.append(nd.left)
                stack.append(nd.right)
    remap: dict[int, int] = {}
    assignment = []
    for leaf in range(n):
        pi = piece_of[leaf]
        if pi not in remap:
            remap[pi] = len(remap)
        assignment.append(remap[pi])

    # per-block local paths straight from the tree (replace format over
    # the block's tensors in original input order)
    by_block: dict[int, int] = {}  # piece index -> block id
    for pi, b in ((pi, remap[pi]) for pi in range(len(pieces)) if pi in remap):
        by_block[b] = pi
    local_paths: list[list[tuple[int, int]]] = []
    for b in range(len(remap)):
        top = pieces[by_block[b]]
        leaves = sorted(i for i, pp in piece_of.items() if pp == by_block[b])
        pos = {leaf: j for j, leaf in enumerate(leaves)}
        local_paths.append(_subtree_ssa(tree, top, pos, len(leaves)))

    # the top region as a fan-in over pieces, then block indices
    piece_block = {pieces[pi]: remap[pi] for pi in remap}
    toplevel = _subtree_ssa(tree, tree.root, piece_block, len(remap))

    return TreecutPlan(assignment, local_paths, toplevel, critical, serial)
