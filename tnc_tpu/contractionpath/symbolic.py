"""Symbolic contraction plans: a compact, digestable wire format.

A planner trial's whole output — the SSA pair sequence, the slice-leg
set, the costs it was scored with, and where it came from — is a few
hundred bytes of structure. Treating that structure as a first-class
*symbolic* value (the EinExprs view, arXiv:2403.18030: plans are
expressions, cheap to re-evaluate, compare and ship) is what lets the
planner fleet (:mod:`tnc_tpu.serve.plansvc`) fan trials out across
replicas: results travel as plain JSON, duplicate candidates collapse
by a canonical digest, and two candidates diff *structurally* (shared
subtrees, slice-set delta) instead of by opaque repr comparison.

Discipline (shared with every on-disk artifact in this codebase):

- identity comes from :func:`tnc_tpu.utils.digest.stable_digest` over
  the plan's *structure only* — the pairs and the sorted slice set.
  Costs and provenance are payload, not identity: two trials that land
  on the same tree+slicing dedupe even when their provenance differs;
- the wire form is plain JSON (never pickle) and self-verifying: the
  recorded digest is recomputed on :meth:`SymbolicPlan.from_obj`, so a
  corrupt or tampered result file degrades to "drop the trial", never
  to adopting a plan that isn't what its digest claims.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

from tnc_tpu.utils.digest import stable_digest

WIRE_VERSION = 1


def plan_digest(
    pairs: Sequence[Sequence[int]], slice_legs: Sequence[int]
) -> str:
    """Canonical structural identity of (tree, slice set) — the dedupe
    key for planner trials. Stable across processes and hash seeds
    (:func:`~tnc_tpu.utils.digest.stable_digest`); slice legs are
    sorted so set order never splits identical plans.

    >>> a = plan_digest([(0, 1), (2, 3)], [7, 4])
    >>> a == plan_digest([[0, 1], [2, 3]], (4, 7))
    True
    >>> a == plan_digest([(0, 1), (2, 3)], [4])
    False
    """
    return stable_digest(
        "tnc-symplan-v%d" % WIRE_VERSION,
        tuple((int(a), int(b)) for a, b in pairs),
        tuple(sorted(int(l) for l in slice_legs)),
    )


@dataclass(frozen=True)
class SymbolicPlan:
    """One candidate contraction plan as a symbolic value.

    ``pairs`` are SSA pairs over the flat leaves (what
    :func:`~tnc_tpu.contractionpath.sliced_cost.joint_slice_search`
    returns), ``slice_legs``/``slice_dims`` the slice set, ``cost`` the
    hoisted sliced cost in the trial's objective domain (flops, or
    predicted seconds under a calibrated model). ``provenance``
    records which trial produced it (kind, seed, SA settings) — it
    rides the wire but never enters the digest.

    >>> p = SymbolicPlan.from_search([(0, 1), (2, 3)], (4,), (2,), 96.0)
    >>> SymbolicPlan.from_obj(p.to_obj()) == p
    True
    """

    pairs: tuple[tuple[int, int], ...]
    slice_legs: tuple[int, ...]
    slice_dims: tuple[int, ...]
    cost: float
    sliced_total: float = 0.0
    peak: float = 0.0
    provenance: Mapping = field(default_factory=dict)

    @classmethod
    def from_search(
        cls,
        pairs: Sequence[Sequence[int]],
        slice_legs: Sequence[int],
        slice_dims: Sequence[int],
        cost: float,
        sliced_total: float = 0.0,
        peak: float = 0.0,
        provenance: Mapping | None = None,
    ) -> "SymbolicPlan":
        """Normalize raw search output (lists, unsorted slice sets)
        into the canonical frozen form: the slice set is co-sorted by
        leg so equal plans compare and digest equal."""
        order = sorted(
            range(len(slice_legs)), key=lambda i: int(slice_legs[i])
        )
        return cls(
            pairs=tuple((int(a), int(b)) for a, b in pairs),
            slice_legs=tuple(int(slice_legs[i]) for i in order),
            slice_dims=tuple(int(slice_dims[i]) for i in order),
            cost=float(cost),
            sliced_total=float(sliced_total),
            peak=float(peak),
            provenance=dict(provenance or {}),
        )

    def digest(self) -> str:
        return plan_digest(self.pairs, self.slice_legs)

    @property
    def num_slices(self) -> int:
        n = 1
        for d in self.slice_dims:
            n *= d
        return n

    def slicing(self):
        """The plan's slice set as a
        :class:`~tnc_tpu.contractionpath.slicing.Slicing` (or None for
        an unsliced plan)."""
        if not self.slice_legs:
            return None
        from tnc_tpu.contractionpath.slicing import Slicing

        return Slicing(self.slice_legs, self.slice_dims)

    # -- wire format --------------------------------------------------------

    def to_obj(self) -> dict:
        return {
            "version": WIRE_VERSION,
            "digest": self.digest(),
            "pairs": [[a, b] for a, b in self.pairs],
            "slice_legs": list(self.slice_legs),
            "slice_dims": list(self.slice_dims),
            "cost": self.cost,
            "sliced_total": self.sliced_total,
            "peak": self.peak,
            "provenance": dict(self.provenance),
        }

    @classmethod
    def from_obj(cls, obj: Mapping) -> "SymbolicPlan":
        """Inverse of :meth:`to_obj`; raises ``ValueError`` when the
        wire record is structurally unusable or its recorded digest
        does not match the recomputed one (corruption, tampering, or a
        version drift — the caller drops the trial)."""
        if not isinstance(obj, Mapping) or obj.get("version") != WIRE_VERSION:
            raise ValueError(f"unusable symbolic plan record: {obj!r:.80}")
        plan = cls.from_search(
            obj["pairs"],
            obj["slice_legs"],
            obj["slice_dims"],
            obj["cost"],
            obj.get("sliced_total", 0.0),
            obj.get("peak", 0.0),
            obj.get("provenance"),
        )
        if obj.get("digest") != plan.digest():
            raise ValueError(
                "symbolic plan digest mismatch: recorded "
                f"{obj.get('digest')!r} != recomputed {plan.digest()!r}"
            )
        return plan

    # -- structural comparison ---------------------------------------------

    def subtree_keys(self) -> frozenset[frozenset[int]]:
        """The leaf set under every internal node — the tree's
        structural fingerprint set. Two plans share a subtree exactly
        when they contract the same leaves together (regardless of SSA
        numbering), which is what :func:`diff` counts."""
        n = len(self.pairs) + 1  # SSA: leaves 0..n-1, internals n..2n-2
        below: dict[int, frozenset[int]] = {
            i: frozenset((i,)) for i in range(n)
        }
        keys = []
        nxt = n
        for a, b in self.pairs:
            below[nxt] = below[a] | below[b]
            keys.append(below[nxt])
            nxt += 1
        return frozenset(keys)


@dataclass(frozen=True)
class PlanDiff:
    """Structural delta between two symbolic plans: subtree overlap
    (by leaf sets, SSA-numbering independent) and the slice-set delta.

    >>> a = SymbolicPlan.from_search([(0, 1), (4, 2), (5, 3)], (7,), (2,), 1.0)
    >>> b = SymbolicPlan.from_search([(0, 1), (2, 3), (4, 5)], (9,), (2,), 1.0)
    >>> d = diff(a, b)
    >>> (d.shared_subtrees, d.only_a, d.only_b)
    (2, 1, 1)
    >>> (d.slices_added, d.slices_dropped, d.identical)
    ((9,), (7,), False)
    """

    shared_subtrees: int
    only_a: int
    only_b: int
    slices_added: tuple[int, ...]  # in b, not a
    slices_dropped: tuple[int, ...]  # in a, not b

    @property
    def identical(self) -> bool:
        return (
            self.only_a == 0
            and self.only_b == 0
            and not self.slices_added
            and not self.slices_dropped
        )


def diff(a: SymbolicPlan, b: SymbolicPlan) -> PlanDiff:
    """Structural diff of two candidates — what a coordinator logs when
    a merge replaces the incumbent (how different is the winner?), and
    what trial-diversity audits read instead of eyeballing pair lists."""
    ka, kb = a.subtree_keys(), b.subtree_keys()
    sa, sb = set(a.slice_legs), set(b.slice_legs)
    return PlanDiff(
        shared_subtrees=len(ka & kb),
        only_a=len(ka - kb),
        only_b=len(kb - ka),
        slices_added=tuple(sorted(sb - sa)),
        slices_dropped=tuple(sorted(sa - sb)),
    )
