"""Contraction slicing: trade flops for peak memory.

The reference explicitly does not support slicing
(``book/src/parallelization.md`` "What about slicing?",
``book/src/future_work.md`` item 2) — it spreads memory across MPI nodes
instead. On TPU, HBM per chip is the binding constraint (16 GB on v5e), so
slicing is first-class here: selected *contracted* legs are fixed to an
index value, the contraction is executed once per index combination, and
the results are summed. Each slice is an identical-shape program — ideal
for XLA: one compiled executable, many cheap invocations (or a batched
axis).

The slice-leg selection is the standard greedy heuristic (as used by
cotengra's SliceFinder): repeatedly slice the leg that most reduces the
predicted peak intermediate size, until the peak fits the target.

Cost model: the executors hoist the slice-invariant stem — steps whose
operands depend on no sliced leg run once, not once per slice
(:mod:`tnc_tpu.ops.hoist`) — so candidate slice sets are scored by
``invariant_flops + num_slices * residual_flops`` rather than
``num_slices * total_flops`` (:class:`StemAccountant`,
:func:`hoisted_sliced_flops`). Leg selection therefore actively prefers
slicings that keep a large hoistable stem.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.tensornetwork.tensor import LeafTensor

__all__ = [
    "Slicing",
    "StemAccountant",
    "SlicedCostEvaluator",
    "find_slicing",
    "find_parallel_slicing",
    "flat_replace_path",
    "greedy_slice_to_target",
    "hoisted_sliced_flops",
    "joint_slice_search",
    "slice_and_reconfigure",
    "sliced_flops",
    "sliced_peak",
]


@dataclass(frozen=True)
class Slicing:
    """A set of sliced legs and their dimensions."""

    legs: tuple[int, ...]
    dims: tuple[int, ...]

    @property
    def num_slices(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def overhead(self) -> float:
        """Upper bound on the flops multiplier caused by slicing."""
        return float(self.num_slices)

    def to_obj(self) -> dict:
        """JSON-able form (plan serialization — the serving plan cache
        persists path + slicing as plain JSON, never pickle)."""
        return {"legs": list(self.legs), "dims": list(self.dims)}

    @classmethod
    def from_obj(cls, obj: dict) -> "Slicing":
        """Inverse of :meth:`to_obj`.

        >>> Slicing.from_obj(Slicing((3, 7), (2, 2)).to_obj())
        Slicing(legs=(3, 7), dims=(2, 2))
        """
        return cls(tuple(int(l) for l in obj["legs"]),
                   tuple(int(d) for d in obj["dims"]))


class _PyReplayer:
    """Python-backed replayer with the native interface, so call sites
    dispatch unconditionally (the two arms cannot diverge)."""

    def __init__(self, inputs, replace_path):
        self._inputs = inputs
        self._path = replace_path

    def sizes(self, removed):
        return _replay_sizes(self._inputs, self._path, removed)

    def flops(self, removed):
        return _reduced_flops(self._inputs, self._path, removed)

    def peak_and_flops(self, removed):
        peak, _ = _replay_sizes(self._inputs, self._path, removed)
        return peak, _reduced_flops(self._inputs, self._path, removed)

    def peak(self, removed):
        peak, _ = _replay_sizes(self._inputs, self._path, removed)
        return peak


def _make_replayer(inputs, replace_path):
    """Path replayer: native (``native/slicereplay.cpp``) when
    available, else the Python loops below (its oracle and fallback).

    Slicing-aware candidate scoring replays the path thousands of times
    per plan; pure Python here is ~96% of north-star planning time
    (profiled 231 s of 241 s; native cuts full planning ~2×)."""
    from tnc_tpu.partitioning.native_binding import SlicedReplayer

    r = SlicedReplayer(inputs, replace_path)
    return r if r.available else _PyReplayer(inputs, replace_path)


def _replay_sizes(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    removed: set[int],
) -> tuple[float, dict[int, float]]:
    """Peak step size of a flat replace path with ``removed`` legs sliced
    away, and per-leg 'presence in peak step' accounting.

    Returns (peak_size, leg -> largest step size that leg participates in).
    """
    tensors = [
        LeafTensor(
            [l for l in t.legs if l not in removed],
            [d for l, d in t.edges() if l not in removed],
        )
        for t in inputs
    ]
    peak = 0.0
    leg_peak: dict[int, float] = {}
    for i, j in replace_path:
        ti, tj = tensors[i], tensors[j]
        out = ti ^ tj
        step = out.size() + ti.size() + tj.size()
        peak = max(peak, step)
        for t in (ti, tj, out):
            for leg in t.legs:
                if step > leg_peak.get(leg, 0.0):
                    leg_peak[leg] = step
        tensors[i] = out
    return peak, leg_peak


class StemAccountant:
    """Hoist-aware flop accounting for candidate slice sets.

    One full-dims replay of the path precomputes, per step, its naive op
    cost and the set of legs contributed by the leaves in its subtree.
    A step is *variant* under a removal set R iff its contributed-leg
    set intersects R (a value computed from a sliced leaf stays
    per-slice even after the sliced leg is contracted away); invariant
    steps never touch a removed leg, so their cost is independent of R.
    ``invariant_flops(R)`` is then an O(steps) mask-and-sum per query —
    cheap enough for the planner's per-candidate scoring loops, on top
    of the (native) replayer's total-flops query.

    ``cost_model`` (a :class:`tnc_tpu.obs.calibrate.CalibratedCostModel`
    fitted from measured step spans) switches :meth:`hoisted_cost` from
    raw flop counts to predicted *seconds* — including the per-slice
    dispatch overhead raw op counts are blind to, so candidate scoring
    stops treating ever-deeper slicing as free (the plan → measure →
    replan loop).
    """

    def __init__(
        self,
        inputs: Sequence[LeafTensor],
        replace_path: Sequence[tuple[int, int]],
        cost_model=None,
    ):
        import numpy as np

        self._cost_model = cost_model

        tensors = [t.copy() for t in inputs]
        contrib: list[frozenset[int]] = [
            frozenset(t.legs) for t in inputs
        ]
        costs: list[float] = []
        step_legs: list[frozenset[int]] = []
        for i, j in replace_path:
            costs.append((tensors[i] | tensors[j]).size())
            merged = contrib[i] | contrib[j]
            step_legs.append(merged)
            tensors[i] = tensors[i] ^ tensors[j]
            contrib[i] = merged
        self._costs = np.asarray(costs, dtype=np.float64)
        self.total_flops = float(self._costs.sum())
        n = len(costs)
        self._leg_steps: dict[int, "np.ndarray"] = {}
        for idx, legs in enumerate(step_legs):
            for leg in legs:
                mask = self._leg_steps.get(leg)
                if mask is None:
                    mask = np.zeros(n, dtype=bool)
                    self._leg_steps[leg] = mask
                mask[idx] = True

    def _variant_mask(self, removed):
        """Boolean step mask (True = variant under ``removed``), or
        ``None`` when no removed leg touches any step."""
        variant = None
        for leg in removed:
            mask = self._leg_steps.get(leg)
            if mask is None:
                continue
            variant = mask.copy() if variant is None else (variant | mask)
        return variant

    def invariant_flops(self, removed) -> float:
        """Flops of the steps that stay slice-invariant with ``removed``
        legs sliced — paid once under hoisted execution."""
        variant = self._variant_mask(removed)
        if variant is None:
            return self.total_flops
        return float(self._costs[~variant].sum())

    def hoist_split(
        self, removed, per_slice_flops: float
    ) -> tuple[float, float]:
        """(invariant, per-slice residual) flops, mirroring the compiled
        hoist pass exactly: :func:`tnc_tpu.ops.hoist.
        hoist_sliced_program` degrades to a no-op — nothing cached,
        everything in the per-slice residual — when NO step is variant
        (1-slice plans: empty removal set) or when EVERY step is, and
        this accounting degrades identically. Keeping the two
        implementations in lockstep is what lets bench.py cross-check
        them without special-casing the 1-slice plan."""
        variant = self._variant_mask(removed)
        n_var = 0 if variant is None else int(variant.sum())
        if n_var == 0 or n_var == len(self._costs):
            return 0.0, per_slice_flops
        inv = float(self._costs[~variant].sum())
        return inv, max(per_slice_flops - inv, 0.0)

    def hoisted_cost(
        self, removed, per_slice_flops: float, num_slices: int
    ) -> float:
        """``invariant + num_slices * residual`` given the replayer's
        per-slice total ``per_slice_flops`` for the same removal set
        (split per :meth:`hoist_split`, so a removal set the hoist pass
        would no-op on is charged the full per-slice cost every slice).
        With a calibrated ``cost_model`` the same split is priced in
        predicted seconds (residual dispatches included) instead of raw
        flops — both are valid scoring keys (monotone in the work), so
        callers compare candidates without caring which one is active.
        """
        inv, residual = self.hoist_split(removed, per_slice_flops)
        if self._cost_model is not None:
            # the fitted dispatch overhead is per STEP: a slice runs
            # every variant step, the prelude every invariant one
            variant = self._variant_mask(removed)
            n = len(self._costs)
            n_var = 0 if variant is None else int(variant.sum())
            if n_var == 0 or n_var == n:  # no-op hoist: all steps loop
                n_var = n
            return self._cost_model.sliced_cost(
                inv,
                residual,
                num_slices,
                steps_per_slice=max(float(n_var), 1.0),
                prelude_steps=max(float(n - n_var), 1.0),
            )
        return inv + float(num_slices) * residual


def hoisted_sliced_flops(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    slicing: Slicing,
) -> tuple[float, float, float]:
    """(invariant_flops, per-slice residual_flops, hoisted total cost)
    of a sliced path under stem-hoisting execution. The naive executor
    pays ``num_slices * (invariant + residual)`` =
    :func:`sliced_flops`; the hoisted one ``invariant + num_slices *
    residual``. The split follows :meth:`StemAccountant.hoist_split`,
    so plans the compiled hoist pass no-ops on (1-slice plans, or
    all-variant step lists) report ``invariant == 0`` here too.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> ts = [LeafTensor.from_const([0, 1], 4), LeafTensor.from_const([1, 2], 4),
    ...       LeafTensor.from_const([2, 3], 4), LeafTensor.from_const([3, 0], 4)]
    >>> path = [(0, 3), (0, 1), (0, 2)]   # (0, 3) touches no sliced leg
    >>> s = Slicing((2,), (4,))
    >>> inv, res, total = hoisted_sliced_flops(ts, path, s)
    >>> inv > 0 and total < sliced_flops(ts, path, s)
    True
    >>> hoisted_sliced_flops(ts, path, Slicing((), ()))[0]  # 1-slice: no-op
    0.0
    """
    removed = set(slicing.legs)
    acct = StemAccountant(inputs, replace_path)
    per_slice = _make_replayer(inputs, replace_path).flops(removed)
    inv, residual = acct.hoist_split(removed, per_slice)
    return inv, residual, inv + slicing.num_slices * residual


@obs.traced("plan.find_slicing")
def find_slicing(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    target_size: float,
    max_slices: int = 1 << 24,
) -> Slicing:
    """Greedily pick legs to slice until the path's peak intermediate size
    (in elements, out+in1+in2 model) is at most ``target_size``.

    Only *closed* legs (absent from the final result) are sliceable.
    Raises if the target cannot be met within ``max_slices``.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> ts = [LeafTensor.from_const([0, 1], 4), LeafTensor.from_const([1, 2], 4),
    ...       LeafTensor.from_const([2, 0], 4)]   # closed triangle
    >>> s = find_slicing(ts, [(0, 1), (0, 2)], target_size=12)
    >>> s.num_slices >= 4 and len(s.legs) >= 1
    True
    """
    dims: dict[int, int] = {}
    open_legs: set[int] = set()
    for t in inputs:
        for leg, dim in t.edges():
            dims[leg] = dim
            if leg in open_legs:
                open_legs.discard(leg)
            else:
                open_legs.add(leg)

    removed: set[int] = set()
    num_slices = 1
    replayer = _make_replayer(inputs, replace_path)
    while True:
        peak, leg_peak = replayer.sizes(removed)
        if peak <= target_size:
            break
        # candidate legs: participate in the peak-sized steps, closed, unsliced
        candidates = [
            (size, dims[leg], leg)
            for leg, size in leg_peak.items()
            if leg not in removed and leg not in open_legs and dims[leg] > 1
        ]
        if not candidates:
            raise ValueError(
                f"No sliceable legs left but peak {peak:.3e} > target {target_size:.3e}"
            )
        # slice the leg participating in the largest step; among those,
        # prefer larger dims (fewer legs for the same memory reduction)
        candidates.sort(key=lambda c: (-c[0], -c[1], c[2]))
        _, dim, leg = candidates[0]
        removed.add(leg)
        num_slices *= dim
        if num_slices > max_slices:
            raise ValueError(
                f"Slicing needs more than {max_slices} slices to reach "
                f"target {target_size:.3e}"
            )

    ordered = sorted(removed)
    return Slicing(tuple(ordered), tuple(dims[l] for l in ordered))


def sliced_flops(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    slicing: Slicing,
) -> float:
    """Total naive op cost across all slices."""
    replayer = _make_replayer(inputs, replace_path)
    return replayer.flops(set(slicing.legs)) * slicing.num_slices


def sliced_peak(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    slicing: Slicing,
) -> float:
    """Peak step size (elements, out+in1+in2) of the path with
    ``slicing.legs`` removed — the memory the executor actually pays
    per slice.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> ts = [LeafTensor.from_const([0, 1], 4), LeafTensor.from_const([1, 2], 4),
    ...       LeafTensor.from_const([2, 0], 4)]
    >>> s = find_slicing(ts, [(0, 1), (0, 2)], target_size=12)
    >>> sliced_peak(ts, [(0, 1), (0, 2)], s) <= 12.0
    True
    """
    return _make_replayer(inputs, replace_path).peak(set(slicing.legs))


@obs.traced("plan.find_parallel_slicing")
def find_parallel_slicing(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    n_devices: int,
    target_size: float | None = None,
    max_extra_legs: int = 8,
    base: Slicing | None = None,
    cost_model=None,
) -> Slicing | None:
    """A slicing suitable for **slice-parallel** SPMD execution
    (:func:`tnc_tpu.parallel.distributed_sliced_contraction`): at least
    ``n_devices`` slices, count divisible by ``n_devices``, and — when
    ``target_size`` is given — peak intermediate size within it.

    Memory slicing picks legs by peak reduction (:func:`find_slicing`),
    or comes in as ``base`` (e.g. a :func:`slice_and_reconfigure`
    result to extend with divisibility legs only); the extra legs
    sliced purely for parallelism are picked to minimize the total
    sliced flops (the overhead the mesh must amortize).
    Returns ``None`` if no divisible slicing exists within
    ``max_extra_legs`` extra legs — the caller falls back to partition
    parallelism. ``cost_model`` (a measured
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`) scores the
    extra legs in predicted seconds — per-slice dispatch overhead
    included — instead of raw flops.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> ts = [LeafTensor.from_const([0, 1], 4), LeafTensor.from_const([1, 2], 4),
    ...       LeafTensor.from_const([2, 0], 4)]   # closed triangle
    >>> s = find_parallel_slicing(ts, [(0, 1), (0, 2)], 4)
    >>> s.num_slices % 4 == 0 and s.num_slices >= 4
    True
    """
    dims: dict[int, int] = {}
    open_legs: set[int] = set()
    for t in inputs:
        for leg, dim in t.edges():
            dims[leg] = dim
            if leg in open_legs:
                open_legs.discard(leg)
            else:
                open_legs.add(leg)

    if base is not None and target_size is not None:
        # precedence would be ambiguous: base was planned against its
        # own budget, and silently skipping the target check here would
        # void the docstring's peak guarantee
        raise ValueError("pass either base or target_size, not both")
    removed: set[int] = set(base.legs) if base is not None else set()
    if base is None and target_size is not None:
        removed = set(
            find_slicing(
                inputs, replace_path, target_size, max_slices=1 << 40
            ).legs
        )

    replayer = _make_replayer(inputs, replace_path)
    acct: StemAccountant | None = None  # built lazily (first extra leg)

    def count(legs: set[int]) -> int:
        n = 1
        for leg in legs:
            n *= dims[leg]
        return n

    extra = 0
    while not (
        count(removed) >= n_devices and count(removed) % n_devices == 0
    ):
        if extra >= max_extra_legs:
            return None
        candidates = [
            leg
            for leg in dims
            if leg not in removed and leg not in open_legs and dims[leg] > 1
        ]
        if not candidates:
            return None
        # minimize total sliced flops under hoisted execution
        # (invariant stem paid once, residual per slice) after adding
        # the leg
        if acct is None:
            acct = StemAccountant(inputs, replace_path, cost_model=cost_model)
        best = min(
            candidates,
            key=lambda leg: (
                acct.hoisted_cost(
                    removed | {leg},
                    replayer.flops(removed | {leg}),
                    count(removed | {leg}),
                ),
                leg,
            ),
        )
        removed.add(best)
        extra += 1

    ordered = sorted(removed)
    return Slicing(tuple(ordered), tuple(dims[l] for l in ordered))


def flat_replace_path(path_: ContractionPath) -> list[tuple[int, int]]:
    """Toplevel of a simple replace path (slicing operates on flat paths)."""
    if path_.nested:
        raise ValueError("Slicing expects a flat (non-nested) path")
    return list(path_.toplevel)


@obs.traced("plan.slice_and_reconfigure")
def slice_and_reconfigure(
    inputs: Sequence[LeafTensor],
    ssa_path: Sequence[tuple[int, int]],
    target_size: float,
    subtree_size: int = 12,
    reconf_rounds: int = 1,
    final_rounds: int = 8,
    step_budget: float | None = 4.0,
    final_budget: float | None = 45.0,
    max_slices: int = 1 << 26,
    max_leg_candidates: int = 48,
    cost_model=None,
    seed_slices: "Sequence[int] | Slicing | None" = None,
) -> tuple[list[tuple[int, int]], Slicing]:
    """Interleaved slicing + subtree reconfiguration (cotengra's
    ``slicing_reconf`` approach): repeatedly slice a leg of the peak
    step, then repair the flops overhead by re-solving subtrees *in the
    sliced size model* (sliced legs have dim 1).

    Leg selection replays the path once per candidate leg of the peak
    step and picks the (post-slice peak, post-slice flops) minimum —
    pure step-size heuristics pick legs that shrink one wide step while
    a plateau of equally wide steps with different legs survives.

    The repair passes run *uncapped*: flops minimization in the reduced
    model naturally deflates wide intermediates (they dominate the op
    count), while a hard size cap would forbid the DP from touching
    exactly the near-peak subtrees it must repair. The outer loop keeps
    slicing until the genuine replayed peak meets the target, and the
    final deep pass is accepted only if it preserves that bound.

    Returns (replace_path, slicing); the path is valid for the unsliced
    network (slicing only pins index values, it never reorders legs).

    ``cost_model`` (a measured
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`) switches leg
    scoring from hoisted flop counts to predicted seconds, charging
    each extra slice its real dispatch overhead.

    ``seed_slices`` (legs, or a :class:`Slicing`) warm-starts the
    removal set — the joint hyper search hands its winning slice set
    over so this pass degrades to a thin repair (one reconfigure over
    the pre-reduced model, usually zero candidate-leg searches), and a
    cached plan's slice set warm-starts replanning the same structure.
    Invalid seeds (open legs, dim 1, unknown) are skipped; the loop
    still extends the set when the seeded peak misses the target.
    """
    from tnc_tpu.contractionpath.contraction_path import (
        ContractionPath,
        ssa_replace_ordering,
    )
    from tnc_tpu.contractionpath.contraction_tree import ContractionTree

    tree = ContractionTree.from_ssa_path(inputs, list(ssa_path))
    tree.dims = dict(tree.dims)  # private copy: sliced legs become dim 1

    open_legs: set[int] = set()
    for t in inputs:
        for leg in t.legs:
            open_legs.symmetric_difference_update((leg,))

    dims: dict[int, int] = {}
    for t in inputs:
        for leg, dim in t.edges():
            dims[leg] = dim

    removed: set[int] = set()
    num_slices = 1
    # Seeds restrict the candidate pool, they don't bypass the loop:
    # each round scores only the remaining seed legs (instead of up to
    # max_leg_candidates peak-step legs) with the SAME (peak, hoisted
    # cost) key and the same interleaved repair cadence. Seeding with a
    # cold run's own slice set on the same path therefore replays that
    # run's trajectory — never worse at equal rounds — while skipping
    # most of its candidate-replay cost; once the pool is exhausted the
    # normal search resumes for any legs the seed missed.
    seed_pool: set[int] = set()
    if seed_slices is not None:
        seed_legs = (
            seed_slices.legs
            if isinstance(seed_slices, Slicing)
            else seed_slices
        )
        seed_pool = {
            leg
            for leg in seed_legs
            if leg in dims and leg not in open_legs and dims[leg] > 1
        }
    while True:
        replace = ssa_replace_ordering(
            ContractionPath.simple(tree.to_ssa_path())
        ).toplevel
        # the path changes every round (reconfigure), so the replayer is
        # rebuilt per round and reused across the ~48 candidate trials
        replayer = _make_replayer(inputs, replace)
        peak, leg_peak = replayer.sizes(removed)
        if peak <= target_size:
            break
        # ascending leg id: both replayer arms then see the same
        # candidate order, so truncation and exact-tie '<' picks cannot
        # diverge between native and Python-fallback machines (this is
        # the order the native leg_peak already iterates in, preserving
        # the canonical prewarmed plan)
        seed_pool -= removed
        if seed_pool:
            candidates = sorted(seed_pool)
        else:
            candidates = sorted(
                leg
                for leg, size in leg_peak.items()
                if size >= peak * 0.99
                and leg not in removed
                and leg not in open_legs
                and dims[leg] > 1
            )
        if not candidates:
            # no sliceable leg in the peak step: fall back to any leg
            candidates = sorted(
                leg
                for leg in leg_peak
                if leg not in removed and leg not in open_legs and dims[leg] > 1
            )
        if not candidates:
            raise ValueError(
                f"No sliceable legs left but peak {peak:.3e} > "
                f"target {target_size:.3e}"
            )
        # score candidates by (post-slice peak, hoisted total cost):
        # the executors run the slice-invariant stem once, so a trial's
        # flops component is invariant + num_slices * residual, which
        # prefers legs that keep a large hoistable stem over legs that
        # drag the whole program into the per-slice loop
        acct = StemAccountant(inputs, replace, cost_model=cost_model)
        best_leg = -1
        best_key: tuple[float, float] | None = None
        for leg in candidates[:max_leg_candidates]:
            trial = removed | {leg}
            trial_peak, trial_flops = replayer.peak_and_flops(trial)
            key = (
                trial_peak,
                acct.hoisted_cost(
                    trial, trial_flops, num_slices * dims[leg]
                ),
            )
            if best_key is None or key < best_key:
                best_key = key
                best_leg = leg
        leg = best_leg
        removed.add(leg)
        num_slices *= dims[leg]
        if num_slices > max_slices:
            raise ValueError(
                f"Slicing needs more than {max_slices} slices to reach "
                f"target {target_size:.3e}"
            )
        tree.dims[leg] = 1
        if reconf_rounds > 0:
            tree.reconfigure(
                subtree_size, reconf_rounds, time_budget=step_budget
            )

    if final_rounds > 0 and removed:
        # Deep repair on a copy; keep it only if the peak bound survives.
        refined = tree.copy()
        refined.reconfigure(subtree_size, final_rounds, time_budget=final_budget)
        refined_replace = ssa_replace_ordering(
            ContractionPath.simple(refined.to_ssa_path())
        ).toplevel
        refined_peak = _make_replayer(inputs, refined_replace).peak(removed)
        if refined_peak <= target_size:
            tree = refined

    replace = ssa_replace_ordering(
        ContractionPath.simple(tree.to_ssa_path())
    ).toplevel
    ordered = sorted(removed)
    return list(replace), Slicing(
        tuple(ordered), tuple(dims[l] for l in ordered)
    )


# The incremental sliced-cost evaluator and the joint tree+slice search
# live in their own module but belong to this layer's public surface:
# the evaluator answers the same questions as the replay oracles above
# (pinned bitwise-equal) with O(affected-steps) delta updates, cheap
# enough to run inside every search loop instead of once per finalist.
from tnc_tpu.contractionpath.sliced_cost import (  # noqa: E402
    SlicedCostEvaluator,
    greedy_slice_to_target,
    joint_slice_search,
)


def _reduced_flops(
    inputs: Sequence[LeafTensor],
    replace_path: Sequence[tuple[int, int]],
    removed: set[int],
) -> float:
    """Per-slice naive op cost of a replace path with ``removed`` legs
    pinned (helper for slice-leg scoring)."""
    tensors = [
        LeafTensor(
            [l for l in t.legs if l not in removed],
            [d for l, d in t.edges() if l not in removed],
        )
        for t in inputs
    ]
    total = 0.0
    for i, j in replace_path:
        total += (tensors[i] | tensors[j]).size()
        tensors[i] = tensors[i] ^ tensors[j]
    return total
