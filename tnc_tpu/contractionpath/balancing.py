"""Greedy iterative partition balancing by contraction-tree surgery.

Mirror of ``tnc/src/contractionpath/contraction_tree/balancing.rs`` (the
``balance_partitions_iter`` entry point, ``:98-210``; node shifting
``:517-613``) and its scheme catalogue
(``balancing/balancing_schemes.rs:83-613``): each iteration picks a
donor/receiver pair of partition subtrees, selects the leaf *or
intermediate* node whose move maximizes the objective, detaches that
node's leaves from the donor subtree, re-runs Greedy on both touched
partitions, rebuilds their subtrees in the tree, re-schedules the fan-in
with a :class:`CommunicationScheme`, and scores the critical path.

The tree here is a **forest of partition subtrees** over persistent leaf
nodes (leaf node ids survive rebuilds, internal nodes are replaced —
exactly the reference's ``remove_subtree`` + ``add_path_as_subtree``
behavior, ``contraction_tree.rs:160-222``). The fan-in levels above the
partition roots are represented as the communication path itself rather
than as tree nodes; the reference rebuilds those nodes every iteration
anyway (``replace_communication_path``, ``contraction_tree.rs:234-258``).
Divergence from the reference (deliberate): the returned path's toplevel
is the *recomputed* communication path of the best iteration — the
reference returns the original toplevel while scoring with the new one
(``balancing.rs:192-196``).

Schemes (``balancing_schemes.rs:12-68``):

- ``BEST_WORST`` — best leaf of the costliest subtree vs leaves of the
  cheapest subtree.
- ``TENSOR`` — best leaf of the costliest subtree vs *all nodes* of every
  other subtree (receiver chosen by objective).
- ``TENSORS`` — the ``TENSOR`` shift, plus the symmetric shift into the
  cheapest subtree from the best middle donor.
- ``ALTERNATING_TENSORS`` — odd iterations: leaf out of the costliest
  subtree (receiver = externals only); even: leaf into the cheapest.
- ``INTERMEDIATE_TENSORS`` — like ``TENSORS`` but donor candidates are
  height-limited *intermediate* nodes: whole subtrees move at once.
- ``ALTERNATING_INTERMEDIATE_TENSORS`` — odd/even halves of the above.
- ``ALTERNATING_TREE_TENSORS`` — intermediate moves scored against the
  receiver's external only, with a required positive objective.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field
from typing import Callable, Sequence

from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_cost import (
    communication_path_op_costs,
)
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor

logger = logging.getLogger(__name__)


class BalancingScheme:
    """Scheme tags; the intermediate schemes honor ``height_limit``."""

    BEST_WORST = "best_worst"
    TENSOR = "tensor"
    TENSORS = "tensors"
    ALTERNATING_TENSORS = "alternating_tensors"
    INTERMEDIATE_TENSORS = "intermediate_tensors"
    ALTERNATING_INTERMEDIATE_TENSORS = "alternating_intermediate_tensors"
    ALTERNATING_TREE_TENSORS = "alternating_tree_tensors"


def _default_objective(shifted: LeafTensor, target: LeafTensor) -> float:
    """Memory-reduction objective, maximized
    (``benchmark/src/main.rs:689-691``): how much total size shrinks when
    ``shifted`` merges into ``target``."""
    return shifted.size() + target.size() - (shifted ^ target).size()


@dataclass
class BalanceSettings:
    """Mirror of ``BalanceSettings`` (``balancing.rs:27-86``)."""

    iterations: int = 20
    scheme: str = BalancingScheme.BEST_WORST
    height_limit: int | None = 4  # for intermediate-subtree schemes
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    # Peak memory bound in ELEMENTS over the fan-in of partition externals
    # (the reference compares ``communication_path_op_costs``'s mem_cost
    # and stops balancing when exceeded, ``balancing.rs:198-200``)
    memory_limit: float | None = None
    objective: Callable[[LeafTensor, LeafTensor], float] = field(
        default=_default_objective
    )
    weighted_random_top: int | None = None  # pick randomly among top-N moves
    # a CalibratedCostModel: fan-in latencies and the iteration score
    # move to predicted seconds (dispatch overhead per local step)
    cost_model: object | None = None


# ---------------------------------------------------------------------------
# Partition forest


@dataclass
class _BNode:
    id: int
    left: int = -1
    right: int = -1
    parent: int = -1
    legs: frozenset = frozenset()
    leaf_index: int | None = None  # global tensor index for leaves

    @property
    def is_leaf(self) -> bool:
        return self.left < 0


class _PartitionForest:
    """One binary subtree per partition over persistent leaf nodes.

    Leaf node ids survive subtree rebuilds; internal node ids are fresh
    per rebuild (``contraction_tree.rs:160-222`` semantics).
    """

    def __init__(self, tensor: CompositeTensor):
        self.tensor = tensor
        self.nodes: dict[int, _BNode] = {}
        self._next_id = 0
        # leaf node id per global tensor index
        self.leaf_of: list[int] = []
        for g, t in enumerate(tensor.tensors):
            node = _BNode(
                id=self._fresh(), legs=frozenset(t.legs), leaf_index=g
            )
            self.nodes[node.id] = node
            self.leaf_of.append(node.id)

    def _fresh(self) -> int:
        self._next_id += 1
        return self._next_id - 1

    def build_subtree(
        self, leaf_node_ids: Sequence[int], local_path: Sequence[tuple[int, int]]
    ) -> int:
        """Create internal nodes for ``local_path`` (replace-path over the
        positions of ``leaf_node_ids``); returns the subtree root id."""
        if not leaf_node_ids:
            raise ValueError("cannot build a subtree over zero leaves")
        slots = list(leaf_node_ids)
        for nid in slots:
            self.nodes[nid].parent = -1
        for a, b in local_path:
            na, nb = slots[a], slots[b]
            node = _BNode(
                id=self._fresh(),
                left=na,
                right=nb,
                legs=self.nodes[na].legs ^ self.nodes[nb].legs,
            )
            self.nodes[node.id] = node
            self.nodes[na].parent = node.id
            self.nodes[nb].parent = node.id
            slots[a] = node.id
        # replace-path: the result replaces the last pair's left slot
        return slots[local_path[-1][0]] if local_path else slots[0]

    def remove_internal(self, root: int) -> None:
        """Drop the internal nodes of ``root``'s subtree, keep leaves."""
        stack = [root]
        while stack:
            i = stack.pop()
            nd = self.nodes[i]
            if nd.is_leaf:
                nd.parent = -1
                continue
            stack.append(nd.left)
            stack.append(nd.right)
            del self.nodes[i]

    def leaf_ids(self, node_id: int) -> list[int]:
        out: list[int] = []
        stack = [node_id]
        while stack:
            i = stack.pop()
            nd = self.nodes[i]
            if nd.is_leaf:
                out.append(i)
            else:
                stack.append(nd.right)
                stack.append(nd.left)
        out.reverse()
        return out

    def node_tensor(self, node_id: int) -> LeafTensor:
        """The (symbolic) tensor a node represents, from its legs."""
        nd = self.nodes[node_id]
        if nd.is_leaf:
            return self.tensor.tensors[nd.leaf_index]
        out = LeafTensor()
        for lid in self.leaf_ids(node_id):
            out = out ^ self.tensor.tensors[self.nodes[lid].leaf_index]
        return out

    def leaf_node_tensor_map(self, root: int) -> dict[int, LeafTensor]:
        """``populate_leaf_node_tensor_map``
        (``contraction_tree.rs:476-489``)."""
        return {
            lid: self.tensor.tensors[self.nodes[lid].leaf_index]
            for lid in self.leaf_ids(root)
        }

    def subtree_tensor_map(
        self, root: int, height_limit: int | None
    ) -> dict[int, LeafTensor]:
        """All leaf + intermediate node tensors of ``root``'s subtree, an
        intermediate included only when both children's heights are below
        ``height_limit`` (``contraction_tree.rs:393-465``)."""
        out: dict[int, LeafTensor] = {}

        def walk(i: int) -> tuple[LeafTensor, int]:
            nd = self.nodes[i]
            if nd.is_leaf:
                t = self.tensor.tensors[nd.leaf_index]
                out[i] = t
                return t, 0
            t1, h1 = walk(nd.left)
            t2, h2 = walk(nd.right)
            t12 = t1 ^ t2
            if height_limit is None or (h1 < height_limit and h2 < height_limit):
                out[i] = t12
            return t12, max(h1, h2) + 1

        walk(root)
        return out


@dataclass
class _PartitionData:
    """Per-partition bookkeeping (``balancing.rs:88-96``)."""

    id: int  # subtree root node id
    flop_cost: float
    mem_cost: float
    contraction: list[tuple[int, int]]  # local replace path over `leaves`
    local_tensor: LeafTensor  # external tensor of the partition
    # leaf node ids in the exact order `contraction` was built over —
    # tree-traversal order is a different permutation, so the path must
    # always be paired with this list
    leaves: list[int] = field(default_factory=list)


@dataclass
class _Shift:
    """A move of leaves between subtrees (``balancing_schemes.rs:72-80``)."""

    from_subtree_id: int
    to_subtree_id: int
    moved_leaf_ids: list[int]


# ---------------------------------------------------------------------------
# Node selection


def _find_rebalance_node(
    rng: random.Random | None,
    weighted_random_top: int | None,
    larger_nodes: dict[int, LeafTensor],
    smaller_nodes: dict[int, LeafTensor],
    objective: Callable[[LeafTensor, LeafTensor], float],
) -> tuple[int, float]:
    """Best-objective node of ``larger_nodes`` against any of
    ``smaller_nodes`` (``balancing.rs:482-513``); optionally a weighted
    random pick among the top-N."""
    comparisons = [
        (larger_id, objective(larger_tensor, smaller_tensor))
        for larger_id, larger_tensor in larger_nodes.items()
        for smaller_tensor in smaller_nodes.values()
    ]
    if weighted_random_top and rng is not None:
        options = sorted(comparisons, key=lambda c: -c[1])[:weighted_random_top]
        top = options[0][1]
        if top <= 0:
            return options[0]
        weights = [max(c[1] / top, 0.0) for c in options]
        total = sum(weights)
        pick = rng.random() * total
        acc = 0.0
        for option, w in zip(options, weights):
            acc += w
            if pick <= acc:
                return option
        return options[-1]
    return max(comparisons, key=lambda c: c[1])


# ---------------------------------------------------------------------------
# The ten scheme functions (``balancing_schemes.rs:83-613``).
# ``partition_data`` is sorted ascending by flop cost on entry: first =
# cheapest ("smaller"), last = costliest ("larger").


def _best_worst(data, forest, settings, rng) -> list[_Shift]:
    larger = data[-1].id
    smaller = data[0].id
    node, _ = _find_rebalance_node(
        rng,
        settings.weighted_random_top,
        forest.leaf_node_tensor_map(larger),
        forest.leaf_node_tensor_map(smaller),
        settings.objective,
    )
    return [_Shift(larger, smaller, forest.leaf_ids(node))]


def _best_receiver(data, forest, settings, rng, donor_id, donor_nodes):
    """Scan receivers (all but the donor): receiver subtree scored with
    its full node map; returns (receiver_id, node, objective)."""
    best = None
    for part in data:
        if part.id == donor_id:
            continue
        receiver_nodes = forest.subtree_tensor_map(part.id, None)
        node, obj = _find_rebalance_node(
            rng,
            settings.weighted_random_top,
            donor_nodes,
            receiver_nodes,
            settings.objective,
        )
        if best is None or obj > best[2]:
            best = (part.id, node, obj)
    return best


def _best_tensor(data, forest, settings, rng) -> list[_Shift]:
    larger = data[-1].id
    donor_nodes = forest.leaf_node_tensor_map(larger)
    best = _best_receiver(data[:-1], forest, settings, rng, larger, donor_nodes)
    if best is None:
        return []
    receiver, node, _ = best
    return [_Shift(larger, receiver, forest.leaf_ids(node))]


def _best_donor_into(data, forest, settings, rng, receiver_id, receiver_nodes, donor_map):
    """Scan donors (all but the receiver): returns (donor_id, node, obj).
    ``donor_map(part)`` yields the donor's candidate node map."""
    best = None
    for part in data:
        if part.id == receiver_id:
            continue
        donor_nodes = donor_map(part)
        if not donor_nodes:
            continue
        node, obj = _find_rebalance_node(
            rng,
            settings.weighted_random_top,
            donor_nodes,
            receiver_nodes,
            settings.objective,
        )
        if best is None or obj > best[2]:
            best = (part.id, node, obj)
    return best


def _best_tensors(data, forest, settings, rng) -> list[_Shift]:
    shifts = _best_tensor(data, forest, settings, rng)
    smaller = data[0].id
    receiver_nodes = forest.subtree_tensor_map(smaller, None)
    best = _best_donor_into(
        data[1:-1],
        forest,
        settings,
        rng,
        smaller,
        receiver_nodes,
        lambda part: forest.leaf_node_tensor_map(part.id),
    )
    if best is not None:
        donor, node, _ = best
        shifts.append(_Shift(donor, smaller, forest.leaf_ids(node)))
    return shifts


def _tensors_odd(data, forest, settings, rng) -> list[_Shift]:
    larger = data[-1].id
    donor_nodes = forest.leaf_node_tensor_map(larger)
    best = None
    for part in data[:-1]:
        node, obj = _find_rebalance_node(
            rng,
            settings.weighted_random_top,
            donor_nodes,
            {0: part.local_tensor},
            settings.objective,
        )
        if best is None or obj > best[2]:
            best = (part.id, node, obj)
    if best is None:
        return []
    receiver, node, _ = best
    return [_Shift(larger, receiver, forest.leaf_ids(node))]


def _tensors_even(data, forest, settings, rng) -> list[_Shift]:
    smaller = data[0]
    receiver_nodes = {0: smaller.local_tensor}
    best = _best_donor_into(
        data[1:],
        forest,
        settings,
        rng,
        smaller.id,
        receiver_nodes,
        lambda part: forest.leaf_node_tensor_map(part.id),
    )
    if best is None:
        return []
    donor, node, _ = best
    return [_Shift(donor, smaller.id, forest.leaf_ids(node))]


def _intermediate_donor_nodes(forest, root, height_limit):
    nodes = forest.subtree_tensor_map(root, height_limit)
    nodes.pop(root, None)  # never move the whole partition
    return nodes


def _best_intermediate_tensors(data, forest, settings, rng) -> list[_Shift]:
    shifts = _intermediate_tensors_odd(data, forest, settings, rng)
    smaller = data[0].id
    receiver_nodes = forest.subtree_tensor_map(smaller, None)
    best = _best_donor_into(
        data[1:-1],
        forest,
        settings,
        rng,
        smaller,
        receiver_nodes,
        lambda part: _intermediate_donor_nodes(
            forest, part.id, settings.height_limit
        ),
    )
    if best is not None:
        donor, node, _ = best
        shifts.append(_Shift(donor, smaller, forest.leaf_ids(node)))
    return shifts


def _intermediate_tensors_odd(data, forest, settings, rng) -> list[_Shift]:
    larger = data[-1].id
    donor_nodes = _intermediate_donor_nodes(forest, larger, settings.height_limit)
    if not donor_nodes:
        return []
    best = _best_receiver(data[:-1], forest, settings, rng, larger, donor_nodes)
    if best is None:
        return []
    receiver, node, _ = best
    return [_Shift(larger, receiver, forest.leaf_ids(node))]


def _intermediate_tensors_even(data, forest, settings, rng) -> list[_Shift]:
    smaller = data[0].id
    receiver_nodes = forest.subtree_tensor_map(smaller, None)
    best = _best_donor_into(
        data[1:],
        forest,
        settings,
        rng,
        smaller,
        receiver_nodes,
        lambda part: _intermediate_donor_nodes(
            forest, part.id, settings.height_limit
        ),
    )
    if best is None:
        return []
    donor, node, _ = best
    return [_Shift(donor, smaller, forest.leaf_ids(node))]


def _tree_tensors_odd(data, forest, settings, rng) -> list[_Shift]:
    """Intermediate move vs receiver externals; requires objective > 0
    (``balancing_schemes.rs:496-546``)."""
    larger = data[-1].id
    donor_nodes = _intermediate_donor_nodes(forest, larger, settings.height_limit)
    if not donor_nodes:
        return []
    best = None
    for part in data[:-1]:
        node = None
        objective = 0.0
        for node_id, node_tensor in donor_nodes.items():
            obj = settings.objective(node_tensor, part.local_tensor)
            if obj > objective:
                objective = obj
                node = node_id
        if node is not None and (best is None or objective > best[2]):
            best = (part.id, node, objective)
    if best is None:
        return []
    receiver, node, _ = best
    return [_Shift(larger, receiver, forest.leaf_ids(node))]


def _tree_tensors_even(data, forest, settings, rng) -> list[_Shift]:
    smaller = data[0]
    best = None
    for part in data[1:]:
        donor_nodes = _intermediate_donor_nodes(
            forest, part.id, settings.height_limit
        )
        if not donor_nodes:
            continue
        node = None
        objective = 0.0
        for node_id, node_tensor in donor_nodes.items():
            obj = settings.objective(node_tensor, smaller.local_tensor)
            if obj > objective:
                objective = obj
                node = node_id
        if node is not None and (best is None or objective > best[2]):
            best = (part.id, node, objective)
    if best is None:
        return []
    donor, node, _ = best
    return [_Shift(donor, smaller.id, forest.leaf_ids(node))]


def _scheme_shifts(data, forest, settings, rng, iteration) -> list[_Shift]:
    """Dispatch (``balancing.rs:258-367``): data sorted ascending by
    flop cost; alternating schemes switch on iteration parity."""
    scheme = settings.scheme
    odd = iteration % 2 == 1
    if scheme == BalancingScheme.BEST_WORST:
        return _best_worst(data, forest, settings, rng)
    if scheme == BalancingScheme.TENSOR:
        return _best_tensor(data, forest, settings, rng)
    if scheme == BalancingScheme.TENSORS:
        return _best_tensors(data, forest, settings, rng)
    if scheme == BalancingScheme.ALTERNATING_TENSORS:
        return (
            _tensors_odd(data, forest, settings, rng)
            if odd
            else _tensors_even(data, forest, settings, rng)
        )
    if scheme == BalancingScheme.INTERMEDIATE_TENSORS:
        return _best_intermediate_tensors(data, forest, settings, rng)
    if scheme == BalancingScheme.ALTERNATING_INTERMEDIATE_TENSORS:
        return (
            _intermediate_tensors_odd(data, forest, settings, rng)
            if odd
            else _intermediate_tensors_even(data, forest, settings, rng)
        )
    if scheme == BalancingScheme.ALTERNATING_TREE_TENSORS:
        return (
            _tree_tensors_odd(data, forest, settings, rng)
            if odd
            else _tree_tensors_even(data, forest, settings, rng)
        )
    raise ValueError(f"unknown balancing scheme {scheme!r}")


# ---------------------------------------------------------------------------
# Shift application


def _apply_shift(
    forest: _PartitionForest, shift: _Shift
) -> tuple[_PartitionData, _PartitionData]:
    """``shift_node_between_subtrees`` (``balancing.rs:517-613``): move
    leaves, re-Greedy both partitions, rebuild both subtrees. Returns the
    new (donor, receiver) partition data."""
    donor_leaves = forest.leaf_ids(shift.from_subtree_id)
    receiver_leaves = forest.leaf_ids(shift.to_subtree_id)
    moved = set(shift.moved_leaf_ids)
    assert moved and moved.issubset(set(donor_leaves))
    assert not moved & set(receiver_leaves)
    donor_leaves = [l for l in donor_leaves if l not in moved]
    receiver_leaves = receiver_leaves + shift.moved_leaf_ids
    if not donor_leaves:
        raise ValueError("shift would empty the donor partition")

    forest.remove_internal(shift.from_subtree_id)
    forest.remove_internal(shift.to_subtree_id)

    out = []
    for leaves in (donor_leaves, receiver_leaves):
        tensors = [
            forest.tensor.tensors[forest.nodes[l].leaf_index] for l in leaves
        ]
        if len(tensors) > 1:
            result = Greedy(OptMethod.GREEDY).find_path(
                CompositeTensor(tensors)
            )
            local = list(result.replace_path().toplevel)
            flops, mem = result.flops, result.size
            root = forest.build_subtree(leaves, local)
        else:
            local, flops, mem = [], 0.0, tensors[0].size()
            root = leaves[0]
            forest.nodes[root].parent = -1
        external = LeafTensor()
        for t in tensors:
            external = external ^ t
        out.append(
            _PartitionData(root, flops, mem, local, external, list(leaves))
        )
    return out[0], out[1]


# ---------------------------------------------------------------------------
# Main loop


def balance_partitions_iter(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    settings: BalanceSettings | None = None,
    rng: random.Random | None = None,
) -> tuple[int, CompositeTensor, ContractionPath, list[float]]:
    """Iteratively rebalance ``partitioning``; returns
    (best iteration, best partitioned network, best path, cost history)
    (``balancing.rs:98-210``).

    >>> import random
    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [2, 2]),
    ...     LeafTensor([1, 2], [2, 2]), LeafTensor([2, 3], [2, 2]),
    ...     LeafTensor([3, 0], [2, 2])])
    >>> it, ptn, path, history = balance_partitions_iter(
    ...     tn, [0, 0, 0, 1], BalanceSettings(iterations=3),
    ...     random.Random(0))
    >>> len(ptn) >= 1 and len(history) >= 1
    True
    """
    settings = settings or BalanceSettings()
    rng = rng or random.Random(42)

    forest = _PartitionForest(tensor)
    blocks: dict[int, list[int]] = {}
    for g, b in enumerate(partitioning):
        blocks.setdefault(b, []).append(g)
    if len(blocks) < 2:
        raise ValueError("balancing needs at least two partitions")

    data: list[_PartitionData] = []
    for b in sorted(blocks):
        leaves = [forest.leaf_of[g] for g in blocks[b]]
        part = _characterize_from_leaves(forest, leaves)
        data.append(part)

    def score(current: list[_PartitionData]) -> tuple[float, list[tuple[int, int]], float]:
        children = [p.local_tensor for p in current]
        latency = {i: p.flop_cost for i, p in enumerate(current)}
        fanin_cost = None
        if settings.cost_model is not None:
            from tnc_tpu.contractionpath.communication_schemes import (
                calibrated_latency_map,
            )
            from tnc_tpu.contractionpath.contraction_cost import (
                CalibratedObjective,
            )

            latency = calibrated_latency_map(
                latency,
                settings.cost_model,
                {i: float(len(p.contraction)) for i, p in enumerate(current)},
            )
            fanin_cost = CalibratedObjective(settings.cost_model).pair_cost
        communication_path = settings.communication_scheme.communication_path(
            children, latency, rng, cost_model=settings.cost_model
        )
        costs = [latency[i] for i in range(len(current))]
        (parallel, _), mem = communication_path_op_costs(
            children, communication_path, True, costs,
            cost_function=fanin_cost,
        )
        return parallel, communication_path, mem

    def snapshot(current: list[_PartitionData], communication_path):
        # p.contraction was built over p.leaves order — never re-derive
        # the order from the tree (traversal order is a different
        # permutation of the same leaf set).
        ordered = []
        nested: dict[int, ContractionPath] = {}
        for i, p in enumerate(current):
            tensors = [
                forest.tensor.tensors[forest.nodes[l].leaf_index]
                for l in p.leaves
            ]
            ordered.append(CompositeTensor(tensors))
            nested[i] = ContractionPath.simple(list(p.contraction))
        return CompositeTensor(ordered), ContractionPath(
            nested, list(communication_path)
        )

    cost, communication_path, _ = score(data)
    history = [cost]
    best_cost = cost
    best_iteration = 0
    best_tn, best_path = snapshot(data, communication_path)

    for iteration in range(1, settings.iterations + 1):
        data.sort(key=lambda p: p.flop_cost)
        logger.debug(
            "balancing iteration %d scheme=%s donor_cost=%.3e",
            iteration,
            settings.scheme,
            data[-1].flop_cost,
        )
        shifts = _scheme_shifts(data, forest, settings, rng, iteration)
        if not shifts:
            break
        id_remap: dict[int, int] = {}
        applied = False
        for shift in shifts:
            from_id = id_remap.get(shift.from_subtree_id, shift.from_subtree_id)
            to_id = id_remap.get(shift.to_subtree_id, shift.to_subtree_id)
            if from_id == to_id:
                continue
            shift = _Shift(from_id, to_id, shift.moved_leaf_ids)
            donor_leaves = set(forest.leaf_ids(from_id))
            if not set(shift.moved_leaf_ids).issubset(donor_leaves):
                continue  # an earlier shift in this round moved these leaves
            if len(shift.moved_leaf_ids) >= len(donor_leaves):
                continue  # would empty the donor
            new_donor, new_receiver = _apply_shift(forest, shift)
            id_remap[shift.from_subtree_id] = new_donor.id
            id_remap[shift.to_subtree_id] = new_receiver.id
            for k, p in enumerate(data):
                if p.id == from_id:
                    data[k] = new_donor
                elif p.id == to_id:
                    data[k] = new_receiver
            applied = True
        if not applied:
            break

        data.sort(key=lambda p: p.flop_cost)
        cost, communication_path, mem = score(data)
        history.append(cost)
        if settings.memory_limit is not None and mem > settings.memory_limit:
            break
        if cost < best_cost:
            best_cost = cost
            best_iteration = iteration
            best_tn, best_path = snapshot(data, communication_path)

    return best_iteration, best_tn, best_path, history


def _characterize_from_leaves(
    forest: _PartitionForest, leaves: list[int]
) -> _PartitionData:
    """Initial characterization: Greedy path + subtree build per block."""
    tensors = [
        forest.tensor.tensors[forest.nodes[l].leaf_index] for l in leaves
    ]
    if len(tensors) > 1:
        result = Greedy(OptMethod.GREEDY).find_path(CompositeTensor(tensors))
        local = list(result.replace_path().toplevel)
        flops, mem = result.flops, result.size
        root = forest.build_subtree(leaves, local)
    else:
        local, flops, mem = [], 0.0, tensors[0].size()
        root = leaves[0]
    external = LeafTensor()
    for t in tensors:
        external = external ^ t
    return _PartitionData(root, flops, mem, local, external, list(leaves))
