"""Greedy iterative partition balancing.

Mirror of ``tnc/src/contractionpath/contraction_tree/balancing.rs`` (the
``balance_partitions_iter`` entry point, ``:98-210``) and its scheme
catalogue (``balancing/balancing_schemes.rs:12-68``): iteratively shift
leaf tensors or whole subtrees between partitions to minimize the
critical-path cost of the partitioned contraction, re-running the greedy
finder on the two touched partitions after every shift and re-scheduling
the fan-in with a :class:`CommunicationScheme`.

Schemes:

- ``BEST_WORST`` — move the best-scoring leaf from the most expensive
  partition to the least expensive one.
- ``TENSOR`` — move the single best leaf tensor from the critical
  partition to the best target partition.
- ``TENSORS`` — additionally consider moving connected leaf *pairs*
  (tensors sharing a leg) in one shift.
- ``ALTERNATING_TENSORS`` — alternate donor between the most expensive
  and the most memory-heavy partition.
- ``INTERMEDIATE_TENSORS(height_limit)`` — move an intermediate subtree
  (bounded leaf count) instead of single leaves.
- ``ALTERNATING_INTERMEDIATE_TENSORS`` / ``ALTERNATING_TREE_TENSORS`` —
  alternating donor selection for subtree moves.

The cost history of every iteration is returned along with the best
iteration's network and path, as in the reference.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable, Sequence

from tnc_tpu.contractionpath.communication_schemes import CommunicationScheme
from tnc_tpu.contractionpath.contraction_cost import (
    compute_memory_requirements,
    contract_path_cost,
    contract_size_tensors_bytes,
)
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.repartitioning import compute_solution
from tnc_tpu.contractionpath.repartitioning.simulated_annealing import (
    _local_greedy_path,
    _subtree_leaves,
)
from tnc_tpu.tensornetwork.partitioning import partition_tensor_network
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


class BalancingScheme:
    """Scheme tags; ``INTERMEDIATE_TENSORS`` carries a height limit."""

    BEST_WORST = "best_worst"
    TENSOR = "tensor"
    TENSORS = "tensors"
    ALTERNATING_TENSORS = "alternating_tensors"
    INTERMEDIATE_TENSORS = "intermediate_tensors"
    ALTERNATING_INTERMEDIATE_TENSORS = "alternating_intermediate_tensors"
    ALTERNATING_TREE_TENSORS = "alternating_tree_tensors"


def _default_objective(
    shifted: LeafTensor, target_external: LeafTensor
) -> float:
    """Memory-reduction objective: growth of the target's external tensor
    (lower is better)."""
    return (shifted ^ target_external).size() - target_external.size()


@dataclass
class BalanceSettings:
    """Mirror of ``BalanceSettings`` (``balancing.rs:27-86``)."""

    iterations: int = 20
    scheme: str = BalancingScheme.BEST_WORST
    height_limit: int = 4  # for intermediate-subtree schemes
    communication_scheme: CommunicationScheme = CommunicationScheme.GREEDY
    memory_limit: float | None = None
    objective: Callable[[LeafTensor, LeafTensor], float] = field(
        default=_default_objective
    )
    weighted_random_top: int | None = None  # pick randomly among top-N moves


@dataclass
class _State:
    partitioning: list[int]
    local_paths: list[list[tuple[int, int]]]
    num_partitions: int


def _partition_cost(
    tensor: CompositeTensor, state: _State, p: int
) -> float:
    members = [
        t for t, b in zip(tensor.tensors, state.partitioning) if b == p
    ]
    if len(members) <= 1:
        return 0.0
    local = CompositeTensor(members)
    flops, _ = contract_path_cost(local, ContractionPath.simple(state.local_paths[p]), True)
    return flops


def _partition_external(tensor: CompositeTensor, state: _State, p: int) -> LeafTensor:
    external = LeafTensor()
    for t, b in zip(tensor.tensors, state.partitioning):
        if b == p:
            external = external ^ t
    return external


def _partition_memory(tensor: CompositeTensor, state: _State, p: int) -> float:
    total = 0.0
    for t, b in zip(tensor.tensors, state.partitioning):
        if b == p:
            total += t.size()
    return total


def _evaluate(
    tensor: CompositeTensor,
    state: _State,
    settings: BalanceSettings,
    rng: random.Random,
) -> tuple[float, CompositeTensor, ContractionPath]:
    partitioned, full_path, parallel, _ = compute_solution(
        tensor, state.partitioning, settings.communication_scheme, rng
    )
    if settings.memory_limit is not None:
        mem = compute_memory_requirements(
            partitioned.tensors, full_path, contract_size_tensors_bytes
        )
        if mem > settings.memory_limit:
            parallel = math.inf
    return parallel, partitioned, full_path


def _movable_groups(
    tensor: CompositeTensor,
    state: _State,
    donor: int,
    settings: BalanceSettings,
    rng: random.Random,
) -> list[list[int]]:
    """Candidate move groups (lists of global tensor indices) from the
    donor partition, per scheme."""
    donor_indices = [
        g for g, b in enumerate(state.partitioning) if b == donor
    ]
    if len(donor_indices) <= 1:
        return []

    scheme = settings.scheme
    subtree_schemes = (
        BalancingScheme.INTERMEDIATE_TENSORS,
        BalancingScheme.ALTERNATING_INTERMEDIATE_TENSORS,
        BalancingScheme.ALTERNATING_TREE_TENSORS,
    )
    if scheme in subtree_schemes:
        local_path = state.local_paths[donor]
        groups = []
        limit = max(2, settings.height_limit)
        for pair_index in range(max(0, len(local_path) - 1)):
            leaves = _subtree_leaves(local_path, pair_index)
            if 2 <= len(leaves) <= limit and len(leaves) < len(donor_indices):
                groups.append([donor_indices[k] for k in sorted(leaves)])
        if groups:
            return groups
    if scheme in (BalancingScheme.TENSORS, BalancingScheme.ALTERNATING_TENSORS):
        # batch moves: connected leaf pairs (sharing a leg) in addition to
        # single leaves, so a bonded cluster can migrate in one shift
        groups = [[g] for g in donor_indices]
        if len(donor_indices) > 2:
            legs_of = {g: set(tensor.tensors[g].legs) for g in donor_indices}
            for a_pos, a in enumerate(donor_indices):
                for b in donor_indices[a_pos + 1 :]:
                    if legs_of[a] & legs_of[b]:
                        groups.append([a, b])
        return groups
    # single-leaf moves (also the fallback for subtree schemes)
    return [[g] for g in donor_indices]


def _pick_donor(
    tensor: CompositeTensor,
    state: _State,
    settings: BalanceSettings,
    iteration: int,
) -> int:
    costs = [
        _partition_cost(tensor, state, p) for p in range(state.num_partitions)
    ]
    alternating = settings.scheme in (
        BalancingScheme.ALTERNATING_TENSORS,
        BalancingScheme.ALTERNATING_INTERMEDIATE_TENSORS,
        BalancingScheme.ALTERNATING_TREE_TENSORS,
    )
    if alternating and iteration % 2 == 1:
        memories = [
            _partition_memory(tensor, state, p)
            for p in range(state.num_partitions)
        ]
        return max(range(state.num_partitions), key=lambda p: memories[p])
    return max(range(state.num_partitions), key=lambda p: costs[p])


def balance_partitions_iter(
    tensor: CompositeTensor,
    partitioning: Sequence[int],
    settings: BalanceSettings | None = None,
    rng: random.Random | None = None,
) -> tuple[int, CompositeTensor, ContractionPath, list[float]]:
    """Iteratively rebalance ``partitioning``; returns
    (best iteration, best partitioned network, best path, cost history)
    (``balancing.rs:98-210``)."""
    settings = settings or BalanceSettings()
    rng = rng or random.Random(42)

    num_partitions = max(partitioning) + 1
    state = _State(
        partitioning=list(partitioning),
        local_paths=[],
        num_partitions=num_partitions,
    )
    for p in range(num_partitions):
        members = [
            t for t, b in zip(tensor.tensors, state.partitioning) if b == p
        ]
        state.local_paths.append(_local_greedy_path(members))

    cost, best_tn, best_path = _evaluate(tensor, state, settings, rng)
    history = [cost]
    best_cost = cost
    best_iteration = 0

    for iteration in range(settings.iterations):
        donor = _pick_donor(tensor, state, settings, iteration)
        groups = _movable_groups(tensor, state, donor, settings, rng)
        if not groups:
            break

        # Score each (group, target) by the objective on the target's
        # external tensor; BEST_WORST fixes the target to the cheapest
        # partition.
        if settings.scheme == BalancingScheme.BEST_WORST:
            costs = [
                _partition_cost(tensor, state, p)
                for p in range(num_partitions)
            ]
            targets = [
                min(
                    (p for p in range(num_partitions) if p != donor),
                    key=lambda p: costs[p],
                )
            ]
        else:
            targets = [p for p in range(num_partitions) if p != donor]

        externals = {
            p: _partition_external(tensor, state, p) for p in targets
        }
        moves: list[tuple[float, list[int], int]] = []
        for group in groups:
            shifted = LeafTensor()
            for g in group:
                shifted = shifted ^ tensor.tensors[g]
            for p in targets:
                moves.append((settings.objective(shifted, externals[p]), group, p))
        if not moves:
            break
        moves.sort(key=lambda m: m[0])
        if settings.weighted_random_top:
            top = moves[: settings.weighted_random_top]
            _, group, target = top[rng.randrange(len(top))]
        else:
            _, group, target = moves[0]

        # Apply the shift and re-path both partitions.
        for g in group:
            state.partitioning[g] = target
        for p in (donor, target):
            members = [
                t
                for t, b in zip(tensor.tensors, state.partitioning)
                if b == p
            ]
            state.local_paths[p] = _local_greedy_path(members)

        cost, tn, path = _evaluate(tensor, state, settings, rng)
        history.append(cost)
        if cost < best_cost:
            best_cost = cost
            best_tn, best_path = tn, path
            best_iteration = iteration + 1

    return best_iteration, best_tn, best_path, history
