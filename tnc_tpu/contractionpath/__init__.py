from tnc_tpu.contractionpath.contraction_path import (  # noqa: F401
    ContractionPath,
    SimplePath,
    path,
    ssa_ordering,
    ssa_replace_ordering,
)
