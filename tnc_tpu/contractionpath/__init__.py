from tnc_tpu.contractionpath.contraction_path import (  # noqa: F401
    ContractionPath,
    SimplePath,
    SimplePathRef,
    path,
    replace_ssa_ordering,
    ssa_ordering,
    ssa_replace_ordering,
)
