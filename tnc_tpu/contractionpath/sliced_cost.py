"""Incremental sliced-cost evaluation and the joint tree+slice search.

The historical planner treated slicing as a **post-pass**: find the
lowest-flop tree, then slice it to the memory budget and repair
(:func:`tnc_tpu.contractionpath.slicing.slice_and_reconfigure`). On
budget-bound networks that sequencing is the dominant waste — a tree
that slices well routinely beats the lowest-flop tree by orders of
magnitude once the slice overhead is charged (docs/future_work.md 8a;
the EinExprs observation, arXiv:2403.18030, that cheap symbolic
re-evaluation makes slicing affordable *inside* the search, and the
SA-based joint partition+slice refinement of arXiv:2507.20667).

This module makes the sliced objective cheap enough to sit in every
search loop:

- :class:`SlicedCostEvaluator` — given a contraction tree (or flat
  replace path) and a candidate slice-leg set, maintains per-step
  "does this leg touch me" masks and answers per-slice flops, the
  hoist split, the sliced peak, and the hoist-aware total (raw flops,
  or predicted seconds under a
  :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`) with
  O(affected-steps) delta updates when a leg is added/removed or a
  subtree move is applied. Exact against the
  :func:`~tnc_tpu.contractionpath.slicing.sliced_flops` /
  :class:`~tnc_tpu.contractionpath.slicing.StemAccountant` oracles.
- :func:`greedy_slice_to_target` — the greedy slice-set maintenance
  every hyper trial can now afford (delta-trial per candidate leg
  instead of a full path replay).
- :func:`joint_slice_search` — SA-style interleaved refinement: tree
  rotation moves and slice-set swap moves accepted under the TRUE
  sliced objective, alternating with exact-DP subtree reconfiguration
  (:meth:`ContractionTree.reconfigure` with a
  :class:`SlicedReconfState`), so tree-internal refinement finally
  optimizes the sliced cost instead of staying flop-domain.

Exactness note: step costs are recomputed as products over each step's
surviving legs (never by dividing a cached product), so evaluator
counts are bitwise-identical to the replay oracles on power-of-two
bond dimensions — i.e. every circuit network this framework plans.
"""

from __future__ import annotations

import math
import random
from typing import Sequence

from tnc_tpu.contractionpath.contraction_tree import ContractionTree
from tnc_tpu.tensornetwork.tensor import LeafTensor


class SlicedCostEvaluator:
    """Incremental hoist-aware sliced-cost evaluator.

    One construction pass records, per contraction step, the step's
    *union* legs (which scale its cost and operand sizes) and its
    *contributed* legs (every leg of the leaves below it — the mask
    that decides slice-variance, mirroring
    :class:`~tnc_tpu.contractionpath.slicing.StemAccountant`). Adding
    or removing a slice leg then touches only the steps whose masks
    contain that leg; queries are one pass over the cached per-step
    values.

    >>> ts = [LeafTensor.from_const([0, 1], 4), LeafTensor.from_const([1, 2], 4),
    ...       LeafTensor.from_const([2, 3], 4), LeafTensor.from_const([3, 0], 4)]
    >>> ev = SlicedCostEvaluator(ts, [(0, 3), (0, 1), (0, 2)])
    >>> ev.add_leg(2)
    >>> ev.num_slices, ev.per_slice_flops() < ev.total_flops
    (4, True)
    >>> ev.drop_leg(2)
    >>> ev.per_slice_flops() == ev.total_flops
    True
    """

    def __init__(
        self,
        inputs: Sequence[LeafTensor],
        replace_path: Sequence[tuple[int, int]] | None = None,
        removed: Sequence[int] = (),
        cost_model=None,
    ):
        self._cost_model = cost_model
        self._removed: set[int] = set()
        self._slot_of: dict[int, int] = {}  # tree node id -> slot
        self._contrib: dict[int, frozenset[int]] = {}  # tree mode only
        # per-slot step tables (parallel lists; freed slots inactive)
        self._active: list[bool] = []
        self._union: list[tuple[int, ...]] = []  # sorted union legs
        self._out: list[tuple[int, ...]] = []
        self._left: list[tuple[int, ...]] = []
        self._right: list[tuple[int, ...]] = []
        self._contrib_of_slot: list[frozenset[int]] = []
        self._cost: list[float] = []
        self._size: list[float] = []
        self._vcount: list[int] = []
        self._free: list[int] = []
        self._leg_cost_slots: dict[int, set[int]] = {}
        self._leg_contrib_slots: dict[int, set[int]] = {}
        self.dims: dict[int, int] = {}
        self.open_legs: set[int] = set()

        if replace_path is None:
            return  # from_tree fills the tables itself

        for t in inputs:
            for leg, dim in t.edges():
                self.dims[leg] = dim
                if leg in self.open_legs:
                    self.open_legs.discard(leg)
                else:
                    self.open_legs.add(leg)

        tensors = [frozenset(t.legs) for t in inputs]
        contrib = [frozenset(t.legs) for t in inputs]
        for i, j in replace_path:
            ti, tj = tensors[i], tensors[j]
            out = ti ^ tj
            self._new_slot(out, ti, tj, contrib[i] | contrib[j])
            tensors[i] = out
            contrib[i] = contrib[i] | contrib[j]
        for leg in removed:
            self.add_leg(leg)

    @classmethod
    def from_tree(
        cls,
        tree: ContractionTree,
        removed: Sequence[int] = (),
        cost_model=None,
        dims: dict[int, int] | None = None,
    ) -> "SlicedCostEvaluator":
        """Tree-backed evaluator: steps keyed by internal node, kept in
        sync through structural moves via :meth:`sync_nodes` /
        :meth:`sync_splice`. ``dims`` overrides ``tree.dims`` (pass the
        full dims when the tree's copy has sliced legs set to 1)."""
        ev = cls((), None, (), cost_model)
        ev.dims = dict(dims if dims is not None else tree.dims)
        for i in range(tree.num_leaves):
            legs = tree.nodes[i].legs
            ev._contrib[i] = legs
            for leg in legs:
                if leg in ev.open_legs:
                    ev.open_legs.discard(leg)
                else:
                    ev.open_legs.add(leg)
        for i in tree._postorder():
            nd = tree.nodes[i]
            if nd.is_leaf:
                continue
            contrib = ev._contrib[nd.left] | ev._contrib[nd.right]
            ev._contrib[i] = contrib
            ev._slot_of[i] = ev._new_slot(
                nd.legs, tree.nodes[nd.left].legs, tree.nodes[nd.right].legs,
                contrib,
            )
        for leg in removed:
            ev.add_leg(leg)
        return ev

    # -- slot bookkeeping ---------------------------------------------------

    def _prod(self, legs) -> float:
        out = 1.0
        dims = self.dims
        removed = self._removed
        for leg in legs:
            if leg not in removed:
                out *= dims[leg]
        return out

    def _step_values(self, slot: int) -> None:
        """Recompute the cached cost and size of ``slot`` from its leg
        tuples (always a fresh product — never a division of a cached
        value — so delta updates stay bitwise-equal to a from-scratch
        build)."""
        self._cost[slot] = self._prod(self._union[slot])
        self._size[slot] = (
            self._prod(self._out[slot])
            + self._prod(self._left[slot])
            + self._prod(self._right[slot])
        )

    def _new_slot(self, out_legs, left_legs, right_legs, contrib) -> int:
        if self._free:
            slot = self._free.pop()
        else:
            slot = len(self._active)
            self._active.append(False)
            self._union.append(())
            self._out.append(())
            self._left.append(())
            self._right.append(())
            self._contrib_of_slot.append(frozenset())
            self._cost.append(0.0)
            self._size.append(0.0)
            self._vcount.append(0)
        self._active[slot] = True
        self._union[slot] = tuple(sorted(set(left_legs) | set(right_legs)))
        self._out[slot] = tuple(sorted(out_legs))
        self._left[slot] = tuple(sorted(left_legs))
        self._right[slot] = tuple(sorted(right_legs))
        self._contrib_of_slot[slot] = frozenset(contrib)
        for leg in self._union[slot]:
            self._leg_cost_slots.setdefault(leg, set()).add(slot)
        for leg in contrib:
            self._leg_contrib_slots.setdefault(leg, set()).add(slot)
        self._vcount[slot] = sum(
            1 for leg in self._removed if leg in self._contrib_of_slot[slot]
        )
        self._step_values(slot)
        return slot

    def _free_slot(self, slot: int) -> None:
        for leg in self._union[slot]:
            self._leg_cost_slots[leg].discard(slot)
        for leg in self._contrib_of_slot[slot]:
            self._leg_contrib_slots[leg].discard(slot)
        self._active[slot] = False
        self._cost[slot] = 0.0
        self._size[slot] = 0.0
        self._vcount[slot] = 0
        self._free.append(slot)

    # -- slice-set mutation -------------------------------------------------

    @property
    def removed(self) -> frozenset[int]:
        return frozenset(self._removed)

    @property
    def num_slices(self) -> int:
        n = 1
        for leg in self._removed:
            n *= self.dims[leg]
        return n

    def sliceable(self, leg: int) -> bool:
        """Closed, dim > 1, and not already sliced."""
        return (
            leg in self.dims
            and leg not in self.open_legs
            and self.dims[leg] > 1
            and leg not in self._removed
        )

    def add_leg(self, leg: int) -> None:
        if leg in self._removed:
            raise ValueError(f"leg {leg} already sliced")
        if leg not in self.dims:
            raise ValueError(f"unknown leg {leg}")
        self._removed.add(leg)
        for slot in self._leg_cost_slots.get(leg, ()):
            self._step_values(slot)
        for slot in self._leg_contrib_slots.get(leg, ()):
            self._vcount[slot] += 1

    def drop_leg(self, leg: int) -> None:
        if leg not in self._removed:
            raise ValueError(f"leg {leg} is not sliced")
        self._removed.discard(leg)
        for slot in self._leg_cost_slots.get(leg, ()):
            self._step_values(slot)
        for slot in self._leg_contrib_slots.get(leg, ()):
            self._vcount[slot] -= 1

    # -- tree synchronization ----------------------------------------------

    def sync_nodes(self, tree: ContractionTree, nodes: Sequence[int]) -> None:
        """Re-derive the given internal nodes from their (current)
        children, bottom-up order required — the O(affected) update for
        a rotation move (pass ``[x, p]``)."""
        for i in nodes:
            nd = tree.nodes[i]
            slot = self._slot_of[i]
            self._free_slot(slot)
            contrib = self._contrib[nd.left] | self._contrib[nd.right]
            self._contrib[i] = contrib
            self._slot_of[i] = self._new_slot(
                nd.legs, tree.nodes[nd.left].legs, tree.nodes[nd.right].legs,
                contrib,
            )

    def sync_splice(
        self,
        tree: ContractionTree,
        top: int,
        frontier: Sequence[int],
        old_internal: Sequence[int],
    ) -> None:
        """Re-slot the subtree between ``top`` and ``frontier`` after a
        DP splice replaced its internal structure. ``old_internal`` is
        the pre-splice internal node set of that region (including
        ``top``)."""
        for node in old_internal:
            slot = self._slot_of.pop(node, None)
            if slot is not None:
                self._free_slot(slot)
        order = self.subtree_internal(tree, top, frontier)
        for i in reversed(order):  # children precede parents
            nd = tree.nodes[i]
            contrib = self._contrib[nd.left] | self._contrib[nd.right]
            self._contrib[i] = contrib
            self._slot_of[i] = self._new_slot(
                nd.legs, tree.nodes[nd.left].legs, tree.nodes[nd.right].legs,
                contrib,
            )

    def subtree_internal(
        self, tree: ContractionTree, top: int, frontier: Sequence[int]
    ) -> list[int]:
        """Internal nodes between ``top`` (inclusive) and ``frontier``
        (exclusive) — what a splice will orphan."""
        frontier_set = set(frontier)
        out: list[int] = []
        stack = [top]
        while stack:
            i = stack.pop()
            if i in frontier_set or tree.nodes[i].is_leaf:
                continue
            out.append(i)
            stack.append(tree.nodes[i].left)
            stack.append(tree.nodes[i].right)
        return out

    # -- queries ------------------------------------------------------------

    @property
    def total_flops(self) -> float:
        """Per-slice flops with NO legs removed (construction-time
        value for an empty slice set; recomputed honestly otherwise)."""
        saved = self._removed
        if not saved:
            return self.per_slice_flops()
        self._removed = set()
        total = 0.0
        for slot in range(len(self._active)):
            if self._active[slot]:
                total += self._prod(self._union[slot])
        self._removed = saved
        return total

    def per_slice_flops(self) -> float:
        total = 0.0
        for slot in range(len(self._active)):
            if self._active[slot]:
                total += self._cost[slot]
        return total

    def peak(self) -> float:
        peak = 0.0
        for slot in range(len(self._active)):
            if self._active[slot] and self._size[slot] > peak:
                peak = self._size[slot]
        return peak

    def hoist_split(self) -> tuple[float, float]:
        """(invariant, per-slice residual) flops — mirrors
        :meth:`~tnc_tpu.contractionpath.slicing.StemAccountant.
        hoist_split` exactly, including the no-op degradation when no
        step (1-slice plans) or every step is variant."""
        n = n_var = 0
        per_slice = 0.0
        inv = 0.0
        for slot in range(len(self._active)):
            if not self._active[slot]:
                continue
            n += 1
            per_slice += self._cost[slot]
            if self._vcount[slot] > 0:
                n_var += 1
            else:
                inv += self._cost[slot]
        if n_var == 0 or n_var == n:
            return 0.0, per_slice
        return inv, max(per_slice - inv, 0.0)

    def sliced_total(self) -> float:
        """Naive total across slices (the
        :func:`~tnc_tpu.contractionpath.slicing.sliced_flops` oracle:
        ``num_slices * per_slice``)."""
        return self.per_slice_flops() * self.num_slices

    def hoisted_total(self) -> float:
        """``invariant + num_slices * residual`` flops under stem
        hoisting (the :func:`~tnc_tpu.contractionpath.slicing.
        hoisted_sliced_flops` total)."""
        inv, residual = self.hoist_split()
        return inv + float(self.num_slices) * residual

    def cost(self) -> float:
        """The scoring key: hoisted flops, or predicted seconds under
        the ``cost_model`` (identical formula to
        :meth:`StemAccountant.hoisted_cost`, residual dispatches
        included)."""
        inv, residual = self.hoist_split()
        if self._cost_model is None:
            return inv + float(self.num_slices) * residual
        n = n_var = 0
        for slot in range(len(self._active)):
            if self._active[slot]:
                n += 1
                if self._vcount[slot] > 0:
                    n_var += 1
        if n_var == 0 or n_var == n:  # no-op hoist: all steps loop
            n_var = n
        return self._cost_model.sliced_cost(
            inv,
            residual,
            self.num_slices,
            steps_per_slice=max(float(n_var), 1.0),
            prelude_steps=max(float(n - n_var), 1.0),
        )

    def peak_step_legs(self, frac: float = 0.99) -> list[int]:
        """Sliceable legs participating in the near-peak steps (the
        slice-candidate pool, mirroring ``slice_and_reconfigure``'s
        leg selection)."""
        peak = self.peak()
        legs: set[int] = set()
        for slot in range(len(self._active)):
            if self._active[slot] and self._size[slot] >= peak * frac:
                legs.update(self._union[slot])
        return sorted(leg for leg in legs if self.sliceable(leg))

    def sliceable_legs(self) -> list[int]:
        """Every currently sliceable leg (fallback candidate pool)."""
        return sorted(leg for leg in self.dims if self.sliceable(leg))


def greedy_slice_to_target(
    ev: SlicedCostEvaluator,
    target_size: float,
    max_slices: int = 1 << 26,
    max_leg_candidates: int = 48,
) -> None:
    """Greedily grow ``ev``'s slice set until the sliced peak fits
    ``target_size``, scoring each candidate leg by (post-slice peak,
    hoisted cost) through a delta add/drop trial — the per-trial slice
    maintenance of the joint hyper search. Mutates ``ev`` in place;
    raises ``ValueError`` when the target is unreachable."""
    while True:
        peak = ev.peak()
        if peak <= target_size:
            return
        candidates = ev.peak_step_legs()
        if not candidates:
            candidates = ev.sliceable_legs()
        if not candidates:
            raise ValueError(
                f"No sliceable legs left but peak {peak:.3e} > "
                f"target {target_size:.3e}"
            )
        best_leg = -1
        best_key: tuple[float, float] | None = None
        for leg in candidates[:max_leg_candidates]:
            ev.add_leg(leg)
            key = (ev.peak(), ev.cost())
            ev.drop_leg(leg)
            if best_key is None or key < best_key:
                best_key = key
                best_leg = leg
        ev.add_leg(best_leg)
        if ev.num_slices > max_slices:
            raise ValueError(
                f"Slicing needs more than {max_slices} slices to reach "
                f"target {target_size:.3e}"
            )


class SlicedReconfState:
    """Sliced-objective acceptance for
    :meth:`ContractionTree.reconfigure`: a DP-proposed splice is kept
    only when the evaluator's hoisted sliced cost improves and the
    sliced peak stays within ``target_size`` — tree-internal
    refinement under the objective the executor actually pays."""

    def __init__(
        self,
        evaluator: SlicedCostEvaluator,
        target_size: float | None = None,
    ):
        self.evaluator = evaluator
        self.target_size = target_size

    def peak_bound(self) -> float:
        """The peak a move may not exceed: the budget, or — while the
        state is transiently over budget — the current peak."""
        peak = self.evaluator.peak()
        if self.target_size is None:
            return math.inf
        return max(self.target_size, peak)


def _sa_accept(delta: float, temp: float, rng: random.Random) -> bool:
    if delta <= 0.0:
        return True
    return temp > 0.0 and rng.random() < math.exp(-delta / temp)


def _log2_delta(new: float, old: float) -> float:
    return math.log2(new + 1.0) - math.log2(old + 1.0)


def anneal_sliced(
    tree: ContractionTree,
    ev: SlicedCostEvaluator,
    rng: random.Random,
    steps: int,
    t_start: float,
    t_end: float,
    target_size: float,
    max_slices: int = 1 << 26,
    p_slice_move: float = 0.25,
    p_partition_move: float = 0.0,
) -> None:
    """SA-style interleaved refinement: tree rotation moves and
    slice-set swap moves, both accepted by Metropolis on the log2 ratio
    of the evaluator's hoisted sliced cost, under the peak budget.
    ``tree.dims`` is kept as the *reduced* model (sliced legs dim 1) so
    DP repair passes interleaved by the caller see the slice set.

    ``p_partition_move`` enables a third move kind — a leaf exchange
    between two subtrees (the partition move of the joint
    partition+slice SA, arXiv:2507.20667), which escapes basins that
    rotations alone cannot leave because a rotation never changes which
    leaves share a subtree. Off by default: the committed planner
    baselines were annealed without it; fleet trial grids
    (:mod:`tnc_tpu.serve.plansvc`) opt in per-trial."""
    internal = [i for i, nd in enumerate(tree.nodes)
                if not nd.is_leaf and i in ev._slot_of]
    if not internal:
        return
    full_dims = ev.dims
    for step in range(steps):
        frac = step / max(1, steps - 1)
        temp = t_start * (t_end / t_start) ** frac
        move_draw = rng.random()
        if move_draw < p_slice_move and ev.removed:
            _slice_move(tree, ev, rng, temp, target_size, max_slices,
                        full_dims)
            continue
        if (
            p_partition_move > 0.0
            and p_slice_move <= move_draw < p_slice_move + p_partition_move
        ):
            _partition_move(tree, ev, rng, temp, target_size)
            continue
        p = internal[rng.randrange(len(internal))]
        if not tree._reachable(p):
            continue
        candidates = list(_rotation_candidates(tree, p))
        if not candidates:
            continue
        x, a, b, c = candidates[rng.randrange(len(candidates))]
        keep, other = (a, b) if rng.random() < 0.5 else (b, a)
        old_cost = ev.cost()
        _apply_rotation(tree, p, x, keep, other, c)
        ev.sync_nodes(tree, [x, p])
        new_cost = ev.cost()
        ok = ev.peak() <= target_size and _sa_accept(
            _log2_delta(new_cost, old_cost), temp, rng
        )
        if not ok:
            _apply_rotation(tree, p, x, keep, c, other)
            ev.sync_nodes(tree, [x, p])


def _slice_move(
    tree: ContractionTree,
    ev: SlicedCostEvaluator,
    rng: random.Random,
    temp: float,
    target_size: float,
    max_slices: int,
    full_dims: dict[int, int],
) -> None:
    """One slice-set move: swap (drop one sliced leg, add a candidate),
    plain drop, or plain add — accepted like a rotation."""
    removed = sorted(ev.removed)
    kind = rng.random()
    old_cost = ev.cost()

    def settle(ok: bool, added: int | None, dropped: int | None) -> None:
        if ok:
            if added is not None:
                tree.dims[added] = 1
            if dropped is not None:
                tree.dims[dropped] = full_dims[dropped]

    if kind < 0.6:  # swap
        drop = removed[rng.randrange(len(removed))]
        pool = ev.peak_step_legs() or ev.sliceable_legs()
        pool = [leg for leg in pool if leg != drop]
        if not pool:
            return
        add = pool[rng.randrange(len(pool))]
        ev.drop_leg(drop)
        ev.add_leg(add)
        ok = (
            ev.peak() <= target_size
            and ev.num_slices <= max_slices
            and _sa_accept(_log2_delta(ev.cost(), old_cost), temp, rng)
        )
        if not ok:
            ev.drop_leg(add)
            ev.add_leg(drop)
        settle(ok, add, drop)
    elif kind < 0.8:  # drop
        drop = removed[rng.randrange(len(removed))]
        ev.drop_leg(drop)
        ok = ev.peak() <= target_size and _sa_accept(
            _log2_delta(ev.cost(), old_cost), temp, rng
        )
        if not ok:
            ev.add_leg(drop)
        settle(ok, None, drop)
    else:  # add
        pool = ev.peak_step_legs() or ev.sliceable_legs()
        if not pool:
            return
        add = pool[rng.randrange(len(pool))]
        ev.add_leg(add)
        ok = ev.num_slices <= max_slices and _sa_accept(
            _log2_delta(ev.cost(), old_cost), temp, rng
        )
        if not ok:
            ev.drop_leg(add)
        settle(ok, add, None)


def _partition_move(
    tree: ContractionTree,
    ev: SlicedCostEvaluator,
    rng: random.Random,
    temp: float,
    target_size: float,
) -> None:
    """One partition move (arXiv:2507.20667): exchange two random
    leaves that sit under different parents, re-deriving legs and
    evaluator slots only along the two parent→LCA chains (above the
    LCA the subtree leaf set — hence every leg set — is unchanged).
    Accepted like a rotation; revert is the same swap again."""
    n = tree.num_leaves
    if n < 4:
        return
    a = rng.randrange(n)
    b = rng.randrange(n)
    if a == b or tree.nodes[a].parent == tree.nodes[b].parent:
        return
    if tree.nodes[a].parent < 0 or tree.nodes[b].parent < 0:
        return
    old_cost = ev.cost()
    _swap_leaves(tree, ev, a, b)
    ok = ev.peak() <= target_size and _sa_accept(
        _log2_delta(ev.cost(), old_cost), temp, rng
    )
    if not ok:
        _swap_leaves(tree, ev, a, b)


def _swap_leaves(
    tree: ContractionTree, ev: SlicedCostEvaluator, a: int, b: int
) -> None:
    """Exchange leaves ``a`` and ``b`` in the tree and bring ``ev``
    back in sync. Self-inverse (calling it twice restores the state
    bitwise), which is what makes the SA revert trivial."""
    nodes = tree.nodes
    pa, pb = nodes[a].parent, nodes[b].parent
    if nodes[pa].left == a:
        nodes[pa].left = b
    else:
        nodes[pa].right = b
    if nodes[pb].left == b:
        nodes[pb].left = a
    else:
        nodes[pb].right = a
    nodes[a].parent, nodes[b].parent = pb, pa

    def ancestors(i: int) -> list[int]:
        out = []
        while i >= 0:
            out.append(i)
            i = nodes[i].parent
        return out

    chain_a, chain_b = ancestors(pa), ancestors(pb)
    on_a = set(chain_a)
    lca = next(i for i in chain_b if i in on_a)
    below_a = chain_a[: chain_a.index(lca)]
    below_b = chain_b[: chain_b.index(lca)]
    # legs first (chain order is bottom-up; chains are disjoint below
    # the LCA), then the evaluator — sync_nodes reads current child
    # legs. The LCA's own legs are invariant but its step cost is not.
    for i in below_a + below_b:
        nd = nodes[i]
        nd.legs = nodes[nd.left].legs ^ nodes[nd.right].legs
    ev.sync_nodes(tree, below_a + below_b + [lca])


def joint_slice_search(
    inputs: Sequence[LeafTensor],
    ssa_path: Sequence[tuple[int, int]],
    target_size: float,
    seed_slices: Sequence[int] | None = None,
    cost_model=None,
    sa_steps: int = 600,
    sa_rounds: int = 2,
    subtree_size: int = 12,
    reconf_rounds: int = 1,
    final_rounds: int = 2,
    seed: int = 42,
    max_slices: int = 1 << 26,
    temps: tuple[float, float] = (0.3, 0.01),
    p_partition_move: float = 0.0,
) -> tuple[list[tuple[int, int]], "Slicing", float]:
    """Joint tree+slice refinement of one candidate tree: greedy slice
    seeding (or ``seed_slices``), then rounds of interleaved SA
    (rotations ⇄ slice swaps, sliced-objective acceptance) and exact-DP
    reconfiguration under :class:`SlicedReconfState`, tracking the best
    (peak-feasible) state seen — the initial seeded state included, so
    the result never scores worse than its greedy seed.

    Returns ``(ssa_pairs, slicing, cost)`` with ``cost`` in the
    evaluator's domain (hoisted flops, or seconds under
    ``cost_model``). Deterministic for a fixed seed (work-bounded, no
    wall-clock deadlines). Raises ``ValueError`` when the target is
    unreachable."""
    from tnc_tpu.contractionpath.slicing import Slicing

    tree = ContractionTree.from_ssa_path(inputs, list(ssa_path))
    full_dims = dict(tree.dims)
    tree.dims = dict(tree.dims)  # private copy: sliced legs become dim 1
    ev = SlicedCostEvaluator.from_tree(tree, cost_model=cost_model,
                                       dims=full_dims)
    if seed_slices:
        for leg in seed_slices:
            if ev.sliceable(leg):
                ev.add_leg(leg)
    greedy_slice_to_target(ev, target_size, max_slices)
    for leg in ev.removed:
        tree.dims[leg] = 1

    rng = random.Random(seed ^ 0x51CE5)
    best_cost = ev.cost()
    best_pairs = tree.to_ssa_path()
    best_removed = ev.removed

    def track() -> None:
        nonlocal best_cost, best_pairs, best_removed
        if ev.peak() <= target_size:
            c = ev.cost()
            if c < best_cost:
                best_cost = c
                best_pairs = tree.to_ssa_path()
                best_removed = ev.removed

    state = SlicedReconfState(ev, target_size)
    for _ in range(max(0, sa_rounds)):
        anneal_sliced(
            tree, ev, rng, sa_steps, temps[0], temps[1], target_size,
            max_slices, p_partition_move=p_partition_move,
        )
        track()
        if reconf_rounds > 0:
            tree.reconfigure(subtree_size, reconf_rounds, sliced=state)
            track()
    if final_rounds > 0:
        tree.reconfigure(subtree_size, final_rounds, sliced=state)
        track()

    ordered = sorted(best_removed)
    slicing = Slicing(
        tuple(ordered), tuple(full_dims[leg] for leg in ordered)
    )
    return best_pairs, slicing, best_cost


def _rotation_candidates(tree: ContractionTree, p: int):
    from tnc_tpu.contractionpath.paths.tree_refine import (
        _rotation_candidates as impl,
    )

    return impl(tree, p)


def _apply_rotation(tree, p, x, keep, other, c):
    from tnc_tpu.contractionpath.paths.tree_refine import (
        _apply_rotation as impl,
    )

    return impl(tree, p, x, keep, other, c)
