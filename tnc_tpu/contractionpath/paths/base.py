"""Pathfinder interface and result types.

Mirror of ``tnc/src/contractionpath/paths.rs:21-85``: a ``Pathfinder``
turns a (possibly nested) tensor network into a contraction path plus its
predicted flops/size; results carry the SSA path and convert to
replace-left format on demand.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from tnc_tpu.contractionpath.contraction_path import (
    ContractionPath,
    ssa_replace_ordering,
)
from tnc_tpu.tensornetwork.tensor import CompositeTensor


class CostType(enum.Enum):
    FLOPS = "flops"
    SIZE = "size"


@dataclass
class BasicContractionPathResult:
    """SSA path + predicted cost (``paths.rs:47-76``).

    >>> from tnc_tpu.contractionpath.contraction_path import ContractionPath
    >>> r = BasicContractionPathResult(
    ...     ContractionPath.simple([(0, 1), (2, 3)]), 100.0, 16.0)
    >>> r.replace_path().toplevel   # ssa ids -> replace-left slots
    [(0, 1), (2, 0)]
    """

    ssa_path: ContractionPath
    flops: float
    size: float

    def replace_path(self) -> ContractionPath:
        return ssa_replace_ordering(self.ssa_path)


class Pathfinder:
    """Base class: ``find_path(tn) -> BasicContractionPathResult``.

    ``find_path`` handles the nested-composite recursion shared by every
    finder (``cotengrust.rs:120-145``): each composite child gets its own
    recursive ``find_path`` and is replaced by its external tensor for the
    top-level search, which subclasses implement in
    :meth:`_solve_toplevel`. Reported flops/size are recomputed by the
    analytic cost model with naive op counting (``cotengrust.rs:149``).
    """

    def find_path(self, tn: CompositeTensor) -> BasicContractionPathResult:
        from tnc_tpu import obs
        from tnc_tpu.contractionpath.contraction_cost import contract_path_cost

        with obs.span(
            "plan.find_path",
            finder=type(self).__name__,
            tensors=len(tn.tensors),
        ) as osp:
            nested: dict[int, ContractionPath] = {}
            flat_inputs = []
            for i, child in enumerate(tn.tensors):
                if isinstance(child, CompositeTensor):
                    sub = self.find_path(child)
                    nested[i] = sub.ssa_path
                    flat_inputs.append(child.external_tensor())
                else:
                    flat_inputs.append(child)

            toplevel = self._solve_toplevel(flat_inputs)
            ssa_path = ContractionPath(nested, toplevel)
            flops, size = contract_path_cost(
                tn.tensors, ssa_replace_ordering(ssa_path), True
            )
            osp.set(predicted_flops=flops, predicted_peak=size)
            return BasicContractionPathResult(ssa_path, flops, size)

    def _solve_toplevel(self, inputs: list) -> list[tuple[int, int]]:
        """Find an SSA pair path over flat leaf tensors."""
        raise NotImplementedError


# Alias used by the reference's public API surface (``paths.rs:31-43``).
ContractionPathResult = BasicContractionPathResult
