"""Optimal (exhaustive) contraction pathfinding via subset DP.

Equivalent of the reference's ``OptMethod::Optimal``
(``tnc/src/contractionpath/paths/cotengrust.rs:16-23`` →
``optimize_optimal_rust``): finds the provably cheapest pairwise
contraction tree. This implementation runs dynamic programming over
tensor subsets (O(3^n) — practical to ~16 tensors), minimizing either
naive op count or peak size (``CostType``, ``paths.rs:80-85``).

Like all finders, nested composites are solved recursively and replaced by
their external tensors at the top level.
"""

from __future__ import annotations

import math

from tnc_tpu.contractionpath.paths.base import CostType, Pathfinder
from tnc_tpu.tensornetwork.tensor import LeafTensor


class Optimal(Pathfinder):
    """Exact subset-DP pathfinder (O(3^n); ``paths/optimal.rs``).

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [4, 4]),
    ...     LeafTensor([1, 2], [4, 4]), LeafTensor([2, 0], [4, 4])])
    >>> from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
    >>> best = Optimal().find_path(tn)
    >>> best.flops <= Greedy(OptMethod.GREEDY).find_path(tn).flops
    True
    """

    def __init__(self, cost_type: CostType = CostType.FLOPS, max_tensors: int = 18):
        self.cost_type = cost_type
        self.max_tensors = max_tensors

    def _solve_toplevel(self, inputs: list[LeafTensor]) -> list[tuple[int, int]]:
        n = len(inputs)
        if n <= 1:
            return []
        if n > self.max_tensors:
            raise ValueError(
                f"Optimal pathfinding is limited to {self.max_tensors} tensors, got {n}"
            )

        dims: dict[int, int] = {}
        for t in inputs:
            for leg, dim in t.edges():
                dims[leg] = dim

        leg_sets = [frozenset(t.legs) for t in inputs]

        def set_size(s: frozenset[int]) -> float:
            out = 1.0
            for leg in s:
                out *= dims[leg]
            return out

        full = (1 << n) - 1
        # subset -> (cost, peak, split_lo, legs)
        legs_of: dict[int, frozenset[int]] = {}
        best: dict[int, tuple[float, float, int]] = {}
        for i in range(n):
            legs_of[1 << i] = leg_sets[i]
            best[1 << i] = (0.0, set_size(leg_sets[i]), 0)

        # Iterate subsets in increasing popcount order.
        subsets_by_count: list[list[int]] = [[] for _ in range(n + 1)]
        for mask in range(1, full + 1):
            subsets_by_count[mask.bit_count()].append(mask)

        for count in range(2, n + 1):
            for mask in subsets_by_count[count]:
                best_cost = math.inf
                best_peak = math.inf
                best_split = 0
                best_legs: frozenset[int] | None = None
                # enumerate proper sub-splits; canonicalize by requiring the
                # lowest set bit of mask to be in `lo`
                lowest = mask & (-mask)
                sub = (mask - 1) & mask
                while sub:
                    if sub & lowest:
                        lo, hi = sub, mask ^ sub
                        if hi and lo in best and hi in best:
                            cost_lo, peak_lo, _ = best[lo]
                            cost_hi, peak_hi, _ = best[hi]
                            l_lo, l_hi = legs_of[lo], legs_of[hi]
                            union = l_lo | l_hi
                            step_cost = set_size(union)
                            cost = cost_lo + cost_hi + step_cost
                            out = l_lo ^ l_hi
                            step_peak = set_size(out) + set_size(l_lo) + set_size(l_hi)
                            peak = max(peak_lo, peak_hi, step_peak)
                            key = cost if self.cost_type is CostType.FLOPS else peak
                            best_key = (
                                best_cost if self.cost_type is CostType.FLOPS else best_peak
                            )
                            if key < best_key:
                                best_cost, best_peak = cost, peak
                                best_split = lo
                                best_legs = out
                    sub = (sub - 1) & mask
                assert best_legs is not None
                best[mask] = (best_cost, best_peak, best_split)
                legs_of[mask] = best_legs

        # Reconstruct SSA path by post-order traversal of the split tree.
        ssa_path: list[tuple[int, int]] = []
        next_id = n

        def build(mask: int) -> int:
            nonlocal next_id
            if mask.bit_count() == 1:
                return mask.bit_length() - 1
            lo = best[mask][2]
            hi = mask ^ lo
            a = build(lo)
            b = build(hi)
            ssa_path.append((a, b))
            out_id = next_id
            next_id += 1
            return out_id

        build(full)
        return ssa_path
