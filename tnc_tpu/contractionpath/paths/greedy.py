"""Greedy and random-greedy contraction pathfinding.

Own implementation of the cotengra-style greedy algorithm the reference
reaches through the ``cotengrust`` crate
(``tnc/src/contractionpath/paths/cotengrust.rs:16-23,51-80``):

- Score every leg-sharing pair by the **memory-removed** heuristic
  ``size(out) - size(a) - size(b)`` and repeatedly contract the minimum
  (ties broken by insertion order). The heuristic is pluggable
  (``cost_fn=`` / ``alpha=``): the improved greedy cost functions of
  arXiv:2405.09644 — alpha-weighted memory-removed, its log-domain
  variant, and plain output size — come from
  :func:`~tnc_tpu.contractionpath.contraction_cost.greedy_cost_fn`.
- When no connected pairs remain, combine the surviving components by
  outer products, smallest first (ties: larger ssa id first — matches the
  reference's observed path output on the outer-product fixtures).
- ``RANDOM_GREEDY`` runs ``ntrials`` jittered repetitions (Gumbel noise on
  the pair score at a fixed temperature) with a deterministic seed and
  keeps the best path under the trial ``objective`` (default: lowest
  flops; a :class:`~tnc_tpu.contractionpath.contraction_cost.
  CalibratedObjective` ranks trials by predicted seconds instead).

Nested composites get their own recursive ``find_path`` and are replaced
by their external tensor for the top-level search, exactly as the
reference does (``cotengrust.rs:120-145``). Reported flops/size are
recomputed by the analytic cost model with naive op counting
(``cotengrust.rs:149``), so numbers are directly comparable with the
reference's fixtures (e.g. flops 600 / size 538 on the 3-tensor fixture).
"""

from __future__ import annotations

import enum
import heapq
import math
import random
from typing import Sequence

from tnc_tpu.contractionpath.contraction_cost import (
    PathObjective,
    contract_path_cost,
    greedy_cost_fn,
)
from tnc_tpu.contractionpath.contraction_path import (
    ContractionPath,
    ssa_replace_ordering,
)
from tnc_tpu.contractionpath.paths.base import Pathfinder
from tnc_tpu.tensornetwork.tensor import LeafTensor

DEFAULT_SEED = 42  # the reference pins this seed (cotengrust.rs:58,71)


class OptMethod(enum.Enum):
    GREEDY = "greedy"
    RANDOM_GREEDY = "random_greedy"


def _ssa_greedy(
    inputs: Sequence[LeafTensor],
    rng: random.Random | None = None,
    temperature: float = 0.0,
    cost_fn=None,
) -> list[tuple[int, int]]:
    """Core greedy over flat leaf tensors; returns an SSA pair path.

    ``cost_fn(out_size, size_a, size_b)`` scores candidate pairs
    (minimum contracts first); ``None`` keeps the classic
    memory-removed heuristic."""
    n = len(inputs)
    if n <= 1:
        return []

    legs: dict[int, frozenset[int]] = {}
    sizes: dict[int, float] = {}
    dims: dict[int, int] = {}
    leg_owners: dict[int, list[int]] = {}
    for i, t in enumerate(inputs):
        legs[i] = frozenset(t.legs)
        sizes[i] = t.size()
        for leg, dim in t.edges():
            dims[leg] = dim
            leg_owners.setdefault(leg, []).append(i)

    def out_size(leg_set: frozenset[int]) -> float:
        s = 1.0
        for leg in leg_set:
            s *= dims[leg]
        return s

    if cost_fn is None:
        cost_fn = greedy_cost_fn("memory-removed")

    def pair_score(i: int, j: int) -> float:
        out = legs[i] ^ legs[j]
        score = cost_fn(out_size(out), sizes[i], sizes[j])
        if temperature > 0.0 and rng is not None:
            # Gumbel perturbation: subtract T * log(-log u)
            u = rng.random()
            score -= temperature * -math.log(-math.log(u + 1e-300) + 1e-300)
        return score

    heap: list[tuple[float, int, int, int]] = []
    counter = 0
    seen_pairs: set[tuple[int, int]] = set()
    for i in range(n):
        for leg in sorted(legs[i]):
            for j in leg_owners[leg]:
                if j <= i:
                    continue
                if (i, j) in seen_pairs:
                    continue
                seen_pairs.add((i, j))
                heapq.heappush(heap, (pair_score(i, j), counter, i, j))
                counter += 1

    alive: set[int] = set(range(n))
    neighbors: dict[int, set[int]] = {i: set() for i in range(n)}
    for owners in leg_owners.values():
        for a in owners:
            for b in owners:
                if a != b:
                    neighbors[a].add(b)

    ssa_path: list[tuple[int, int]] = []
    next_id = n
    while heap:
        _, _, i, j = heapq.heappop(heap)
        if i not in alive or j not in alive:
            continue
        new_legs = legs[i] ^ legs[j]
        new_id = next_id
        next_id += 1
        ssa_path.append((i, j))

        alive.discard(i)
        alive.discard(j)
        new_neighbors = (neighbors[i] | neighbors[j]) & alive
        alive.add(new_id)
        legs[new_id] = new_legs
        sizes[new_id] = out_size(new_legs)
        neighbors[new_id] = new_neighbors
        for k in new_neighbors:
            neighbors[k].add(new_id)
            heapq.heappush(heap, (pair_score(new_id, k), counter, new_id, k))
            counter += 1

    # Outer products between remaining components: smallest size first, ties
    # broken by larger ssa id (matches the reference's output ordering).
    remaining = [(sizes[i], -i, i) for i in alive]
    heapq.heapify(remaining)
    while len(remaining) > 1:
        size_a, _, a = heapq.heappop(remaining)
        size_b, _, b = heapq.heappop(remaining)
        new_legs = legs[a] ^ legs[b]
        new_id = next_id
        next_id += 1
        ssa_path.append((a, b))
        legs[new_id] = new_legs
        new_size = out_size(new_legs)
        sizes[new_id] = new_size
        heapq.heappush(remaining, (new_size, -new_id, new_id))

    return ssa_path


class Greedy(Pathfinder):
    """Greedy / random-greedy pathfinder (cotengrust equivalent).

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [4, 4]),
    ...     LeafTensor([1, 2], [4, 4]), LeafTensor([2, 0], [4, 4])])
    >>> result = Greedy(OptMethod.GREEDY).find_path(tn)
    >>> len(result.replace_path().toplevel), result.flops > 0
    (2, True)
    """

    def __init__(
        self,
        method: OptMethod = OptMethod.GREEDY,
        ntrials: int = 32,
        seed: int = DEFAULT_SEED,
        temperature: float = 1.0,
        cost_fn: str | None = None,
        alpha: float = 1.0,
        objective: PathObjective | None = None,
    ) -> None:
        """``cost_fn``/``alpha`` select the pair heuristic
        (:func:`~tnc_tpu.contractionpath.contraction_cost.greedy_cost_fn`
        names, default memory-removed); ``objective`` ranks
        ``RANDOM_GREEDY`` trials (default: naive-op flops, the
        historical behavior — a calibrated objective keeps the trial
        whose *predicted seconds* are lowest)."""
        self.method = method
        self.ntrials = ntrials
        self.seed = seed
        self.temperature = temperature
        self.cost_fn = (
            greedy_cost_fn(cost_fn, alpha) if cost_fn is not None else None
        )
        self.objective = objective

    def _solve_toplevel(self, inputs: list[LeafTensor]) -> list[tuple[int, int]]:
        if self.method is OptMethod.GREEDY:
            return _ssa_greedy(inputs, cost_fn=self.cost_fn)
        return self._random_greedy(inputs)

    def _random_greedy(self, inputs: Sequence[LeafTensor]) -> list[tuple[int, int]]:
        best_path: list[tuple[int, int]] | None = None
        best_cost = math.inf
        leaf_tensors = list(inputs)
        for trial in range(self.ntrials):
            rng = random.Random(self.seed + trial)
            temp = 0.0 if trial == 0 else self.temperature
            candidate = _ssa_greedy(leaf_tensors, rng, temp, self.cost_fn)
            if self.objective is not None:
                cost = self.objective.ssa_path_cost(leaf_tensors, candidate)
            else:
                cost, _ = contract_path_cost(
                    leaf_tensors,
                    ssa_replace_ordering(ContractionPath.simple(candidate)),
                    True,
                )
            if cost < best_cost:
                best_cost = cost
                best_path = candidate
        assert best_path is not None
        return best_path


# Backwards-parity alias: the reference calls this finder `Cotengrust`.
Cotengrust = Greedy
