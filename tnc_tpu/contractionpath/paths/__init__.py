from tnc_tpu.contractionpath.paths.base import (  # noqa: F401
    BasicContractionPathResult,
    ContractionPathResult,
    CostType,
    Pathfinder,
)
from tnc_tpu.contractionpath.paths.branchbound import (  # noqa: F401
    BranchBound,
    WeightedBranchBound,
)
from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod  # noqa: F401
from tnc_tpu.contractionpath.paths.hyper import Hyperoptimizer  # noqa: F401
from tnc_tpu.contractionpath.paths.optimal import Optimal  # noqa: F401
from tnc_tpu.contractionpath.paths.tree_refine import (  # noqa: F401
    TreeAnnealing,
    TreeReconfigure,
    TreeTempering,
)
