"""Branch-and-bound pathfinding.

Mirror of ``tnc/src/contractionpath/paths/branchbound.rs`` and
``weighted_branchbound.rs`` (both ports of opt_einsum's branching
approach): depth-first search over pair contractions with

- candidate ordering per step: smallest intermediate size first, ties
  broken toward larger flops (the reference's ``Candidate`` ordering,
  ``candidates.rs:26-33``),
- ``nbranch`` limiting the fan-out per level,
- pruning against the best complete path found so far and a
  ``cutoff_flops_factor`` against the best partial cost at the same
  search depth (``branchbound.rs:86-97``),
- memoized pair results keyed by (i, j) with the larger tensor first.

:class:`WeightedBranchBound` searches the same space but accumulates
``flops + max(latency_i, latency_j)`` — the **critical path** including
per-input start latencies — making it a communication-schedule optimizer
(``weighted_branchbound.rs:74-80``; used by
``communication_schemes.rs:125-143``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from tnc_tpu.contractionpath.contraction_cost import (
    PathObjective,
    contract_cost_tensors,
    contract_op_cost_tensors,
    contract_size_tensors,
)
from tnc_tpu.contractionpath.paths.base import CostType, Pathfinder
from tnc_tpu.tensornetwork.tensor import LeafTensor


@dataclass
class _Candidate:
    flop_cost: float
    size_cost: float
    parent_ids: tuple[int, int]
    child_id: int

    def sort_key(self):
        # smallest size first; ties toward larger flops (candidates.rs:26-33)
        return (self.size_cost, -self.flop_cost)


class _BranchSearch:
    """Shared DFS engine for both branch-and-bound variants."""

    def __init__(
        self,
        nbranch: int | None,
        cutoff_flops_factor: float,
        minimize: CostType,
        latencies: dict[int, float] | None,
        objective: PathObjective | None = None,
    ) -> None:
        self.nbranch = nbranch
        self.cutoff_flops_factor = cutoff_flops_factor
        self.minimize = minimize
        self.latencies = latencies  # None -> plain flops accumulation
        # objective overrides the per-pair cost (e.g. predicted seconds
        # under a CalibratedObjective); the accumulated "flops" and any
        # latencies are then in that objective's domain
        self.objective = objective

    def search(self, inputs: list[LeafTensor]) -> list[tuple[int, int]]:
        n = len(inputs)
        if n <= 1:
            return []

        self.tensors: dict[int, LeafTensor] = dict(enumerate(inputs))
        self.result_cache: dict[tuple[int, int], tuple[int, float, float]] = {}
        self.comm: dict[int, float] = (
            dict(self.latencies) if self.latencies is not None else {}
        )
        self.largest_latency = max(self.comm.values(), default=0.0)
        self.best_flops = math.inf
        self.best_size = math.inf
        self.best_triples: list[tuple[int, int, int]] = []
        self.best_progress: dict[int, float] = {}

        self._iterate(list(range(n)), [], 0.0, 0.0)

        # triples -> SSA (contractionpath.rs ssa_ordering semantics)
        from tnc_tpu.contractionpath.contraction_path import ssa_ordering

        return ssa_ordering(self.best_triples, n).toplevel

    # -- candidate assessment ----------------------------------------------

    def _assess(
        self, i: int, j: int, flops: float, size: float, remaining_len: int
    ) -> _Candidate | None:
        if self.tensors[j].size() > self.tensors[i].size():
            i, j = j, i

        cached = self.result_cache.get((i, j))
        if cached is None:
            k12 = len(self.tensors)
            ti, tj = self.tensors[i], self.tensors[j]
            if self.objective is not None:
                flops_12 = self.objective.pair_cost(ti, tj)
            elif self.latencies is not None:
                flops_12 = contract_op_cost_tensors(ti, tj)
            else:
                flops_12 = contract_cost_tensors(ti, tj)
            size_12 = contract_size_tensors(ti, tj)
            self.tensors[k12] = ti ^ tj
            self.result_cache[(i, j)] = (k12, flops_12, size_12)
        else:
            k12, flops_12, size_12 = cached

        if self.latencies is not None:
            current_flops = self.comm.get(k12)
            if current_flops is None:
                current_flops = flops_12 + max(self.comm[i], self.comm[j])
                self.comm[k12] = current_flops
        else:
            current_flops = flops + flops_12
        current_size = max(size, size_12)

        if current_flops > self.best_flops and current_size > self.best_size:
            return None
        best_at_depth = self.best_progress.setdefault(remaining_len, current_flops)
        if current_flops < best_at_depth:
            self.best_progress[remaining_len] = current_flops
        elif current_flops > self.cutoff_flops_factor * best_at_depth + (
            self.largest_latency if self.latencies is not None else 0.0
        ):
            return None

        return _Candidate(current_flops, current_size, (i, j), k12)

    def _iterate(
        self,
        remaining: list[int],
        triples: list[tuple[int, int, int]],
        flops: float,
        size: float,
    ) -> None:
        if len(remaining) == 1:
            better = (
                self.best_flops > flops
                if self.minimize is CostType.FLOPS
                else self.best_size > size
            )
            if better:
                self.best_flops = flops
                self.best_size = size
                self.best_triples = list(triples)
            return

        candidates: list[_Candidate] = []
        for a in range(len(remaining)):
            for b in range(a + 1, len(remaining)):
                cand = self._assess(
                    remaining[a], remaining[b], flops, size, len(remaining)
                )
                if cand is not None:
                    candidates.append(cand)
        candidates.sort(key=_Candidate.sort_key)
        if self.nbranch is not None:
            candidates = candidates[: self.nbranch]

        for cand in candidates:
            i, j = cand.parent_ids
            new_remaining = [r for r in remaining if r != i and r != j]
            new_remaining.append(cand.child_id)
            triples.append((i, j, cand.child_id))
            self._iterate(new_remaining, triples, cand.flop_cost, cand.size_cost)
            triples.pop()


class BranchBound(Pathfinder):
    """DFS branch-and-bound minimizing complex-op flops (or size).

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [4, 4]),
    ...     LeafTensor([1, 2], [4, 4]), LeafTensor([2, 0], [4, 4])])
    >>> result = BranchBound().find_path(tn)
    >>> len(result.replace_path().toplevel)
    2
    """

    def __init__(
        self,
        nbranch: int | None = 10,
        cutoff_flops_factor: float = 4.0,
        minimize: CostType = CostType.FLOPS,
        objective: PathObjective | None = None,
    ) -> None:
        self.nbranch = nbranch
        self.cutoff_flops_factor = cutoff_flops_factor
        self.minimize = minimize
        self.objective = objective

    def _solve_toplevel(self, inputs: list[LeafTensor]) -> list[tuple[int, int]]:
        search = _BranchSearch(
            self.nbranch, self.cutoff_flops_factor, self.minimize, None,
            self.objective,
        )
        return search.search(list(inputs))


class WeightedBranchBound(Pathfinder):
    """Branch-and-bound over the critical path with per-input latencies.

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [4, 4]),
    ...     LeafTensor([1, 2], [4, 4]), LeafTensor([2, 0], [4, 4])])
    >>> finder = WeightedBranchBound({0: 100.0, 1: 0.0, 2: 0.0})
    >>> result = finder.find_path(tn)  # defers the latency-100 input
    >>> result.replace_path().toplevel[0]
    (1, 2)
    """

    def __init__(
        self,
        latency_map: dict[int, float],
        nbranch: int | None = 10,
        cutoff_flops_factor: float = 5.0,
        minimize: CostType = CostType.FLOPS,
        objective: PathObjective | None = None,
    ) -> None:
        """``objective`` prices each fan-in contraction (default: naive
        op count). With a :class:`~tnc_tpu.contractionpath.
        contraction_cost.CalibratedObjective` the step costs are
        predicted seconds — ``latency_map`` must then be in seconds too
        (the partitions' predicted local completion times), making the
        accumulated critical path a real makespan estimate."""
        self.latency_map = dict(latency_map)
        self.nbranch = nbranch
        self.cutoff_flops_factor = cutoff_flops_factor
        self.minimize = minimize
        self.objective = objective

    def _solve_toplevel(self, inputs: list[LeafTensor]) -> list[tuple[int, int]]:
        if len(self.latency_map) != len(inputs):
            raise ValueError("latency_map must cover every input tensor")
        search = _BranchSearch(
            self.nbranch, self.cutoff_flops_factor, self.minimize,
            self.latency_map, self.objective,
        )
        return search.search(list(inputs))
