"""Tree-refinement pathfinders: annealing, reconfiguration, tempering.

Native implementations of the three cotengra tree-refinement methods the
reference bridges to Python through rustengra (all runtime-gated on a
cotengra install there, ``cotengra_check()``):

- :class:`TreeAnnealing` — simulated annealing over local tree rotations
  (``tnc/src/contractionpath/paths/tree_annealing.rs:63-71``,
  cotengra's ``simulated_anneal_tree``).
- :class:`TreeReconfigure` — iterative exact re-solving of the most
  expensive subtrees (``tree_reconfiguration.rs:54-56``,
  ``subtree_reconfigure``); thin wrapper over
  :meth:`ContractionTree.reconfigure`.
- :class:`TreeTempering` — parallel tempering: several annealing replicas
  at different temperatures with Metropolis replica exchange
  (``tree_tempering.rs:53-55``, ``parallel_temper_tree``).

Like the reference's trio these are flat single-level refiners, but they
inherit the shared nested-composite recursion from :class:`Pathfinder`,
so they also work on partitioned networks. All are deterministic for a
fixed seed.

The SA move set is the standard contraction-tree rotation: for a node
``p = (A∘B)∘C`` the two alternative associations ``(A∘C)∘B`` and
``(B∘C)∘A`` re-use the same nodes, so a move only changes one
intermediate's legs and the local cost; acceptance is Metropolis on the
log2 cost ratio, matching the reference SA's acceptance shape
(``repartitioning/simulated_annealing.rs:122-127``).
"""

from __future__ import annotations

import math
import random
from typing import Sequence

from tnc_tpu.contractionpath.contraction_tree import ContractionTree
from tnc_tpu.contractionpath.paths.base import Pathfinder
from tnc_tpu.contractionpath.paths.greedy import DEFAULT_SEED, _ssa_greedy
from tnc_tpu.tensornetwork.tensor import LeafTensor


def _initial_tree(inputs: Sequence[LeafTensor]) -> ContractionTree:
    ssa = _ssa_greedy(inputs)
    return ContractionTree.from_ssa_path(inputs, ssa)


def _check_minimize(minimize: str) -> str:
    if minimize not in ("flops", "size"):
        raise ValueError("minimize must be 'flops' or 'size'")
    return minimize


def _tree_objective(tree: ContractionTree, minimize: str) -> float:
    """Global objective matching the SA accept rule: total flops, or the
    largest intermediate tensor size."""
    if minimize == "size":
        return max(
            (tree._size(nd.legs) for nd in tree.nodes if not nd.is_leaf),
            default=0.0,
        )
    return tree.total_cost()[0]


def _local_cost(tree: ContractionTree, i: int, minimize: str) -> float:
    nd = tree.nodes[i]
    if nd.is_leaf:
        return 0.0
    if minimize == "size":
        return tree._size(nd.legs)
    return tree.node_cost(i)


def _rotation_candidates(tree: ContractionTree, p: int):
    """Yield (x, a, b, c) for p's two rotation variants: p has children
    (x, c) with x internal over (a, b); variants contract (a,c) or (b,c)
    first, re-using node x."""
    nd = tree.nodes[p]
    if nd.is_leaf:
        return
    left, right = nd.left, nd.right
    for x, c in ((left, right), (right, left)):
        xn = tree.nodes[x]
        if xn.is_leaf:
            continue
        yield x, xn.left, xn.right, c


def _apply_rotation(
    tree: ContractionTree, p: int, x: int, keep: int, other: int, c: int
) -> None:
    """Rewire ``p = (keep∘other)∘c`` into ``p = (keep∘c)∘other`` where
    ``x`` is the intermediate node (re-used for ``keep∘c``)."""
    xn = tree.nodes[x]
    xn.left, xn.right = keep, c
    xn.legs = tree.nodes[keep].legs ^ tree.nodes[c].legs
    tree.nodes[keep].parent = x
    tree.nodes[c].parent = x
    pn = tree.nodes[p]
    pn.left, pn.right = x, other
    tree.nodes[other].parent = p
    tree.nodes[x].parent = p


def _anneal(
    tree: ContractionTree,
    rng: random.Random,
    steps: int,
    t_start: float,
    t_end: float,
    minimize: str,
) -> None:
    """In-place simulated annealing over rotations; keeps the best state
    implicitly (pure improvement moves dominate at low temperature)."""
    internal = [i for i, nd in enumerate(tree.nodes) if not nd.is_leaf]
    if not internal:
        return
    for step in range(steps):
        frac = step / max(1, steps - 1)
        # log-interpolated temperature, as in the reference SA engine
        # (simulated_annealing.rs: temp from 2.0 -> 0.05)
        temp = t_start * (t_end / t_start) ** frac
        p = internal[rng.randrange(len(internal))]
        if not tree._reachable(p):
            continue
        candidates = list(_rotation_candidates(tree, p))
        if not candidates:
            continue
        x, a, b, c = candidates[rng.randrange(len(candidates))]
        keep, other = (a, b) if rng.random() < 0.5 else (b, a)

        old_cost = _local_cost(tree, x, minimize) + _local_cost(tree, p, minimize)
        new_x_legs = tree.nodes[keep].legs ^ tree.nodes[c].legs
        if minimize == "size":
            new_x_cost = tree._size(new_x_legs)
        else:
            new_x_cost = tree._size(tree.nodes[keep].legs | tree.nodes[c].legs)
        new_p_cost_legs = new_x_legs | tree.nodes[other].legs
        if minimize == "size":
            new_p_cost = tree._size(tree.nodes[p].legs)
        else:
            new_p_cost = tree._size(new_p_cost_legs)
        new_cost = new_x_cost + new_p_cost

        delta = math.log2(new_cost + 1.0) - math.log2(old_cost + 1.0)
        if delta <= 0.0 or (
            temp > 0.0 and rng.random() < math.exp(-delta / temp)
        ):
            _apply_rotation(tree, p, x, keep, other, c)


class TreeAnnealing(Pathfinder):
    """Simulated-annealing tree refinement
    (``tree_annealing.rs``; greedy init + rotation SA).

    >>> from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor
    >>> tn = CompositeTensor([LeafTensor([0, 1], [4, 4]),
    ...     LeafTensor([1, 2], [4, 4]), LeafTensor([2, 0], [4, 4])])
    >>> result = TreeAnnealing(iterations=5, seed=1).find_path(tn)
    >>> len(result.replace_path().toplevel), result.flops > 0
    (2, True)
    """

    def __init__(
        self,
        iterations: int = 40,
        t_start: float = 2.0,
        t_end: float = 0.05,
        minimize: str = "flops",
        seed: int = DEFAULT_SEED,
    ):
        self.iterations = iterations
        self.t_start = t_start
        self.t_end = t_end
        self.minimize = _check_minimize(minimize)
        self.seed = seed

    def _solve_toplevel(self, inputs: list) -> list[tuple[int, int]]:
        if len(inputs) <= 1:
            return []
        rng = random.Random(self.seed)
        tree = _initial_tree(inputs)
        best = tree.copy()
        best_cost = _tree_objective(tree, self.minimize)
        steps = max(64, self.iterations * len(inputs))
        chunks = 8
        for _ in range(chunks):
            _anneal(
                tree, rng, steps // chunks, self.t_start, self.t_end,
                self.minimize,
            )
            cost = _tree_objective(tree, self.minimize)
            if cost < best_cost:
                best_cost = cost
                best = tree.copy()
        return best.to_ssa_path()


class TreeReconfigure(Pathfinder):
    """Subtree reconfiguration (``tree_reconfiguration.rs``): exact
    re-solving of the most expensive <=``subtree_size`` subtrees."""

    def __init__(
        self,
        subtree_size: int = 8,
        max_rounds: int = 4,
        minimize: str = "flops",
    ):
        # no seed: reconfiguration is fully deterministic (exact DP walk)
        self.subtree_size = subtree_size
        self.max_rounds = max_rounds
        self.minimize = _check_minimize(minimize)

    def _solve_toplevel(self, inputs: list) -> list[tuple[int, int]]:
        if len(inputs) <= 1:
            return []
        tree = _initial_tree(inputs)
        tree.reconfigure(
            subtree_size=self.subtree_size,
            max_rounds=self.max_rounds,
            minimize=self.minimize,
        )
        return tree.to_ssa_path()


class TreeTempering(Pathfinder):
    """Parallel tempering (``tree_tempering.rs``): annealing replicas on
    a temperature ladder with Metropolis replica exchange between
    rounds; the coldest replica's best tree wins."""

    def __init__(
        self,
        num_replicas: int = 4,
        rounds: int = 8,
        steps_per_round: int | None = None,
        t_min: float = 0.05,
        t_max: float = 2.0,
        minimize: str = "flops",
        seed: int = DEFAULT_SEED,
    ):
        self.num_replicas = max(2, num_replicas)
        self.rounds = rounds
        self.steps_per_round = steps_per_round
        self.t_min = t_min
        self.t_max = t_max
        self.minimize = _check_minimize(minimize)
        self.seed = seed

    def _solve_toplevel(self, inputs: list) -> list[tuple[int, int]]:
        if len(inputs) <= 1:
            return []
        rng = random.Random(self.seed)
        r = self.num_replicas
        temps = [
            self.t_min * (self.t_max / self.t_min) ** (i / (r - 1))
            for i in range(r)
        ]
        replicas = [_initial_tree(inputs) for _ in range(r)]
        steps = self.steps_per_round or max(32, 10 * len(inputs))

        best = replicas[0].copy()
        best_cost = _tree_objective(best, self.minimize)
        for _ in range(self.rounds):
            costs = []
            for i in range(r):
                # constant temperature within a round (t_start == t_end)
                _anneal(
                    replicas[i], rng, steps, temps[i], temps[i], self.minimize
                )
                cost = _tree_objective(replicas[i], self.minimize)
                costs.append(cost)
                if cost < best_cost:
                    best_cost = cost
                    best = replicas[i].copy()
            # Metropolis replica exchange between temperature neighbors,
            # on log2 cost (the same scale the acceptance rule uses)
            for i in range(r - 1):
                li = math.log2(costs[i] + 1.0)
                lj = math.log2(costs[i + 1] + 1.0)
                arg = (1.0 / temps[i] - 1.0 / temps[i + 1]) * (li - lj)
                if arg >= 0.0 or rng.random() < math.exp(arg):
                    replicas[i], replicas[i + 1] = replicas[i + 1], replicas[i]
                    costs[i], costs[i + 1] = costs[i + 1], costs[i]
        return best.to_ssa_path()
