"""Hyper-optimized pathfinding via recursive hypergraph bisection.

Equivalent of the reference's cotengra ``HyperOptimizer`` bridge
(``tnc/src/contractionpath/paths/hyperoptimization.rs:36-73``, which calls
cotengra's kahypar-based search through Python). This is a native
implementation of the same algorithm family, using the framework's own
multilevel partitioner:

- Build the contraction tree **top-down**: recursively bisect the
  network's hypergraph (legs = hyperedges, weight = log2(bond dim)); the
  cut structure becomes the upper tree levels.
- Below a cutoff, finish subproblems with the greedy finder.
- Run ``ntrials`` randomized trials (different seeds and imbalance
  fractions, as cotengra samples imbalance) plus a plain-greedy baseline,
  and keep the lowest predicted cost.

On Sycamore-class circuits this produces paths orders of magnitude
cheaper than pure greedy, which is why the reference reserves this finder
for its hardest benchmark configs (``BASELINE.md`` config 3).
"""

from __future__ import annotations

import math
import os
import random

from tnc_tpu.contractionpath.contraction_cost import (
    PathObjective,
    contract_path_cost,
)
from tnc_tpu.contractionpath.contraction_path import (
    ContractionPath,
    ssa_replace_ordering,
)
from tnc_tpu.contractionpath.paths.base import Pathfinder
from tnc_tpu.contractionpath.paths.greedy import _ssa_greedy
from tnc_tpu.partitioning.bisect import bisect
from tnc_tpu.partitioning.hypergraph import Hypergraph
from tnc_tpu.tensornetwork.tensor import LeafTensor


class Hyperoptimizer(Pathfinder):
    """Native recursive-bisection hyper-search with annealing polish.

    >>> import numpy as np
    >>> from tnc_tpu.builders.connectivity import ConnectivityLayout
    >>> from tnc_tpu.builders.random_circuit import random_circuit
    >>> from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
    >>> tn = random_circuit(8, 6, 0.5, 0.5, np.random.default_rng(3),
    ...                     ConnectivityLayout.LINE)
    >>> hy = Hyperoptimizer(ntrials=2, reconfigure_budget=2.0,
    ...                     polish_rounds=1, polish_steps=200)
    >>> result = hy.find_path(tn)
    >>> result.flops <= Greedy(OptMethod.GREEDY).find_path(tn).flops
    True
    """

    def __init__(
        self,
        ntrials: int = 16,
        seed: int = 42,
        cutoff: int = 12,
        imbalance_range: tuple[float, float] = (0.02, 0.40),
        minimize: str = "flops",
        reconfigure_size: int = 12,
        reconfigure_rounds: int = 6,
        reconfigure_budget: float | None = 60.0,
        reconfigure_top: int = 4,
        target_size: float | None = None,
        polish_rounds: int = 12,
        polish_steps: int = 8000,
        polish_temps: tuple[float, float] = (0.3, 0.01),
        objective: PathObjective | None = None,
        joint_slicing: bool = True,
        joint_sa_steps: int = 1200,
        joint_sa_rounds: int = 2,
    ) -> None:
        """``objective``: a :class:`~tnc_tpu.contractionpath.
        contraction_cost.PathObjective` that overrides ``minimize`` for
        candidate ranking and final selection — a
        ``CalibratedObjective`` ranks every trial, refinement result and
        polish snapshot by *predicted seconds* (and, with
        ``target_size``, prices sliced candidates with the hoist-aware
        seconds formula, dispatch overhead included). Tree-internal
        moves (reconfigure/anneal) keep minimizing ``minimize`` — the
        search heuristics stay in the cheap flop domain; the objective
        decides which resulting tree wins.

        ``target_size``: when set, the final candidate selection is
        slicing-aware — candidates are scored by their *total sliced
        flops* after greedy slicing to ``target_size`` peak elements,
        not by raw flops (a slightly worse raw path that slices well is
        the better plan on HBM-bound networks).

        ``joint_slicing`` (default on, engages only with a
        ``target_size``): slicing becomes a first-class dimension of
        the search instead of a post-pass. EVERY trial carries a
        greedily-maintained slice set and is ranked by its hoisted
        sliced cost under the budget (the incremental
        :class:`~tnc_tpu.contractionpath.sliced_cost.
        SlicedCostEvaluator` makes that a per-trial price, not a
        per-finalist one), and finalists are refined by the joint
        tree+slice SA (:func:`~tnc_tpu.contractionpath.sliced_cost.
        joint_slice_search`: rotation moves ⇄ slice-set swap moves ⇄
        exact-DP reconfiguration, all accepted under the sliced
        objective) with a classic ``slice_and_reconfigure`` repair as a
        quality floor. The winning slice set is exposed as
        ``last_slicing`` so callers seed their repair pass from it.
        ``joint_slicing=False`` forces the old optimize-then-slice
        post-pass mode (A/B comparisons; scripts/planner_quality.py
        records both). ``joint_sa_steps`` / ``joint_sa_rounds`` bound
        the per-finalist SA work.

        ``polish_rounds``: the winner gets an annealing polish — rounds
        of subtree rotations at a cooling temperature interleaved with
        exact-DP reconfiguration (the TreeAnnealing/TreeReconfigure
        combination applied to the best bisection tree instead of a
        fresh one). On Sycamore-53 m=14 the default 12×8000 polish cuts
        the final path ~4.8× beyond the refined bisection optimum
        (r3 sweep: 3.19e14 → 6.6e13 flops, sliced total 3.88e14 →
        8.4e13 at 2^29; 24 rounds reach 7.7e13 sliced) for ~1 min of
        extra planning. ``polish_rounds=0`` disables."""
        if minimize not in ("flops", "size"):
            raise ValueError("minimize must be 'flops' or 'size'")
        self.ntrials = ntrials
        self.seed = seed
        self.cutoff = cutoff
        self.imbalance_range = imbalance_range
        self.minimize = minimize
        self.reconfigure_size = reconfigure_size
        self.reconfigure_rounds = reconfigure_rounds
        self.reconfigure_budget = reconfigure_budget
        self.reconfigure_top = reconfigure_top
        self.target_size = target_size
        self.polish_rounds = polish_rounds
        self.polish_steps = polish_steps
        self.polish_temps = polish_temps
        self.objective = objective
        self.joint_slicing = joint_slicing
        self.joint_sa_steps = joint_sa_steps
        self.joint_sa_rounds = joint_sa_rounds
        #: the slice set of the most recent winning plan (joint mode
        #: only; ``None`` when the winner fits the budget unsliced) —
        #: callers seed ``slice_and_reconfigure(seed_slices=...)`` with
        #: it so the post repair is a thin pass, not a fresh search
        self.last_slicing = None

    def _solve_toplevel(self, inputs: list[LeafTensor]) -> list[tuple[int, int]]:
        self.last_slicing = None
        n = len(inputs)
        if n <= 2:
            return [(0, 1)] if n == 2 else []

        dims: dict[int, int] = {}
        for t in inputs:
            for leg, dim in t.edges():
                dims[leg] = dim

        # Preprocessing: absorb rank<=2 tensors (kets, bras, single-qubit
        # gate chains) into their neighbours. These contractions cost
        # next to nothing but shrink the graph to its rank>=3 cores,
        # which is what makes partition-based trees competitive on
        # circuit networks (cotengra's preprocessing does the same).
        prefix, legs_map, next_id = _simplify(
            {i: frozenset(t.legs) for i, t in enumerate(inputs)}, dims
        )
        core_ids = sorted(legs_map)

        candidates: list[list[tuple[int, int]]] = [
            prefix + _greedy_on(core_ids, legs_map, dims, next_id)[0]
        ]
        for path in self._run_trials(core_ids, legs_map, dims, next_id):
            candidates.append(prefix + path)

        def evaluate(candidate: list[tuple[int, int]]) -> float:
            if self.objective is not None:
                return self.objective.ssa_path_cost(inputs, candidate)
            flops, size = contract_path_cost(
                inputs,
                ssa_replace_ordering(ContractionPath.simple(candidate)),
                True,
            )
            return flops if self.minimize == "flops" else size

        sliced_cache: dict[tuple, float] = {}

        def sliced_score(candidate: list[tuple[int, int]]) -> float:
            """Cost after slicing to the HBM target *with repair*: a
            light slice-and-reconfigure pass, scored under the active
            objective (total sliced flops by default; hoist-aware
            predicted seconds under a calibrated objective). Plain
            greedy slicing without repair wildly misranks low-flops
            candidates (their naive slicing overhead is enormous, but
            reconfiguration recovers most of it). Memoized on the
            candidate path — annealing-polish snapshots repeat already
            scored trees (and the inf-fallback re-scores the winner),
            and the repair pass is far too expensive to re-run on a
            repeat."""
            from tnc_tpu.contractionpath.slicing import (
                slice_and_reconfigure,
                sliced_flops,
            )

            assert self.target_size is not None
            key = tuple(candidate)
            hit = sliced_cache.get(key)
            if hit is not None:
                return hit
            try:
                # Work-bounded repair (rounds only, no wall-clock
                # deadline) so candidate ranking is reproducible
                # run-to-run and machine-to-machine.
                replace, slicing = slice_and_reconfigure(
                    inputs,
                    candidate,
                    self.target_size,
                    reconf_rounds=1,
                    step_budget=None,
                    final_rounds=2,
                    final_budget=None,
                )
            except ValueError:
                sliced_cache[key] = math.inf
                return math.inf
            if self.objective is not None:
                score = self.objective.sliced_path_cost(
                    inputs, replace, slicing
                )
            else:
                score = sliced_flops(inputs, replace, slicing)
            sliced_cache[key] = score
            return score

        use_joint = self.target_size is not None and self.joint_slicing
        cost_model = getattr(self.objective, "cost_model", None)
        # trial key -> (greedy sliced cost, greedy slice legs)
        rank_cache: dict[tuple, tuple[float, tuple[int, ...]]] = {}
        # trial key -> (refined cost, refined ssa pairs, Slicing | None)
        final_cache: dict[tuple, tuple] = {}

        def trial_sliced_rank(candidate: list[tuple[int, int]]) -> float:
            """Joint mode, stage 1: EVERY trial carries a greedily
            maintained slice set under the budget and is ranked by its
            hoisted sliced cost (seconds under a calibrated objective)
            — the incremental evaluator prices a trial in O(deltas)
            where the classic pipeline paid a full
            slice-and-reconfigure per finalist."""
            key = tuple(candidate)
            hit = rank_cache.get(key)
            if hit is not None:
                return hit[0]
            from tnc_tpu.contractionpath.sliced_cost import (
                SlicedCostEvaluator,
                greedy_slice_to_target,
            )

            replace = ssa_replace_ordering(
                ContractionPath.simple(list(candidate))
            ).toplevel
            ev = SlicedCostEvaluator(inputs, replace, cost_model=cost_model)
            try:
                greedy_slice_to_target(ev, self.target_size)
                entry = (ev.cost(), tuple(sorted(ev.removed)))
            except ValueError:
                entry = (math.inf, ())
            rank_cache[key] = entry
            return entry[0]

        def joint_final(candidate: list[tuple[int, int]]) -> tuple:
            """Joint mode, stage 2 (finalists + polish snapshots):
            refine tree and slice set TOGETHER (SA rotations ⇄ slice
            swaps ⇄ sliced-objective DP reconfiguration), floored by
            the classic bounded repair so the joint mode can only match
            or beat the post-pass pipeline. Memoized like
            :func:`sliced_score`."""
            key = tuple(candidate)
            hit = final_cache.get(key)
            if hit is not None:
                return hit
            from tnc_tpu.contractionpath.sliced_cost import (
                SlicedCostEvaluator,
                joint_slice_search,
            )
            from tnc_tpu.contractionpath.slicing import (
                slice_and_reconfigure,
            )

            score0 = trial_sliced_rank(candidate)
            seed_legs = rank_cache[tuple(candidate)][1]
            if math.isinf(score0):
                entry = (math.inf, list(candidate), None, math.inf)
            elif not seed_legs:
                # fits the budget unsliced: nothing to search jointly
                entry = (score0, list(candidate), None, score0)
            else:
                pairs, slicing, cost = joint_slice_search(
                    inputs,
                    candidate,
                    self.target_size,
                    seed_slices=seed_legs,
                    cost_model=cost_model,
                    sa_steps=self.joint_sa_steps,
                    sa_rounds=self.joint_sa_rounds,
                    seed=self.seed,
                    temps=self.polish_temps,
                )
                legacy_floor = math.inf
                try:
                    replace2, s2 = slice_and_reconfigure(
                        inputs,
                        candidate,
                        self.target_size,
                        reconf_rounds=1,
                        step_budget=None,
                        final_rounds=2,
                        final_budget=None,
                        cost_model=cost_model,
                    )
                except ValueError:
                    replace2 = None
                if replace2 is not None:
                    from tnc_tpu.contractionpath.slicing import (
                        sliced_flops,
                    )

                    ev2 = SlicedCostEvaluator(
                        inputs,
                        list(replace2),
                        removed=s2.legs,
                        cost_model=cost_model,
                    )
                    floor_cost = ev2.cost()
                    # the score the POST-PASS pipeline would have given
                    # this candidate (sliced_score's metric) — used to
                    # find the trajectory that pipeline would polish
                    legacy_floor = (
                        self.objective.sliced_path_cost(
                            inputs, replace2, s2
                        )
                        if self.objective is not None
                        else sliced_flops(inputs, replace2, s2)
                    )
                entry = (cost, pairs, slicing, legacy_floor)
                if replace2 is not None and floor_cost < cost:
                    from tnc_tpu.contractionpath.contraction_path import (
                        replace_ssa_ordering,
                    )

                    entry = (
                        floor_cost,
                        replace_ssa_ordering(list(replace2), len(inputs)),
                        s2,
                        legacy_floor,
                    )
            final_cache[key] = entry
            return entry

        ranked = sorted(
            candidates, key=trial_sliced_rank if use_joint else evaluate
        )

        # Refine the best few candidates by exact-DP subtree
        # reconfiguration (the reference's TreeReconfigure capability,
        # natively): different bisection trees settle into different
        # local minima, so refining several beats refining one.
        top = max(1, self.reconfigure_top)
        finalists = ranked[:top]
        evaluate_side: list[list[tuple[int, int]]] = []
        if use_joint:
            # hedge the finalist pool with the raw-objective ranking:
            # greedy-maintained slice sets are unrepaired, and on
            # treewidth-class networks they misrank candidates whose
            # slicing overhead repair would recover — carrying the
            # post-pass pipeline's own finalists (plus its unrefined
            # guard) means the per-finalist repair floor covers every
            # candidate that pipeline could have picked
            evaluate_side = sorted(candidates, key=evaluate)[:top]
            seen_f: set[tuple] = set()
            finalists = []
            for candidate in ranked[:top] + evaluate_side:
                key = tuple(candidate)
                if key not in seen_f:
                    seen_f.add(key)
                    finalists.append(candidate)
        # the post-pass pipeline's candidate pool, rebuilt inside the
        # joint pool (refined below in lockstep): polish is strongly
        # path-dependent, so the joint mode must also anneal the exact
        # trajectory that pipeline would have polished
        post_pool: list[list[tuple[int, int]]] = []
        if self.reconfigure_rounds > 0:
            from tnc_tpu.contractionpath.contraction_tree import ContractionTree

            refined: list[list[tuple[int, int]]] = []
            for candidate in finalists:
                tree = ContractionTree.from_ssa_path(inputs, candidate)
                tree.reconfigure(
                    self.reconfigure_size,
                    self.reconfigure_rounds,
                    minimize=self.minimize,
                    time_budget=self.reconfigure_budget,
                )
                refined.append(tree.to_ssa_path())
            if use_joint:
                eval_keys = {tuple(c) for c in evaluate_side}
                post_pool = [
                    r
                    for f, r in zip(finalists, refined)
                    if tuple(f) in eval_keys
                ]
                post_pool.append(evaluate_side[0])
            # The refined trees dominate their raw versions on both raw
            # and sliced scores; keep the best raw candidate as a guard.
            finalists = refined + [ranked[0]] + post_pool[-1:]
        elif use_joint:
            post_pool = list(evaluate_side)
            finalists = finalists + post_pool[:1]

        # Dedup (reconfigure often leaves a good tree unchanged) so the
        # expensive sliced_score never runs twice on the same path.
        seen: set[tuple] = set()
        unique = []
        for candidate in finalists:
            key = tuple(candidate)
            if key not in seen:
                seen.add(key)
                unique.append(candidate)

        if self.target_size is not None:
            score_fn = (
                (lambda c: joint_final(c)[0]) if use_joint else sliced_score
            )
            scored = [(score_fn(c), c) for c in unique]
            winner_score, winner = min(scored, key=lambda p: p[0])
            if math.isinf(winner_score):
                # No finalist could be sliced to the target: fall back to
                # the raw-flops ranking explicitly (an arbitrary
                # inf-scored pick would defer the failure to the caller's
                # own slicing attempt, far from this decision).
                winner = min(unique, key=evaluate)
                winner_score = score_fn(winner)
            final_score = score_fn
        else:
            winner = min(unique, key=evaluate)
            winner_score = evaluate(winner)
            final_score = evaluate

        # Annealing polish: every round's snapshot competes under the
        # SAME objective as the final selection (in slicing-aware mode a
        # raw-flops-worse tree can be the sliced-flops winner).
        polish_seeds = [winner]
        if use_joint and post_pool:
            # polish is strongly path-dependent (on treewidth-class
            # networks it cuts the final plan several-fold), so the
            # joint mode also anneals the trajectory the POST-PASS
            # pipeline would have polished: the winner of ITS OWN
            # finalist pool under ITS OWN scoring (the classic
            # bounded-repair floor). Without this hedge a
            # sliced-selection winner whose basin polishes poorly can
            # lose to the old pipeline.
            floor_winner = min(
                post_pool, key=lambda c: joint_final(c)[3]
            )
            if tuple(floor_winner) != tuple(winner):
                polish_seeds.append(floor_winner)
        best_path, best_score = winner, winner_score
        for polish_seed in polish_seeds:
            for snapshot in self._polish(inputs, polish_seed):
                s = final_score(snapshot)
                if s < best_score:
                    best_path, best_score = snapshot, s
        if use_joint:
            # the winner's *refined* tree (the joint search moved it)
            # and its slice set are the plan; expose the slice set so
            # the caller's slice_and_reconfigure is a seeded thin
            # repair instead of a fresh post-pass search
            _, refined_pairs, slicing, _ = joint_final(best_path)
            if refined_pairs is not None and not math.isinf(
                final_score(best_path)
            ):
                self.last_slicing = slicing
                return refined_pairs
        return best_path

    def _run_trials(
        self,
        core_ids: list[int],
        legs_map: dict[int, frozenset[int]],
        dims: dict[int, int],
        next_id: int,
    ) -> list[list[tuple[int, int]]]:
        """The ``ntrials`` randomized bisection trials, fanned out over a
        spawn-safe process pool when the host has cores to spare — the
        rayon-style search parallelism the reference applies to its SA
        trials (``repartitioning/simulated_annealing.rs:113-135``),
        applied to the hyper search (VERDICT r3 #8).

        Deterministic merge: trial ``t`` always uses
        ``random.Random(seed + t)``, and results come back indexed by
        trial, so the candidate list — and the winning path — is
        identical to the serial loop's at any worker count
        (``TNC_TPU_HYPER_WORKERS`` overrides; <=1 forces serial).
        """
        spec = (
            core_ids,
            legs_map,
            dims,
            next_id,
            self.cutoff,
            self.seed,
            self.imbalance_range,
        )
        env = os.environ.get("TNC_TPU_HYPER_WORKERS")
        workers = int(env) if env else (os.cpu_count() or 1)
        workers = max(1, min(workers, self.ntrials))
        # pool startup (spawn + package re-import) costs seconds; only
        # worth it when trials are individually expensive. Unless the
        # env knob explicitly asks for a pool, gate on problem size —
        # small searches (most planning calls) stay serial.
        if env is None and len(core_ids) < 64:
            workers = 1
        if workers > 1:
            import concurrent.futures
            import multiprocessing
            import pickle

            try:
                ctx = multiprocessing.get_context("spawn")
                with concurrent.futures.ProcessPoolExecutor(
                    max_workers=workers,
                    mp_context=ctx,
                    initializer=_trials_init,
                    initargs=(pickle.dumps(spec),),
                ) as pool:
                    return list(pool.map(_trial_worker, range(self.ntrials)))
            except Exception:  # pool failure: the serial loop is law
                pass
        return [_one_trial(spec, t) for t in range(self.ntrials)]

    def _polish(
        self, inputs: list[LeafTensor], candidate: list[tuple[int, int]]
    ) -> list[list[tuple[int, int]]]:
        """Annealing polish of the winning tree: rounds of Metropolis
        subtree rotations at a cooling temperature, each followed by
        exact-DP reconfiguration. Returns the deduplicated per-round
        snapshots that improved the raw objective at least once
        (annealing legitimately regresses between rounds); the caller
        scores them under the final-selection objective."""
        if self.polish_rounds <= 0 or len(inputs) <= 2:
            return []
        from tnc_tpu.contractionpath.contraction_tree import ContractionTree
        from tnc_tpu.contractionpath.paths.tree_refine import (
            _anneal,
            _tree_objective,
        )

        rng = random.Random(self.seed ^ 0x9E3779B9)
        tree = ContractionTree.from_ssa_path(inputs, list(candidate))
        t_hi, t_lo = self.polish_temps
        snapshots: list[list[tuple[int, int]]] = []
        seen: set[tuple] = {tuple(candidate)}
        best_obj = _tree_objective(tree, self.minimize)
        for _ in range(self.polish_rounds):
            _anneal(tree, rng, self.polish_steps, t_hi, t_lo, self.minimize)
            tree.reconfigure(
                self.reconfigure_size,
                2,
                minimize=self.minimize,
                time_budget=self.reconfigure_budget,
            )
            obj = _tree_objective(tree, self.minimize)
            if obj < best_obj * 1.5:  # skip clearly-regressed rounds
                best_obj = min(best_obj, obj)
                path = tree.to_ssa_path()
                key = tuple(path)
                if key not in seen:
                    seen.add(key)
                    snapshots.append(path)
        return snapshots

    def _bisection_path(
        self,
        core_ids: list[int],
        legs_map: dict[int, frozenset[int]],
        dims: dict[int, int],
        start_id: int,
        rng: random.Random,
        imbalance: float,
    ) -> list[tuple[int, int]]:
        return _bisection_path_impl(
            core_ids, legs_map, dims, start_id, rng, imbalance, self.cutoff
        )


def _bisection_path_impl(
    core_ids: list[int],
    legs_map: dict[int, frozenset[int]],
    dims: dict[int, int],
    start_id: int,
    rng: random.Random,
    imbalance: float,
    cutoff: int,
    discount_legs: frozenset[int] | None = None,
    discount_weight: float = 0.125,
) -> list[tuple[int, int]]:
    """One randomized top-down bisection trial (module-level so the
    trial pool's spawn workers can run it).

    ``discount_legs`` makes the cut slice-aware: legs in the set (a
    candidate slice set) get cut weight ``discount_weight`` instead of
    ``log2(bond dim)``, steering the partitioner toward cutting legs
    that will be sliced away anyway. An explicit weight override is
    required — dim-based discounting is a no-op on bond-dimension-2
    circuit legs, where ``log2(max(2, d))`` is 1 for every leg."""
    legs = dict(legs_map)
    next_id = start_id
    ssa_path: list[tuple[int, int]] = []

    def greedy_finish(ids: list[int]) -> int:
        """Contract a small set of (global-id) tensors with greedy."""
        nonlocal next_id
        local_tensors = [
            LeafTensor(sorted(legs[i]), [dims[l] for l in sorted(legs[i])])
            for i in ids
        ]
        local_pairs = _ssa_greedy(local_tensors)
        m = len(ids)
        local_to_global = {i: ids[i] for i in range(m)}
        last = ids[0]
        for a, b in local_pairs:
            ga = local_to_global[a]
            gb = local_to_global[b]
            ssa_path.append((ga, gb))
            legs[next_id] = legs[ga] ^ legs[gb]
            local_to_global[m] = next_id
            m += 1
            last = next_id
            next_id += 1
        return last

    def solve(ids: list[int]) -> int:
        nonlocal next_id
        if len(ids) == 1:
            return ids[0]
        if len(ids) <= cutoff:
            return greedy_finish(ids)

        # Sub-hypergraph over `ids`
        index = {v: i for i, v in enumerate(ids)}
        pin_lists: dict[int, list[int]] = {}
        for v in ids:
            for leg in legs[v]:
                pin_lists.setdefault(leg, []).append(index[v])
        edge_pins = []
        edge_weights = []
        for leg, pins in pin_lists.items():
            if len(pins) >= 2:
                edge_pins.append(pins)
                if discount_legs is not None and leg in discount_legs:
                    edge_weights.append(discount_weight)
                else:
                    edge_weights.append(math.log2(max(2, dims[leg])))
        sub = Hypergraph(len(ids), [1.0] * len(ids), edge_pins, edge_weights)
        sides = bisect(sub, imbalance, rng)
        left = [v for v, s in zip(ids, sides) if s == 0]
        right = [v for v, s in zip(ids, sides) if s == 1]
        if not left or not right:
            return greedy_finish(ids)
        a = solve(left)
        b = solve(right)
        ssa_path.append((a, b))
        legs[next_id] = legs[a] ^ legs[b]
        result = next_id
        next_id += 1
        return result

    solve(list(core_ids))
    return ssa_path


_TRIALS_SPEC = None


def _trials_init(blob: bytes) -> None:
    import pickle

    global _TRIALS_SPEC
    _TRIALS_SPEC = pickle.loads(blob)


def _trial_worker(trial: int) -> list[tuple[int, int]]:
    assert _TRIALS_SPEC is not None
    return _one_trial(_TRIALS_SPEC, trial)


def _one_trial(spec, trial: int) -> list[tuple[int, int]]:
    """Trial ``trial`` of the hyper search — identical draw discipline
    to the original serial loop (``Random(seed + trial)`` drives both
    the imbalance sample and the bisection), so serial and pooled runs
    produce byte-identical candidates."""
    core_ids, legs_map, dims, next_id, cutoff, seed, (lo, hi) = spec
    rng = random.Random(seed + trial)
    imbalance = lo + (hi - lo) * rng.random()
    return _bisection_path_impl(
        core_ids, legs_map, dims, next_id, rng, imbalance, cutoff
    )


def _simplify(
    legs: dict[int, frozenset[int]], dims: dict[int, int]
) -> tuple[list[tuple[int, int]], dict[int, frozenset[int]], int]:
    """Absorb every rank<=2 tensor into a neighbour sharing a leg.

    Returns (ssa prefix pairs, surviving id -> legs, next free ssa id).
    Tensors sharing no leg with anyone are left for the outer search's
    outer-product handling.
    """
    legs = dict(legs)
    next_id = max(legs) + 1 if legs else 0
    pairs: list[tuple[int, int]] = []

    leg_owners: dict[int, set[int]] = {}
    for i, ls in legs.items():
        for leg in ls:
            leg_owners.setdefault(leg, set()).add(i)

    from collections import deque

    queue = deque(i for i, ls in legs.items() if len(ls) <= 2)
    while queue:
        i = queue.popleft()
        if i not in legs or len(legs[i]) > 2:
            continue
        if len(legs) <= 2:
            break
        # find a neighbour (prefer the smallest) sharing any leg
        neighbour = -1
        neighbour_rank = 1 << 30
        for leg in legs[i]:
            for j in leg_owners.get(leg, ()):
                if j != i and j in legs and len(legs[j]) < neighbour_rank:
                    neighbour = j
                    neighbour_rank = len(legs[j])
        if neighbour < 0:
            continue  # disconnected scalar/vector; leave it
        merged = legs[i] ^ legs[neighbour]
        pairs.append((i, neighbour))
        for leg in legs[i] | legs[neighbour]:
            owners = leg_owners.get(leg)
            if owners is not None:
                owners.discard(i)
                owners.discard(neighbour)
        del legs[i], legs[neighbour]
        new_id = next_id
        next_id += 1
        legs[new_id] = merged
        for leg in merged:
            leg_owners.setdefault(leg, set()).add(new_id)
        if len(merged) <= 2:
            queue.append(new_id)
        # neighbours of the merged tensor may have become absorbable
        # (not strictly needed: ranks only shrink via future merges)

    return pairs, legs, next_id


def _greedy_on(
    core_ids: list[int],
    legs_map: dict[int, frozenset[int]],
    dims: dict[int, int],
    start_id: int,
) -> tuple[list[tuple[int, int]], int]:
    """Run the greedy finder over surviving cores, mapping local ssa ids
    back to global ids."""
    local_tensors = [
        LeafTensor(sorted(legs_map[i]), [dims[l] for l in sorted(legs_map[i])])
        for i in core_ids
    ]
    local_pairs = _ssa_greedy(local_tensors)
    m = len(core_ids)
    to_global = {k: core_ids[k] for k in range(m)}
    out: list[tuple[int, int]] = []
    next_id = start_id
    for a, b in local_pairs:
        out.append((to_global[a], to_global[b]))
        to_global[m] = next_id
        m += 1
        next_id += 1
    return out, next_id
