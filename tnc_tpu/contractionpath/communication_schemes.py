"""Communication scheduling for partitioned contraction.

Mirror of ``tnc/src/contractionpath/communication_schemes.rs:19-73``: once
each partition has contracted locally, the partitions' result tensors must
be combined. The pair order of that fan-in *is* the inter-device
communication schedule (``mpi/communication.rs:199-249``; in this
framework it drives mesh collectives instead of MPI sends), and the right
objective is the **critical path** including each partition's local
completion latency.

Six schemes, as in the reference:

- ``GREEDY`` / ``RANDOM_GREEDY`` — the greedy pathfinders over the
  partition result tensors (latencies ignored).
- ``BIPARTITION`` — recursive 2-cut of the result tensors, larger tensor
  kept left (``communication_schemes.rs:147-212``).
- ``BIPARTITION_SWEEP`` — 20 random imbalances in [0.01, 0.5], keep the
  best critical-path cost (``communication_schemes.rs:91-123``).
- ``WEIGHTED_BRANCH_BOUND`` — latency-aware branch-and-bound.
- ``BRANCH_BOUND`` — same engine with zero latencies.

All schemes return a **replace-format** flat path over the partition
indices.
"""

from __future__ import annotations

import enum
import random
from typing import Sequence

from tnc_tpu.contractionpath.contraction_cost import (
    CalibratedObjective,
    communication_path_cost,
)
from tnc_tpu.contractionpath.contraction_path import SimplePath  # noqa: F401
from tnc_tpu.contractionpath.paths.branchbound import WeightedBranchBound
from tnc_tpu.contractionpath.paths.greedy import Greedy, OptMethod
from tnc_tpu.partitioning.bisect import bisect
from tnc_tpu.partitioning.hypergraph import hypergraph_from_tensors
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor


def calibrated_latency_map(
    local_flops: dict[int, float],
    cost_model,
    local_steps: dict[int, float] | None = None,
) -> dict[int, float]:
    """Per-partition fan-in latencies in predicted **seconds**.

    ``local_flops[i]`` is partition ``i``'s local contraction op count
    and ``local_steps[i]`` its step count (dispatch overhead is charged
    per step; defaults to 1). The result is what the latency-aware
    schemes should receive instead of raw flop counts once a
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` is available —
    mixing flop latencies with seconds step costs (or vice versa) makes
    the critical path meaningless.

    >>> from tnc_tpu.obs.calibrate import CalibratedCostModel
    >>> m = CalibratedCostModel(flops_per_s=1e9, dispatch_s=1e-3)
    >>> calibrated_latency_map({0: 1e6, 1: 0.0}, m)[0]
    0.002
    """
    out: dict[int, float] = {}
    for i, flops in local_flops.items():
        steps = 1.0 if local_steps is None else max(local_steps.get(i, 1.0), 1.0)
        out[i] = cost_model.op_seconds(flops, dispatches=steps)
    return out


def fanin_levels(
    toplevel: Sequence[tuple[int, int]],
) -> list[list[tuple[int, int]]]:
    """Group a replace-format fan-in path into dependency **levels**:
    every pair within a level touches disjoint indices, so all of a
    level's contractions are independent and may dispatch concurrently;
    a pair lands one level past the deepest level either operand was
    last produced in. This is the overlap schedule the pod executor
    runs (``intermediate_reduce``): same-level pairs dispatch without
    intervening host synchronization, levels execute in order.

    The schedule is derived from the communication scheme's path, so a
    latency-aware scheme (priced with the calibrated latency map) still
    controls WHICH pairs exist and their tree shape — levels only make
    the independence that was already in the tree explicit.

    Disjointness within a level holds by construction: a pair at level
    ``L`` bumps its surviving index ``x`` to depth ``L+1``, so any later
    pair touching ``x`` is scheduled at ``L+1`` or deeper, and consumed
    ``y`` indices never reappear (``_fanin_survivor`` validates that).

    >>> fanin_levels([(0, 1), (2, 3), (0, 2)])
    [[(0, 1), (2, 3)], [(0, 2)]]
    >>> fanin_levels([(0, 1), (0, 2), (0, 3)])
    [[(0, 1)], [(0, 2)], [(0, 3)]]
    """
    depth: dict[int, int] = {}
    levels: list[list[tuple[int, int]]] = []
    for x, y in toplevel:
        level = max(depth.get(x, 0), depth.get(y, 0))
        if level == len(levels):
            levels.append([])
        levels[level].append((x, y))
        depth[x] = level + 1
    return levels


class CommunicationScheme(enum.Enum):
    GREEDY = "greedy"
    RANDOM_GREEDY = "random_greedy"
    BIPARTITION = "bipartition"
    BIPARTITION_SWEEP = "bipartition_sweep"
    WEIGHTED_BRANCH_BOUND = "weightedbranchbound"
    BRANCH_BOUND = "branchbound"

    def communication_path(
        self,
        children_tensors: Sequence[LeafTensor],
        latency_map: dict[int, float] | None = None,
        rng: random.Random | None = None,
        cost_model=None,
    ) -> list[tuple[int, int]]:
        """Replace-format fan-in path over the partition tensors.

        ``cost_model`` (a :class:`~tnc_tpu.obs.calibrate.
        CalibratedCostModel`) switches the latency-aware schemes to the
        seconds domain: fan-in steps are priced as predicted step
        seconds, and ``latency_map`` is expected in seconds too
        (:func:`calibrated_latency_map`).

        >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
        >>> parts = [LeafTensor([0, 1], [4, 4]), LeafTensor([1, 2], [4, 4]),
        ...          LeafTensor([2, 0], [4, 4])]
        >>> sorted(CommunicationScheme.GREEDY.communication_path(parts))
        [(0, 1), (0, 2)]
        >>> CommunicationScheme.WEIGHTED_BRANCH_BOUND.communication_path(
        ...     parts, {0: 1000.0, 1: 0.0, 2: 0.0})[0]  # defer latency-1000
        (1, 2)
        """
        if latency_map is None:
            latency_map = {i: 0.0 for i in range(len(children_tensors))}
        if len(children_tensors) <= 1:
            return []

        if self is CommunicationScheme.GREEDY:
            return _greedy_path(children_tensors, OptMethod.GREEDY)
        if self is CommunicationScheme.RANDOM_GREEDY:
            return _greedy_path(children_tensors, OptMethod.RANDOM_GREEDY)
        if self is CommunicationScheme.BIPARTITION:
            return _tensor_bipartition(list(enumerate(children_tensors)), 0.03)
        if self is CommunicationScheme.BIPARTITION_SWEEP:
            if rng is None:
                raise ValueError("BIPARTITION_SWEEP requires a random generator")
            return _bipartition_sweep(
                children_tensors, latency_map, rng, cost_model=cost_model
            )
        if self is CommunicationScheme.WEIGHTED_BRANCH_BOUND:
            return _branchbound_path(
                children_tensors, latency_map, cost_model
            )
        if self is CommunicationScheme.BRANCH_BOUND:
            zero = {i: 0.0 for i in range(len(children_tensors))}
            return _branchbound_path(children_tensors, zero, cost_model)
        raise ValueError(self)  # pragma: no cover


def _greedy_path(
    children_tensors: Sequence[LeafTensor], method: OptMethod
) -> list[tuple[int, int]]:
    tn = CompositeTensor([t.copy() for t in children_tensors])
    result = Greedy(method).find_path(tn)
    return result.replace_path().toplevel


def _branchbound_path(
    children_tensors: Sequence[LeafTensor],
    latency_map: dict[int, float],
    cost_model=None,
) -> list[tuple[int, int]]:
    tn = CompositeTensor([t.copy() for t in children_tensors])
    objective = (
        CalibratedObjective(cost_model) if cost_model is not None else None
    )
    finder = WeightedBranchBound(
        latency_map, nbranch=10, cutoff_flops_factor=5.0, objective=objective
    )
    return finder.find_path(tn).replace_path().toplevel


def _bipartition_sweep(
    children_tensors: Sequence[LeafTensor],
    latency_map: dict[int, float],
    rng: random.Random,
    sweeps: int = 20,
    cost_model=None,
) -> list[tuple[int, int]]:
    latencies = [latency_map[i] for i in sorted(latency_map)]
    pair_cost = (
        CalibratedObjective(cost_model).pair_cost
        if cost_model is not None
        else None
    )
    best_cost = float("inf")
    best_path: list[tuple[int, int]] = []
    for _ in range(sweeps):
        imbalance = 0.01 + rng.random() * 0.49
        path = _tensor_bipartition(list(enumerate(children_tensors)), imbalance, rng)
        cost, _ = communication_path_cost(
            children_tensors, path, True, True, latencies,
            cost_function=pair_cost,
        )
        if cost < best_cost:
            best_cost = cost
            best_path = path
    return best_path


def _tensor_bipartition(
    children: list[tuple[int, LeafTensor]],
    imbalance: float,
    rng: random.Random | None = None,
) -> list[tuple[int, int]]:
    """Recursive bipartition fan-in; result replaces the larger side's id
    (``communication_schemes.rs:147-212``)."""
    _, _, path = _tensor_bipartition_recursive(children, imbalance, rng)
    return path


def _tensor_bipartition_recursive(
    children: list[tuple[int, LeafTensor]],
    imbalance: float,
    rng: random.Random | None,
) -> tuple[int, LeafTensor, list[tuple[int, int]]]:
    if len(children) == 1:
        return children[0][0], children[0][1], []
    if len(children) == 2:
        (ia, ta), (ib, tb) = children
        if tb.size() > ta.size():
            ia, ib = ib, ia
        return ia, ta ^ tb, [(ia, ib)]

    hg = hypergraph_from_tensors([t for _, t in children])
    sides = bisect(hg, imbalance, rng or random.Random(42))
    left = [c for c, s in zip(children, sides) if s == 0]
    right = [c for c, s in zip(children, sides) if s == 1]
    if not left or not right:
        half = len(children) // 2
        left, right = children[:half], children[half:]

    id1, t1, path1 = _tensor_bipartition_recursive(left, imbalance, rng)
    id2, t2, path2 = _tensor_bipartition_recursive(right, imbalance, rng)
    out = t1 ^ t2
    if t2.size() > t1.size():
        id1, id2 = id2, id1
    combined = path1 + path2
    combined.append((id1, id2))
    return id1, out, combined
