"""Explicit contraction-tree representation and local refinement.

Parity with the reference's ``ContractionTree``
(``tnc/src/contractionpath/contraction_tree.rs:20-27``): an explicit
binary tree over a flat contraction path, supporting conversion to/from
SSA paths, per-node cost weights (``tree_weights``,
``contraction_tree.rs:303-314``), and mutation.

On top of it, :meth:`ContractionTree.reconfigure` implements subtree
reconfiguration — the refinement the reference reaches through cotengra's
``subtree_reconfigure`` (``paths/tree_reconfiguration.rs:54-56``): pick
the most expensive subtrees, re-solve their local contraction order
exactly (subset DP over <= ``subtree_size`` frontier nodes), splice the
improvement back, repeat until converged.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Sequence

from tnc_tpu.tensornetwork.tensor import LeafTensor


def _has_native_dp() -> bool:
    from tnc_tpu.partitioning.native_binding import load_native

    lib = load_native()
    return lib is not None and hasattr(lib, "tnc_optimal_order")


@dataclass
class _Node:
    left: int = -1
    right: int = -1
    parent: int = -1
    legs: frozenset[int] = field(default_factory=frozenset)

    @property
    def is_leaf(self) -> bool:
        return self.left < 0


class ContractionTree:
    """Binary contraction tree over ``n`` leaf tensors.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> ts = [LeafTensor([0, 1], [4, 4]), LeafTensor([1, 2], [4, 4]),
    ...       LeafTensor([2, 0], [4, 4])]
    >>> tree = ContractionTree.from_ssa_path(ts, [(0, 1), (3, 2)])
    >>> tree.to_ssa_path()
    [(0, 1), (3, 2)]
    >>> flops, peak = tree.total_cost()
    >>> flops > 0 and peak >= 48.0
    True
    """

    def __init__(self, leaf_legs: Sequence[frozenset[int]], dims: dict[int, int]):
        self.dims = dims
        self.nodes: list[_Node] = [_Node(legs=l) for l in leaf_legs]
        self.num_leaves = len(self.nodes)
        self.root = -1

    # -- construction -------------------------------------------------------

    @classmethod
    def from_ssa_path(
        cls,
        inputs: Sequence[LeafTensor],
        ssa_pairs: Sequence[tuple[int, int]],
    ) -> "ContractionTree":
        dims: dict[int, int] = {}
        for t in inputs:
            for leg, dim in t.edges():
                dims[leg] = dim
        tree = cls([frozenset(t.legs) for t in inputs], dims)
        for a, b in ssa_pairs:
            tree._join(a, b)
        roots = [i for i, nd in enumerate(tree.nodes) if nd.parent < 0]
        if len(roots) != 1:
            raise ValueError(f"path does not form a single tree ({len(roots)} roots)")
        tree.root = roots[0]
        return tree

    def _join(self, a: int, b: int) -> int:
        new_id = len(self.nodes)
        self.nodes.append(
            _Node(left=a, right=b, legs=self.nodes[a].legs ^ self.nodes[b].legs)
        )
        self.nodes[a].parent = new_id
        self.nodes[b].parent = new_id
        return new_id

    def copy(self) -> "ContractionTree":
        """Deep copy (used by the tempering replicas)."""
        out = ContractionTree.__new__(ContractionTree)
        out.dims = self.dims
        out.nodes = [
            _Node(nd.left, nd.right, nd.parent, nd.legs) for nd in self.nodes
        ]
        out.num_leaves = self.num_leaves
        out.root = self.root
        return out

    # -- queries ------------------------------------------------------------

    def _size(self, legs: frozenset[int]) -> float:
        out = 1.0
        for leg in legs:
            out *= self.dims[leg]
        return out

    def node_cost(self, i: int) -> float:
        """Naive op cost of the contraction forming node ``i``."""
        nd = self.nodes[i]
        if nd.is_leaf:
            return 0.0
        union = self.nodes[nd.left].legs | self.nodes[nd.right].legs
        return self._size(union)

    def total_cost(self) -> tuple[float, float]:
        """(total naive flops, peak out+in1+in2 size) of the whole tree."""
        flops = 0.0
        peak = 0.0
        stack = [self.root]
        while stack:
            i = stack.pop()
            nd = self.nodes[i]
            if nd.is_leaf:
                continue
            flops += self.node_cost(i)
            step = (
                self._size(nd.legs)
                + self._size(self.nodes[nd.left].legs)
                + self._size(self.nodes[nd.right].legs)
            )
            peak = max(peak, step)
            stack.append(nd.left)
            stack.append(nd.right)
        return flops, peak

    def _postorder(self) -> list[int]:
        """Iterative post-order over the subtree of ``root`` (deep
        caterpillar trees exceed Python's recursion limit)."""
        order: list[int] = []
        stack = [self.root]
        while stack:
            i = stack.pop()
            order.append(i)
            nd = self.nodes[i]
            if not nd.is_leaf:
                stack.append(nd.left)
                stack.append(nd.right)
        order.reverse()
        return order

    def tree_weights(self) -> dict[int, float]:
        """Accumulated contraction cost per node
        (``contraction_tree.rs:303-314``)."""
        weights: dict[int, float] = {}
        for i in self._postorder():
            nd = self.nodes[i]
            if nd.is_leaf:
                weights[i] = 0.0
            else:
                weights[i] = (
                    weights[nd.left] + weights[nd.right] + self.node_cost(i)
                )
        return weights

    def to_ssa_path(self) -> list[tuple[int, int]]:
        """Post-order SSA pair emission (leaves keep their original ids)."""
        ssa_of: dict[int, int] = {}
        next_id = self.num_leaves
        pairs: list[tuple[int, int]] = []
        for i in self._postorder():
            nd = self.nodes[i]
            if nd.is_leaf:
                ssa_of[i] = i
                continue
            pairs.append((ssa_of[nd.left], ssa_of[nd.right]))
            ssa_of[i] = next_id
            next_id += 1
        return pairs

    # -- subtree reconfiguration -------------------------------------------

    def _collect_frontier(self, top: int, max_size: int) -> list[int]:
        """Expand ``top`` downward into at most ``max_size`` frontier
        nodes, preferentially splitting the most expensive nodes."""
        frontier = [top]
        while len(frontier) < max_size:
            # split the non-leaf frontier node with the largest tensor
            best = -1
            best_key = -1.0
            for idx, node_id in enumerate(frontier):
                nd = self.nodes[node_id]
                if nd.is_leaf:
                    continue
                key = self._size(nd.legs)
                if key > best_key:
                    best_key = key
                    best = idx
            if best < 0:
                break
            node_id = frontier.pop(best)
            nd = self.nodes[node_id]
            frontier.append(nd.left)
            frontier.append(nd.right)
        return frontier

    def _optimal_order(
        self,
        leg_sets: list[frozenset[int]],
        minimize: str = "flops",
        logsize_cap: float = -1.0,
    ) -> tuple[float, list[tuple[int, int]]] | None:
        """Subset-DP optimal pairwise order over ``leg_sets``; returns
        (cost, local ssa pairs) or None if too large / no order satisfies
        ``logsize_cap``. ``minimize`` is ``"flops"`` (sum of naive op
        counts) or ``"size"`` (max intermediate tensor size — a
        max-objective composes over splits just like a sum does). When
        ``logsize_cap`` >= 0, intermediates larger than ``2**logsize_cap``
        elements are forbidden (slice-aware refinement). Dispatches to the
        native C++ kernel when available."""
        n = len(leg_sets)
        if n >= 5:
            from tnc_tpu.partitioning.native_binding import native_optimal_order

            native = native_optimal_order(
                leg_sets, self.dims, minimize, logsize_cap
            )
            if native is not None:
                if math.isinf(native[0]):
                    return None  # proven infeasible under the cap
                return native
        if n > 12:
            return None
        by_size = minimize == "size"
        cap_size = math.inf if logsize_cap < 0 else 2.0**logsize_cap
        full = (1 << n) - 1
        # Result legs of any subset are the XOR of its members' legs (a leg
        # joins at most two tensors) — split-independent, precompute.
        legs_of: dict[int, frozenset[int]] = {0: frozenset()}
        for mask in range(1, full + 1):
            low = mask & (-mask)
            legs_of[mask] = legs_of[mask ^ low] ^ leg_sets[low.bit_length() - 1]
        best: dict[int, tuple[float, int]] = {}
        for i in range(n):
            best[1 << i] = (0.0, 0)
        order = [[] for _ in range(n + 1)]
        for mask in range(1, full + 1):
            order[mask.bit_count()].append(mask)
        for count in range(2, n + 1):
            for mask in order[count]:
                if mask != full and self._size(legs_of[mask]) > cap_size:
                    best[mask] = (math.inf, 0)
                    continue
                lowest = mask & (-mask)
                best_cost = math.inf
                best_split = 0
                sub = (mask - 1) & mask
                while sub:
                    if sub & lowest:
                        hi = mask ^ sub
                        if hi:
                            c_lo, _ = best[sub]
                            c_hi, _ = best[hi]
                            if not (c_lo == math.inf or c_hi == math.inf):
                                if by_size:
                                    cost = max(
                                        c_lo, c_hi, self._size(legs_of[mask])
                                    )
                                else:
                                    union = legs_of[sub] | legs_of[hi]
                                    cost = c_lo + c_hi + self._size(union)
                                if cost < best_cost:
                                    best_cost = cost
                                    best_split = sub
                    sub = (sub - 1) & mask
                best[mask] = (best_cost, best_split)
        if best[full][0] == math.inf:
            return None

        pairs: list[tuple[int, int]] = []
        next_local = n

        def build(mask: int) -> int:
            nonlocal next_local
            if mask.bit_count() == 1:
                return mask.bit_length() - 1
            lo = best[mask][1]
            a = build(lo)
            b = build(mask ^ lo)
            pairs.append((a, b))
            out = next_local
            next_local += 1
            return out

        build(full)
        return best[full][0], pairs

    def _subtree_cost(
        self, top: int, frontier: set[int], minimize: str = "flops"
    ) -> float:
        """Cost of the internal nodes of ``top``'s subtree down to
        ``frontier`` (sum of flops, or max intermediate size)."""
        by_size = minimize == "size"
        cost = 0.0
        stack = [top]
        while stack:
            i = stack.pop()
            if i in frontier:
                continue
            nd = self.nodes[i]
            if by_size:
                cost = max(cost, self._size(nd.legs))
            else:
                cost += self.node_cost(i)
            stack.append(nd.left)
            stack.append(nd.right)
        return cost

    def _splice(self, top: int, frontier: list[int], pairs: list[tuple[int, int]]) -> None:
        """Replace ``top``'s subtree-internal structure with the local
        order ``pairs`` over ``frontier``."""
        local_to_node = {i: f for i, f in enumerate(frontier)}
        m = len(frontier)
        last = top
        for k, (a, b) in enumerate(pairs):
            na = local_to_node[a]
            nb = local_to_node[b]
            if k == len(pairs) - 1:
                # reuse `top` as the final node so its parent link survives
                node_id = top
                self.nodes[node_id].left = na
                self.nodes[node_id].right = nb
                self.nodes[node_id].legs = self.nodes[na].legs ^ self.nodes[nb].legs
            else:
                node_id = len(self.nodes)
                self.nodes.append(
                    _Node(
                        left=na,
                        right=nb,
                        legs=self.nodes[na].legs ^ self.nodes[nb].legs,
                    )
                )
            self.nodes[na].parent = node_id
            self.nodes[nb].parent = node_id
            local_to_node[m + k] = node_id
            last = node_id
        assert last == top

    def _local_pairs(
        self, top: int, frontier: list[int]
    ) -> list[tuple[int, int]]:
        """The subtree-internal structure of ``top`` down to
        ``frontier``, as local ssa pairs over the frontier order — the
        inverse of :meth:`_splice` (re-splicing these pairs restores
        the structure), used to revert a rejected sliced-objective
        splice."""
        local_of = {f: i for i, f in enumerate(frontier)}
        frontier_set = set(frontier)
        order: list[int] = []
        stack = [top]
        while stack:
            i = stack.pop()
            if i in frontier_set:
                continue
            order.append(i)
            stack.append(self.nodes[i].left)
            stack.append(self.nodes[i].right)
        pairs: list[tuple[int, int]] = []
        next_local = len(frontier)
        for i in reversed(order):  # children precede parents
            nd = self.nodes[i]
            pairs.append((local_of[nd.left], local_of[nd.right]))
            local_of[i] = next_local
            next_local += 1
        return pairs

    def reconfigure(
        self,
        subtree_size: int = 8,
        max_rounds: int = 4,
        minimize: str = "flops",
        time_budget: float | None = None,
        logsize_cap: float = -1.0,
        sliced=None,
    ) -> None:
        """Iterative subtree reconfiguration, in place.

        Each round walks internal nodes in descending contraction cost,
        re-solves each node's <=``subtree_size``-frontier subtree with the
        exact DP, and splices improvements. Stops when a round makes no
        improvement, or when ``time_budget`` seconds elapse (the reference
        gives its optimizers explicit time budgets too,
        ``benchmark/src/main.rs:63``).

        ``sliced``: a :class:`~tnc_tpu.contractionpath.sliced_cost.
        SlicedReconfState` switches splice *acceptance* to the sliced
        objective — the DP still proposes orders in this tree's (slice-
        reduced) flop model, but a proposal is kept only when the
        attached incremental evaluator's hoisted sliced cost does not
        regress and the sliced peak stays within the budget; rejected
        splices are reverted exactly (:meth:`_local_pairs`). This is the
        "tree reconfigure move" half of the joint tree+slice search.
        """
        import time

        deadline = time.monotonic() + time_budget if time_budget else None
        for _ in range(max_rounds):
            improved = False
            internal = [
                i
                for i, nd in enumerate(self.nodes)
                if not nd.is_leaf and self._reachable(i)
            ]
            internal.sort(key=self.node_cost, reverse=True)
            # With the native DP each subtree solve is sub-millisecond, so
            # every round can afford to visit every internal node; the
            # pure-Python DP is ~1000x slower, so cap its per-round work
            # as before.
            if not _has_native_dp():
                internal = internal[: max(16, len(internal) // 4)]
            for top in internal:
                if deadline is not None and time.monotonic() > deadline:
                    return
                if not self._reachable(top):
                    continue
                frontier = self._collect_frontier(top, subtree_size)
                if len(frontier) < 3:
                    continue
                result = self._optimal_order(
                    [self.nodes[f].legs for f in frontier], minimize, logsize_cap
                )
                if result is None:
                    continue
                new_cost, pairs = result
                old_cost = self._subtree_cost(top, set(frontier), minimize)
                if not new_cost < old_cost * (1 - 1e-12):
                    continue
                if sliced is None:
                    self._splice(top, frontier, pairs)
                    improved = True
                    continue
                ev = sliced.evaluator
                old_pairs = self._local_pairs(top, frontier)
                old_internal = ev.subtree_internal(self, top, frontier)
                cost_before = ev.cost()
                peak_bound = sliced.peak_bound()
                self._splice(top, frontier, pairs)
                ev.sync_splice(self, top, frontier, old_internal)
                if ev.cost() <= cost_before and ev.peak() <= peak_bound:
                    improved = True
                else:
                    undo = ev.subtree_internal(self, top, frontier)
                    self._splice(top, frontier, old_pairs)
                    ev.sync_splice(self, top, frontier, undo)
            if not improved:
                break

    def _reachable(self, i: int) -> bool:
        """Whether node ``i`` is still part of the tree (splicing orphans
        old internal nodes)."""
        while self.nodes[i].parent >= 0:
            parent = self.nodes[i].parent
            pn = self.nodes[parent]
            if pn.left != i and pn.right != i:
                return False
            i = parent
        return i == self.root
