"""Analytic cost models for contraction paths.

Mirror of ``tnc/src/contractionpath/contraction_cost.rs``. These are the
framework's "profiler": flops and peak memory are predicted *before* any
kernel runs, and every optimizer (pathfinding, partition balancing,
simulated annealing) minimizes these analytic costs. All costs are floats —
Sycamore-class networks overflow 64-bit integers.

Cost functions on a pair of leaf tensors:

- :func:`contract_cost_tensors` — complex-op count
  ``((s-1)*2 + s*6) * |out|`` where ``s = |shared|``
  (``contraction_cost.rs:26-32``)
- :func:`contract_op_cost_tensors` — naive op count = product of the union
  dims (``contraction_cost.rs:49-52``)
- :func:`contract_size_tensors` — ``|out| + |a| + |b|`` elements
  (``contraction_cost.rs:69-77``); ``_bytes`` variant multiplies by 16
  (complex128).

Path-level aggregation walks nested paths (accumulating op cost, maxing
memory) then the toplevel replace-left pairs; the communication variant
adds per-input start latencies and supports critical-path vs sum metrics
(``contraction_cost.rs:156-244``).

Beyond the fixed cost functions, this module defines the **pluggable
objective layer** every pathfinder minimizes:

- :func:`greedy_cost_fn` — the local pair-scoring heuristics of the
  greedy finder (the improved cost functions of arXiv:2405.09644:
  memory-removed with a tunable ``alpha``, log-domain memory-removed,
  and plain output size), consumed by
  :class:`~tnc_tpu.contractionpath.paths.greedy.Greedy`;
- :class:`PathObjective` / :class:`FlopsObjective` /
  :class:`SizeObjective` — the path-level ranking the trial-based
  finders (random-greedy, hyper, branch-and-bound) minimize;
- :class:`CalibratedObjective` — the same interface priced in
  **predicted seconds** under a fitted
  :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` (per-step flops /
  bytes / dispatch-constant pricing), so planning optimizes what the
  hardware charges instead of a flop proxy.
"""

from __future__ import annotations

import math
from typing import Callable, Sequence

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor, Tensor

COMPLEX_BYTES = 16.0

CostFn = Callable[[LeafTensor, LeafTensor], float]


def contract_cost_tensors(t1: LeafTensor, t2: LeafTensor) -> float:
    """Complex-operation count of contracting ``t1`` with ``t2``.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> a = LeafTensor([0, 1], [2, 3])   # shares leg 1 (dim 3) with b
    >>> b = LeafTensor([1, 2], [3, 4])
    >>> contract_cost_tensors(a, b)      # ((3-1)*2 + 3*6) * (2*4)
    176.0
    """
    final_size = (t1 ^ t2).size()
    shared_size = (t1 & t2).size()
    return ((shared_size - 1.0) * 2.0 + shared_size * 6.0) * final_size


def contract_op_cost_tensors(t1: LeafTensor, t2: LeafTensor) -> float:
    """Naive operation count: product of all dims in the union.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> contract_op_cost_tensors(LeafTensor([0, 1], [2, 3]), LeafTensor([1, 2], [3, 4]))
    24.0
    """
    return (t1 | t2).size()


def contract_size_tensors(t1: LeafTensor, t2: LeafTensor) -> float:
    """Elements live during the pairwise contraction: out + in1 + in2.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> contract_size_tensors(LeafTensor([0, 1], [2, 3]), LeafTensor([1, 2], [3, 4]))
    26.0
    """
    return (t1 ^ t2).size() + t1.size() + t2.size()


def contract_size_tensors_bytes(t1: LeafTensor, t2: LeafTensor) -> float:
    return contract_size_tensors(t1, t2) * COMPLEX_BYTES


def _as_external_leaf(t: Tensor) -> LeafTensor:
    return t.external_tensor() if isinstance(t, CompositeTensor) else t


def _contract_path_custom_cost(
    inputs: Sequence[Tensor],
    contract_path: ContractionPath,
    cost_function: CostFn,
    size_function: CostFn,
) -> tuple[float, float]:
    op_cost = 0.0
    mem_cost = 0.0
    tensors: list[LeafTensor | Tensor] = list(inputs)

    for i, nested_path in contract_path.nested.items():
        child = tensors[i]
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"nested path at {i} targets a non-composite tensor")
        nested_op, nested_mem = _contract_path_custom_cost(
            child.tensors, nested_path, cost_function, size_function
        )
        op_cost += nested_op
        mem_cost = max(mem_cost, nested_mem)
        tensors[i] = child.external_tensor()

    for i, j in contract_path.toplevel:
        ti = _as_external_leaf(tensors[i])
        tj = _as_external_leaf(tensors[j])
        op_cost += cost_function(ti, tj)
        mem_cost = max(mem_cost, size_function(ti, tj))
        tensors[i] = ti ^ tj

    return op_cost, mem_cost


def contract_path_cost(
    inputs: Sequence[Tensor],
    contract_path: ContractionPath,
    only_count_ops: bool = False,
) -> tuple[float, float]:
    """(op cost, peak element memory) of a nested replace-left path
    (``contraction_cost.rs:101-151``).
    """
    cost_function = contract_op_cost_tensors if only_count_ops else contract_cost_tensors
    return _contract_path_custom_cost(
        inputs, contract_path, cost_function, contract_size_tensors
    )


def communication_path_cost(
    inputs: Sequence[LeafTensor],
    contract_path: Sequence[tuple[int, int]],
    only_count_ops: bool = False,
    only_critical_path: bool = True,
    tensor_cost: Sequence[float] | None = None,
    cost_function: CostFn | None = None,
) -> tuple[float, float]:
    """Cost of a flat (communication) path with per-input start latencies.

    With ``only_critical_path`` the accumulated cost of a contraction is
    ``cost(i,j) + max(latency_i, latency_j)`` — the parallel makespan;
    otherwise latencies add — the serial sum (``contraction_cost.rs:178-244``).

    ``cost_function`` overrides the per-pair cost (e.g. a
    :class:`CalibratedObjective`'s seconds-domain ``pair_cost``, with
    ``tensor_cost`` latencies in seconds to match).
    """
    if cost_function is None:
        cost_function = (
            contract_op_cost_tensors if only_count_ops else contract_cost_tensors
        )
    if tensor_cost is not None:
        if len(tensor_cost) != len(inputs):
            raise ValueError("tensor_cost length must match inputs")
        latencies = list(tensor_cost)
    else:
        latencies = [0.0] * len(inputs)

    if len(inputs) == 1:
        return latencies[0], latencies[0]

    tensors = [t.copy() for t in inputs]
    op_cost = 0.0
    mem_cost = 0.0
    for i, j in contract_path:
        out = tensors[i] ^ tensors[j]
        mem_cost = max(mem_cost, contract_size_tensors(tensors[i], tensors[j]))
        step = cost_function(tensors[i], tensors[j])
        if only_critical_path:
            op_cost = step + max(latencies[i], latencies[j])
        else:
            op_cost = step + latencies[i] + latencies[j]
        latencies[i] = op_cost
        tensors[i] = out
    return op_cost, mem_cost


def communication_path_op_costs(
    inputs: Sequence[LeafTensor],
    contract_path: Sequence[tuple[int, int]],
    only_count_ops: bool = False,
    tensor_cost: Sequence[float] | None = None,
    cost_function: CostFn | None = None,
) -> tuple[tuple[float, float], float]:
    """((critical-path cost, sum cost), peak memory)
    (``contraction_cost.rs:156-167``).
    """
    parallel_cost, _ = communication_path_cost(
        inputs, contract_path, only_count_ops, True, tensor_cost,
        cost_function,
    )
    serial_cost, mem_cost = communication_path_cost(
        inputs, contract_path, only_count_ops, False, tensor_cost,
        cost_function,
    )
    return (parallel_cost, serial_cost), mem_cost


def compute_memory_requirements(
    inputs: Sequence[Tensor],
    contract_path: ContractionPath,
    memory_estimator: CostFn = contract_size_tensors,
) -> float:
    """Peak memory of a nested path under ``memory_estimator``
    (``contraction_cost.rs:254-264``).
    """

    def zero(_a: LeafTensor, _b: LeafTensor) -> float:
        return 0.0

    _, mem = _contract_path_custom_cost(inputs, contract_path, zero, memory_estimator)
    return mem


# ---------------------------------------------------------------------------
# Greedy pair-scoring cost functions (arXiv:2405.09644)


#: registry of greedy pair heuristics: name -> factory(alpha) -> fn.
#: Each fn maps (out_size, size_a, size_b) to a score; the greedy finder
#: repeatedly contracts the minimum-score pair.
GREEDY_COST_KINDS = ("memory-removed", "memory-removed-log", "size")


def greedy_cost_fn(
    kind: str = "memory-removed", alpha: float = 1.0
) -> Callable[[float, float, float], float]:
    """A pair-scoring function for the greedy finder.

    The improved greedy cost functions of arXiv:2405.09644 generalize
    cotengra's memory-removed heuristic: ``alpha`` weights how strongly
    freeing the input tensors is rewarded, and the log-domain variant
    compares tensor *ranks* instead of raw sizes (robust when bond
    dimensions span orders of magnitude).

    - ``memory-removed``: ``size(out) - alpha * (size(a) + size(b))``
      (``alpha=1`` is the classic default the reference reaches through
      cotengrust);
    - ``memory-removed-log``: ``log2(1+size(out)) - alpha *
      log2(1 + size(a) + size(b))``;
    - ``size``: ``size(out)`` — greedily keep intermediates small,
      ignoring what is freed.

    >>> fn = greedy_cost_fn("memory-removed")
    >>> fn(16.0, 8.0, 8.0)
    0.0
    >>> greedy_cost_fn("size")(16.0, 8.0, 8.0)
    16.0
    """
    if kind == "memory-removed":
        if alpha == 1.0:
            return lambda out, a, b: out - a - b
        return lambda out, a, b: out - alpha * (a + b)
    if kind == "memory-removed-log":
        return lambda out, a, b: (
            math.log2(1.0 + out) - alpha * math.log2(1.0 + a + b)
        )
    if kind == "size":
        return lambda out, a, b: out
    raise ValueError(
        f"unknown greedy cost function {kind!r}; expected one of "
        f"{GREEDY_COST_KINDS}"
    )


# ---------------------------------------------------------------------------
# Pluggable path objectives


class PathObjective:
    """What a trial-based pathfinder minimizes, as a pluggable strategy.

    Implementations supply :meth:`pair_cost` — the cost charged for one
    pairwise contraction — and inherit path-level aggregation. The
    *domain* of the returned numbers is the implementation's choice
    (flop counts, predicted seconds); finders only compare candidates
    under ONE objective, so any monotone scale works.
    """

    #: short name recorded in plan artifacts (plan cache, bench JSON)
    name = "abstract"

    def pair_cost(self, t1: LeafTensor, t2: LeafTensor) -> float:
        raise NotImplementedError

    def path_cost(
        self, inputs: Sequence[Tensor], contract_path: ContractionPath
    ) -> float:
        """Total cost of a (possibly nested) replace path."""
        cost, _ = _contract_path_custom_cost(
            inputs, contract_path, self.pair_cost, contract_size_tensors
        )
        return cost

    def ssa_path_cost(
        self, inputs: Sequence[Tensor], ssa_pairs: Sequence[tuple[int, int]]
    ) -> float:
        """Total cost of a flat SSA pair path (the finders' native
        candidate format)."""
        from tnc_tpu.contractionpath.contraction_path import (
            ssa_replace_ordering,
        )

        return self.path_cost(
            inputs,
            ssa_replace_ordering(ContractionPath.simple(list(ssa_pairs))),
        )

    def sliced_path_cost(
        self,
        inputs: Sequence[LeafTensor],
        replace_pairs: Sequence[tuple[int, int]],
        slicing,
    ) -> float:
        """Cost of a flat path executed as a slice loop. The base
        implementation charges the naive ``num_slices x per-slice`` flop
        total (the historical slicing-aware score, valid for the flops
        and size objectives alike since both rank by the same slicing
        overhead); :class:`CalibratedObjective` overrides with the
        hoist-aware seconds formula."""
        from tnc_tpu.contractionpath.slicing import sliced_flops

        return sliced_flops(inputs, list(replace_pairs), slicing)


class FlopsObjective(PathObjective):
    """Minimize naive op counts — the historical default everywhere.

    >>> a, b = LeafTensor([0, 1], [2, 3]), LeafTensor([1, 2], [3, 4])
    >>> FlopsObjective().pair_cost(a, b)
    24.0
    """

    name = "flops"

    def pair_cost(self, t1: LeafTensor, t2: LeafTensor) -> float:
        return contract_op_cost_tensors(t1, t2)


class SizeObjective(PathObjective):
    """Minimize the peak intermediate size (elements). ``path_cost``
    returns the peak, not a sum — candidates still compare correctly
    because every finder only ranks under one objective at a time."""

    name = "size"

    def pair_cost(self, t1: LeafTensor, t2: LeafTensor) -> float:
        return contract_size_tensors(t1, t2)

    def path_cost(
        self, inputs: Sequence[Tensor], contract_path: ContractionPath
    ) -> float:
        _, mem = _contract_path_custom_cost(
            inputs, contract_path, self.pair_cost, contract_size_tensors
        )
        return mem


class CalibratedObjective(PathObjective):
    """Predicted **seconds** under a fitted device model — the
    plan→measure→replan loop's objective.

    Each pairwise contraction is priced as one dispatched step:
    ``flops / flops_per_s + bytes / bytes_per_s + dispatch_s`` (the
    per-step constant raw flop counts are blind to, cf.
    :meth:`~tnc_tpu.obs.calibrate.CalibratedCostModel.
    dispatch_equivalent_flops`). A path of many tiny steps therefore
    correctly loses to a path of few large ones even at equal flops,
    and sliced plans are priced with the hoisted
    ``prelude + num_slices x residual`` seconds formula.

    >>> from tnc_tpu.obs.calibrate import CalibratedCostModel
    >>> m = CalibratedCostModel(flops_per_s=1e9, dispatch_s=1e-3)
    >>> obj = CalibratedObjective(m)
    >>> a, b = LeafTensor([0, 1], [2, 3]), LeafTensor([1, 2], [3, 4])
    >>> round(obj.pair_cost(a, b), 9)   # 24 flops + one dispatch
    0.001000024
    """

    name = "calibrated"

    def __init__(self, cost_model, bytes_per_elem: float = COMPLEX_BYTES):
        if cost_model is None:
            raise ValueError("CalibratedObjective requires a cost model")
        self.cost_model = cost_model
        self.bytes_per_elem = float(bytes_per_elem)

    def pair_cost(self, t1: LeafTensor, t2: LeafTensor) -> float:
        flops = contract_op_cost_tensors(t1, t2)
        nbytes = contract_size_tensors(t1, t2) * self.bytes_per_elem
        return self.cost_model.op_seconds(flops, nbytes)

    def sliced_path_cost(
        self,
        inputs: Sequence[LeafTensor],
        replace_pairs: Sequence[tuple[int, int]],
        slicing,
    ) -> float:
        from tnc_tpu.contractionpath.slicing import (
            StemAccountant,
            _make_replayer,
        )

        pairs = list(replace_pairs)
        acct = StemAccountant(inputs, pairs, cost_model=self.cost_model)
        removed = set(slicing.legs)
        per_slice = _make_replayer(inputs, pairs).flops(removed)
        return acct.hoisted_cost(removed, per_slice, slicing.num_slices)


def resolve_objective(minimize) -> PathObjective:
    """Normalize a ``minimize`` argument — an objective instance, or the
    legacy strings ``"flops"`` / ``"size"`` — to a :class:`PathObjective`.

    >>> resolve_objective("flops").name
    'flops'
    >>> resolve_objective(SizeObjective()).name
    'size'
    """
    if isinstance(minimize, PathObjective):
        return minimize
    if minimize in (None, "flops"):
        return FlopsObjective()
    if minimize == "size":
        return SizeObjective()
    raise ValueError(
        f"unknown objective {minimize!r}; expected 'flops', 'size', or a "
        "PathObjective instance"
    )
