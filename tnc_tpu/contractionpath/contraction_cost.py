"""Analytic cost models for contraction paths.

Mirror of ``tnc/src/contractionpath/contraction_cost.rs``. These are the
framework's "profiler": flops and peak memory are predicted *before* any
kernel runs, and every optimizer (pathfinding, partition balancing,
simulated annealing) minimizes these analytic costs. All costs are floats —
Sycamore-class networks overflow 64-bit integers.

Cost functions on a pair of leaf tensors:

- :func:`contract_cost_tensors` — complex-op count
  ``((s-1)*2 + s*6) * |out|`` where ``s = |shared|``
  (``contraction_cost.rs:26-32``)
- :func:`contract_op_cost_tensors` — naive op count = product of the union
  dims (``contraction_cost.rs:49-52``)
- :func:`contract_size_tensors` — ``|out| + |a| + |b|`` elements
  (``contraction_cost.rs:69-77``); ``_bytes`` variant multiplies by 16
  (complex128).

Path-level aggregation walks nested paths (accumulating op cost, maxing
memory) then the toplevel replace-left pairs; the communication variant
adds per-input start latencies and supports critical-path vs sum metrics
(``contraction_cost.rs:156-244``).
"""

from __future__ import annotations

from typing import Callable, Sequence

from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.tensornetwork.tensor import CompositeTensor, LeafTensor, Tensor

COMPLEX_BYTES = 16.0

CostFn = Callable[[LeafTensor, LeafTensor], float]


def contract_cost_tensors(t1: LeafTensor, t2: LeafTensor) -> float:
    """Complex-operation count of contracting ``t1`` with ``t2``.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> a = LeafTensor([0, 1], [2, 3])   # shares leg 1 (dim 3) with b
    >>> b = LeafTensor([1, 2], [3, 4])
    >>> contract_cost_tensors(a, b)      # ((3-1)*2 + 3*6) * (2*4)
    176.0
    """
    final_size = (t1 ^ t2).size()
    shared_size = (t1 & t2).size()
    return ((shared_size - 1.0) * 2.0 + shared_size * 6.0) * final_size


def contract_op_cost_tensors(t1: LeafTensor, t2: LeafTensor) -> float:
    """Naive operation count: product of all dims in the union.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> contract_op_cost_tensors(LeafTensor([0, 1], [2, 3]), LeafTensor([1, 2], [3, 4]))
    24.0
    """
    return (t1 | t2).size()


def contract_size_tensors(t1: LeafTensor, t2: LeafTensor) -> float:
    """Elements live during the pairwise contraction: out + in1 + in2.

    >>> from tnc_tpu.tensornetwork.tensor import LeafTensor
    >>> contract_size_tensors(LeafTensor([0, 1], [2, 3]), LeafTensor([1, 2], [3, 4]))
    26.0
    """
    return (t1 ^ t2).size() + t1.size() + t2.size()


def contract_size_tensors_bytes(t1: LeafTensor, t2: LeafTensor) -> float:
    return contract_size_tensors(t1, t2) * COMPLEX_BYTES


def _as_external_leaf(t: Tensor) -> LeafTensor:
    return t.external_tensor() if isinstance(t, CompositeTensor) else t


def _contract_path_custom_cost(
    inputs: Sequence[Tensor],
    contract_path: ContractionPath,
    cost_function: CostFn,
    size_function: CostFn,
) -> tuple[float, float]:
    op_cost = 0.0
    mem_cost = 0.0
    tensors: list[LeafTensor | Tensor] = list(inputs)

    for i, nested_path in contract_path.nested.items():
        child = tensors[i]
        if not isinstance(child, CompositeTensor):
            raise TypeError(f"nested path at {i} targets a non-composite tensor")
        nested_op, nested_mem = _contract_path_custom_cost(
            child.tensors, nested_path, cost_function, size_function
        )
        op_cost += nested_op
        mem_cost = max(mem_cost, nested_mem)
        tensors[i] = child.external_tensor()

    for i, j in contract_path.toplevel:
        ti = _as_external_leaf(tensors[i])
        tj = _as_external_leaf(tensors[j])
        op_cost += cost_function(ti, tj)
        mem_cost = max(mem_cost, size_function(ti, tj))
        tensors[i] = ti ^ tj

    return op_cost, mem_cost


def contract_path_cost(
    inputs: Sequence[Tensor],
    contract_path: ContractionPath,
    only_count_ops: bool = False,
) -> tuple[float, float]:
    """(op cost, peak element memory) of a nested replace-left path
    (``contraction_cost.rs:101-151``).
    """
    cost_function = contract_op_cost_tensors if only_count_ops else contract_cost_tensors
    return _contract_path_custom_cost(
        inputs, contract_path, cost_function, contract_size_tensors
    )


def communication_path_cost(
    inputs: Sequence[LeafTensor],
    contract_path: Sequence[tuple[int, int]],
    only_count_ops: bool = False,
    only_critical_path: bool = True,
    tensor_cost: Sequence[float] | None = None,
) -> tuple[float, float]:
    """Cost of a flat (communication) path with per-input start latencies.

    With ``only_critical_path`` the accumulated cost of a contraction is
    ``cost(i,j) + max(latency_i, latency_j)`` — the parallel makespan;
    otherwise latencies add — the serial sum (``contraction_cost.rs:178-244``).
    """
    cost_function = contract_op_cost_tensors if only_count_ops else contract_cost_tensors
    if tensor_cost is not None:
        if len(tensor_cost) != len(inputs):
            raise ValueError("tensor_cost length must match inputs")
        latencies = list(tensor_cost)
    else:
        latencies = [0.0] * len(inputs)

    if len(inputs) == 1:
        return latencies[0], latencies[0]

    tensors = [t.copy() for t in inputs]
    op_cost = 0.0
    mem_cost = 0.0
    for i, j in contract_path:
        out = tensors[i] ^ tensors[j]
        mem_cost = max(mem_cost, contract_size_tensors(tensors[i], tensors[j]))
        step = cost_function(tensors[i], tensors[j])
        if only_critical_path:
            op_cost = step + max(latencies[i], latencies[j])
        else:
            op_cost = step + latencies[i] + latencies[j]
        latencies[i] = op_cost
        tensors[i] = out
    return op_cost, mem_cost


def communication_path_op_costs(
    inputs: Sequence[LeafTensor],
    contract_path: Sequence[tuple[int, int]],
    only_count_ops: bool = False,
    tensor_cost: Sequence[float] | None = None,
) -> tuple[tuple[float, float], float]:
    """((critical-path cost, sum cost), peak memory)
    (``contraction_cost.rs:156-167``).
    """
    parallel_cost, _ = communication_path_cost(
        inputs, contract_path, only_count_ops, True, tensor_cost
    )
    serial_cost, mem_cost = communication_path_cost(
        inputs, contract_path, only_count_ops, False, tensor_cost
    )
    return (parallel_cost, serial_cost), mem_cost


def compute_memory_requirements(
    inputs: Sequence[Tensor],
    contract_path: ContractionPath,
    memory_estimator: CostFn = contract_size_tensors,
) -> float:
    """Peak memory of a nested path under ``memory_estimator``
    (``contraction_cost.rs:254-264``).
    """

    def zero(_a: LeafTensor, _b: LeafTensor) -> float:
        return 0.0

    _, mem = _contract_path_custom_cost(inputs, contract_path, zero, memory_estimator)
    return mem
