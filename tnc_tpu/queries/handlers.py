"""Query handlers: sampling / expectation / marginal requests on a
:class:`~tnc_tpu.serve.service.ContractionService`.

The service owns the queue, micro-batching window, deadlines,
admission control, retry and degradation; a handler owns one query
TYPE — payload validation at submit time, the per-type batching key
(a batch never mixes structures), and the batched dispatch. All
handler structures plan through :func:`~tnc_tpu.serve.rebind.
bind_template` with the service's plan cache, so repeat structures
are cache hits with zero pathfinding, exactly like amplitude serving.

Attach with :func:`attach_query_handlers` (or
``ContractionService.from_circuit(..., queries=True)``):

>>> from tnc_tpu.serve import ContractionService
>>> from tnc_tpu.tensornetwork.tensordata import TensorData
>>> c = Circuit(); reg = c.allocate_register(2)
>>> c.append_gate(TensorData.gate("x"), [reg.qubit(0)])
>>> with ContractionService.from_circuit(c, queries=True) as svc:
...     samples = svc.sample(2, seed=0)
...     ev = svc.expectation("zi")
...     p = svc.marginal("1*")
>>> samples, complex(ev), round(p, 6)
(['10', '10'], (-1+0j), 1.0)
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.builders.circuit_builder import Circuit, normalize_bitstring
from tnc_tpu.queries.expectation import (
    ExpectationProgram,
    bind_expectation,
    normalize_terms,
)
from tnc_tpu.queries.marginal import (
    bind_marginal,
    marginal_probabilities,
    wildcard_mask,
)
from tnc_tpu.queries.sampling import ChainSampler

__all__ = [
    "SampleQueryHandler",
    "ExpectationQueryHandler",
    "MarginalQueryHandler",
    "attach_query_handlers",
]


class SampleQueryHandler:
    """``kind="sample"``: payload ``{"n_samples": int, "seed": ...}`` →
    a list of sampled bitstrings. Co-batched requests share every
    chain step's conditional dispatch (distinct prefixes across ALL
    in-flight samples dedupe into one rebind batch) while each request
    draws from its own seeded RNG — results are independent of who
    rides along."""

    kind = "sample"
    # per-dispatch work scales with each request's n_samples, not the
    # batch size — measured seconds per batch-size bucket are not
    # comparable, so the SLO drift detector must not track this kind
    drift_stable = False
    # stochastic: two requests with equal payloads but distinct seeds
    # (or seed=None) must draw independently — the dispatcher's
    # queue-level dedup never collapses sample riders
    dedup_payloads = False

    def __init__(self, sampler: ChainSampler) -> None:
        self.sampler = sampler

    def validate(self, payload) -> tuple[dict, tuple]:
        if isinstance(payload, int):
            payload = {"n_samples": payload}
        payload = dict(payload)
        n_samples = int(payload.pop("n_samples", 1))
        if n_samples < 1:
            raise ValueError("n_samples must be >= 1")
        seed = payload.pop("seed", None)
        if payload:
            raise ValueError(
                f"unknown sample payload keys: {sorted(payload)}"
            )
        return {"n_samples": n_samples, "seed": seed}, (self.kind,)

    def dispatch(self, payloads: Sequence[dict], backend) -> list:
        # the per-type timeline tag: the handler's whole batched
        # execution nests under the service's `serve.dispatch` span, so
        # a trace rollup attributes chain-step time to the query type
        with obs.span("serve.handler", type=self.kind, batch=len(payloads)):
            return self.sampler.sample_groups(
                [(p["n_samples"], p["seed"]) for p in payloads], backend
            )


class ExpectationQueryHandler:
    """``kind="expectation"``: payload = a Pauli string or an iterable
    of ``(coeff, pauli)`` terms → the (complex) expectation value. All
    requests share ONE sandwich structure; the union of all co-batched
    requests' distinct Pauli strings dispatches as one observable-leaf
    rebind batch."""

    kind = "expectation"
    # per-dispatch work scales with the UNIQUE Pauli strings across the
    # batch (plus a compile per new unique-count bucket) — not
    # drift-comparable per batch-size bucket
    drift_stable = False
    # deterministic in the payload (normalized term tuples are
    # hashable): identical riders in one window collapse to a single
    # dispatch entry
    dedup_payloads = True

    def __init__(
        self,
        circuit: Circuit,
        pathfinder=None,
        plan_cache=None,
        target_size: float | None = None,
    ) -> None:
        self._circuit = circuit.copy()
        self.num_qubits = self._circuit.num_qubits()
        self.pathfinder = pathfinder
        self.plan_cache = plan_cache
        self.target_size = target_size
        self._program: ExpectationProgram | None = None

    def program(self) -> ExpectationProgram:
        if self._program is None:
            self._program = bind_expectation(
                self._circuit.copy(),
                self.pathfinder,
                self.plan_cache,
                self.target_size,
            )
        return self._program

    def validate(self, payload) -> tuple[tuple, tuple]:
        return normalize_terms(payload, self.num_qubits), (self.kind,)

    def dispatch(self, payloads: Sequence[tuple], backend) -> list:
        unique: dict[str, int] = {}
        for terms in payloads:
            for _c, pauli in terms:
                unique.setdefault(pauli, len(unique))
        with obs.span(
            "serve.handler", type=self.kind, batch=len(payloads),
            unique_terms=len(unique),
        ):
            vals = self.program().values(list(unique), backend)
        return [
            complex(sum(c * vals[unique[p]] for c, p in terms))
            for terms in payloads
        ]


class MarginalQueryHandler:
    """``kind="marginal"``: payload = a pattern with ``'*'`` wildcards
    → the marginal probability of its determined bits. The batching
    key carries the wildcard MASK — patterns sharing a mask share a
    structure and batch; distinct masks are distinct (cached)
    plans."""

    kind = "marginal"
    # one structure per mask, work linear in batch rows: batch-size
    # buckets see comparable seconds — drift tracking is meaningful
    drift_stable = True
    # deterministic in the (string) pattern: safe to collapse
    # identical riders queue-level
    dedup_payloads = True

    def __init__(
        self,
        circuit: Circuit,
        pathfinder=None,
        plan_cache=None,
        target_size: float | None = None,
    ) -> None:
        self._circuit = circuit.copy()
        self.num_qubits = self._circuit.num_qubits()
        self.pathfinder = pathfinder
        self.plan_cache = plan_cache
        self.target_size = target_size
        self._bounds: dict[str, object] = {}

    def validate(self, payload) -> tuple[str, tuple]:
        bits = normalize_bitstring(payload, self.num_qubits)
        return bits, (self.kind, wildcard_mask(bits))

    def bound_for(self, mask: str):
        bound = self._bounds.get(mask)
        if bound is None:
            bound = bind_marginal(
                self._circuit.copy(),
                mask,
                self.pathfinder,
                self.plan_cache,
                self.target_size,
            )
            self._bounds[mask] = bound
        return bound

    def dispatch(self, payloads: Sequence[str], backend) -> list:
        with obs.span(
            "serve.handler", type=self.kind, batch=len(payloads),
        ):
            bound = self.bound_for(wildcard_mask(payloads[0]))
            probs = marginal_probabilities(bound, list(payloads), backend)
        return [float(p) for p in np.asarray(probs)]


def attach_query_handlers(
    service,
    circuit: Circuit,
    pathfinder=None,
    plan_cache=None,
    target_size: float | None = None,
) -> None:
    """Register sampling, expectation and marginal handlers for
    ``circuit`` on ``service`` (``circuit`` is copied, not consumed).
    ``plan_cache``/``target_size`` flow into every handler's planning,
    so all query structures share the service's cache and budget."""
    service.register_query_handler(
        SampleQueryHandler(
            ChainSampler(
                circuit,
                pathfinder=pathfinder,
                plan_cache=plan_cache,
                target_size=target_size,
            )
        )
    )
    service.register_query_handler(
        ExpectationQueryHandler(
            circuit, pathfinder, plan_cache, target_size
        )
    )
    service.register_query_handler(
        MarginalQueryHandler(circuit, pathfinder, plan_cache, target_size)
    )
