"""Bitstring sampling by qubit-by-qubit chain rule over marginal
networks.

Sampling b ~ |⟨b|C|0…0⟩|² factorizes as a chain of conditionals:
``p(b) = Π_k p(b_k | b_0..b_{k-1})``. Each conditional is ONE
contraction of a *marginal sandwich network* — circuit ++ adjoint
mirror with the already-sampled prefix qubits closed by bras (both
layers), qubit ``k`` left open (its 2×2 density block's diagonal is
the pair of unnormalized marginals ``p(prefix+'0')``/``p(prefix+'1')``)
and every later qubit traced against its mirror
(:meth:`~tnc_tpu.builders.circuit_builder.Circuit.
into_sandwich_template`).

The structure of step ``k``'s network depends only on the PREFIX
LENGTH, never on the sampled bits — so each of the ``n`` structures
plans once (:func:`~tnc_tpu.serve.rebind.bind_template`: plan-cache
honored, budget-sliced when needed) and every conditional is a bra
rebind. The frozen-bits fast path batches all in-flight samples'
conditionals per step into one dispatch (:mod:`tnc_tpu.ops.batched`
threads the batch leg), after deduplicating identical prefixes — B
samples concentrate on few distinct prefixes early in the chain, so a
step usually dispatches far fewer than B conditionals.

Determinism: a seeded run is reproducible across processes (no
set-ordered iteration anywhere on the sampling path; prefix dedup uses
insertion-ordered dicts) — one uniform vector is drawn per qubit
position, sample-major, so a request's stream never depends on
co-riders batched with it.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.builders.circuit_builder import Circuit

__all__ = ["ChainSampler", "sample_bitstrings"]


class ChainSampler:
    """Chain-rule bitstring sampler over one circuit.

    The constructor copies ``circuit`` (it stays usable); marginal
    structures bind lazily, one per prefix length, through the shared
    plan cache when one is given.

    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("x"), [reg.qubit(0)])
    >>> ChainSampler(c).sample(3, seed=0)
    ['10', '10', '10']
    """

    def __init__(
        self,
        circuit: Circuit,
        pathfinder=None,
        plan_cache=None,
        target_size: float | None = None,
        backend=None,
    ) -> None:
        self._circuit = circuit.copy()
        self.num_qubits = self._circuit.num_qubits()
        if self.num_qubits == 0:
            raise ValueError("cannot sample a 0-qubit circuit")
        self.pathfinder = pathfinder
        self.plan_cache = plan_cache
        self.target_size = target_size
        self.backend = backend
        self._bounds: dict[int, object] = {}  # prefix length -> BoundProgram

    # -- marginal structures ----------------------------------------------

    def bound_for(self, k: int):
        """The bound marginal program for prefix length ``k`` (planned
        and compiled on first use; repeat structures come from the plan
        cache with zero pathfinding)."""
        bound = self._bounds.get(k)
        if bound is None:
            from tnc_tpu.serve.rebind import bind_template

            spec = "?" * k + "o" + "*" * (self.num_qubits - k - 1)
            template = self._circuit.copy().into_sandwich_template(spec)
            bound = bind_template(
                template, self.pathfinder, self.plan_cache, self.target_size
            )
            self._bounds[k] = bound
        return bound

    def marginals(
        self, prefixes: Sequence[str], backend=None
    ) -> np.ndarray:
        """Unnormalized next-bit marginals for equal-length prefixes:
        ``out[i] = (p(prefixes[i] + '0'), p(prefixes[i] + '1'))`` with
        all later qubits traced out — one batched dispatch."""
        if not prefixes:
            return np.zeros((0, 2))
        k = len(prefixes[0])
        for p in prefixes:
            if len(p) != k:
                raise ValueError("all prefixes must have equal length")
        bound = self.bound_for(k)
        batch = [bound.template.request_bits(p) for p in prefixes]
        out = bound.amplitudes_det(batch, backend or self.backend)
        # the open qubit's two legs arrive in program result-leg order;
        # the diagonal is order-invariant (M and M^T share it)
        diag = np.einsum("bii->bi", out.reshape(len(prefixes), 2, 2))
        return np.real(diag)

    def conditionals(
        self, prefixes: Sequence[str], backend=None
    ) -> np.ndarray:
        """Normalized ``p(next bit = 0 | prefix), p(= 1 | prefix)`` rows
        for a batch of equal-length prefixes."""
        raw = self.marginals(prefixes, backend)
        totals = raw.sum(axis=1, keepdims=True)
        safe = np.where(totals > 0.0, totals, 1.0)
        out = raw / safe
        out[totals.reshape(-1) <= 0.0] = 0.5
        return out

    # -- sampling ----------------------------------------------------------

    def sample(
        self, n_samples: int, seed=None, backend=None
    ) -> list[str]:
        """``n_samples`` bitstrings from |⟨b|C|0⟩|², chain-rule order.
        ``seed`` feeds ``np.random.default_rng`` — a seeded run is
        deterministic across processes."""
        return self.sample_groups([(n_samples, seed)], backend)[0]

    def sample_groups(
        self,
        specs: Sequence[tuple[int, object]],
        backend=None,
    ) -> list[list[str]]:
        """Sample several independent requests ``(n_samples, seed)`` in
        one chain walk: every step dispatches the UNION of all in-flight
        samples' distinct prefixes as one batch, while each request
        draws from its own RNG in sample-major order — so a request's
        sampled stream is identical whether it rides alone or batched
        with co-riders (the dispatch-batching contract of the serving
        layer)."""
        sizes = []
        rngs = []
        for n_samples, seed in specs:
            n_samples = int(n_samples)
            if n_samples < 1:
                raise ValueError("n_samples must be >= 1")
            sizes.append(n_samples)
            rngs.append(np.random.default_rng(seed))
        total = sum(sizes)
        prefixes = [""] * total
        for _k in range(self.num_qubits):
            unique: dict[str, int] = {}
            for p in prefixes:
                unique.setdefault(p, len(unique))
            probs = self.conditionals(list(unique), backend)
            obs.counter_add("queries.sample.steps")
            obs.counter_add(
                "queries.sample.conditionals", value=len(unique)
            )
            draws = np.concatenate(
                [rng.random(n) for rng, n in zip(rngs, sizes)]
            )
            for i, prefix in enumerate(prefixes):
                p1 = probs[unique[prefix]][1]
                prefixes[i] = prefix + ("1" if draws[i] < p1 else "0")
        out: list[list[str]] = []
        start = 0
        for n_samples in sizes:
            out.append(prefixes[start : start + n_samples])
            start += n_samples
        return out


def sample_bitstrings(
    circuit: Circuit,
    n_samples: int,
    seed=None,
    pathfinder=None,
    plan_cache=None,
    target_size: float | None = None,
    backend=None,
) -> list[str]:
    """One-shot convenience over :class:`ChainSampler` (``circuit`` is
    copied, not consumed)."""
    return ChainSampler(
        circuit,
        pathfinder=pathfinder,
        plan_cache=plan_cache,
        target_size=target_size,
        backend=backend,
    ).sample(n_samples, seed=seed)
