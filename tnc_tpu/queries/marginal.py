"""Marginal-probability sweeps: wildcard bitstring patterns as
first-class queries.

``amplitude_sweep`` historically rejected ``'*'`` wildcards — an open
leg in a single-layer amplitude network yields a statevector *slice*,
exponential in the number of wildcards. The marginal sweep instead
contracts the circuit ++ adjoint *sandwich* in which every wildcard
position's leg is traced against its mirror
(:meth:`~tnc_tpu.builders.circuit_builder.Circuit.
into_sandwich_template` spec ``'*'``), so the network computes
``p(determined bits) = Σ_wildcards |⟨b|C|0…0⟩|²`` directly — cost is
one scalar contraction per pattern, independent of how many positions
are marginalized.

All patterns of a sweep must share one wildcard MASK (the mask is the
structure; the determined bits are bra values) — the batch rebinds
through one planned program exactly like amplitude serving
(:mod:`tnc_tpu.serve.rebind`), and a repeat mask is a plan-cache hit.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from tnc_tpu.builders.circuit_builder import Circuit, normalize_bitstring

__all__ = [
    "marginal_sweep",
    "marginal_probabilities",
    "bind_marginal",
    "wildcard_mask",
]


def wildcard_mask(pattern: str) -> str:
    """The structure-defining mask of a pattern: ``'?'`` per determined
    position, ``'*'`` per wildcard.

    >>> wildcard_mask("0*1")
    '?*?'
    """
    return "".join("*" if c == "*" else "?" for c in pattern)


def bind_marginal(
    circuit: Circuit,
    mask: str,
    pathfinder=None,
    plan_cache=None,
    target_size: float | None = None,
):
    """Plan/compile the marginal sandwich for one wildcard ``mask``
    (``'?'``/``'*'`` per qubit; ``circuit`` consumed). Returns the
    :class:`~tnc_tpu.serve.rebind.BoundProgram`; each query rebinds
    the determined positions' bras."""
    from tnc_tpu.serve.rebind import bind_template

    template = circuit.into_sandwich_template(mask)
    return bind_template(template, pathfinder, plan_cache, target_size)


def marginal_probabilities(
    bound, patterns: Sequence[str], backend=None
) -> np.ndarray:
    """Marginal probabilities for patterns sharing ``bound``'s mask —
    one batched dispatch; real ``(B,)``, clipped at 0 (a marginal is a
    born-rule mass; tiny negative roundoff must not leak to callers)."""
    template = bound.template
    bra_qubits = template.bra_qubits
    batch = []
    for pattern in patterns:
        bits = normalize_bitstring(pattern, template.num_qubits)
        if wildcard_mask(bits) != template.spec:
            raise ValueError(
                f"pattern {bits!r} does not match this sweep's wildcard "
                f"mask {template.spec!r}"
            )
        batch.append(
            template.request_bits("".join(bits[q] for q in bra_qubits))
        )
    out = bound.amplitudes_det(batch, backend)
    return np.clip(np.real(out).reshape(len(patterns)), 0.0, None)


def marginal_sweep(
    circuit: Circuit,
    patterns: Sequence[str | Iterable],
    pathfinder=None,
    backend=None,
    plan_cache=None,
    target_size: float | None = None,
) -> np.ndarray:
    """Marginal probabilities of the determined positions for every
    pattern, sharing one path and one compiled sandwich program
    (``circuit`` is consumed — finalizer semantics, matching
    :func:`~tnc_tpu.tensornetwork.sweep.amplitude_sweep`, which
    delegates its wildcard case here). All patterns must carry the
    same wildcard mask. Returns a real ``(len(patterns),)`` array.

    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("x"), [reg.qubit(0)])
    >>> marginal_sweep(c, ["0*", "1*"]).tolist()
    [0.0, 1.0]
    """
    if len(patterns) == 0:
        return np.zeros((0,), dtype=np.float64)
    bits_list = [
        normalize_bitstring(p, circuit.num_qubits()) for p in patterns
    ]
    mask = wildcard_mask(bits_list[0])
    for bits in bits_list[1:]:
        if wildcard_mask(bits) != mask:
            raise ValueError(
                "all patterns of a marginal sweep must share one "
                f"wildcard mask (got {wildcard_mask(bits)!r} and "
                f"{mask!r}); split per-mask or pad with bits"
            )
    bound = bind_marginal(
        circuit, mask, pathfinder, plan_cache, target_size
    )
    return marginal_probabilities(bound, bits_list, backend)
