"""tnc_tpu.queries — the query engine: bitstring sampling, Pauli
expectation values and marginal sweeps as first-class query types.

Everything the stack serves is a contraction of one circuit's tensor
networks; this package adds the three queries a real user fleet asks
for beyond single amplitudes, all riding the existing planning,
rebinding, batching and serving machinery:

- **Sampling** (``sampling.py``) — qubit-by-qubit chain-rule sampling
  over marginal sandwich networks: one planned structure per prefix
  length (plan-cache keyed), conditionals rebound and batched across
  all in-flight samples, seeded-deterministic streams.
- **Expectation values** (``expectation.py``) — ⟨ψ|P|ψ⟩ sandwich
  networks with rebindable observable leaves; Pauli-sum terms batch
  like bras through one compiled program; ``value_and_grad`` through
  the autodiff-capable jax executors.
- **Marginal sweeps** (``marginal.py``) — wildcard patterns contract
  as traced sandwich legs, returning marginal probabilities of the
  determined positions (this is ``amplitude_sweep``'s lifted ``'*'``
  case).
- **Dense oracle** (``statevector.py``) — brute-force ``O(2^n)``
  ground truth for all of the above, used by the exactness pins.
- **Service handlers** (``handlers.py``) — the three types as
  ``submit()``-able requests on a
  :class:`~tnc_tpu.serve.service.ContractionService` mixed queue with
  per-type batching keys.

See ``docs/serving.md`` ("Query types").
"""

from tnc_tpu.queries.expectation import (  # noqa: F401
    ExpectationProgram,
    bind_expectation,
    pauli_expectation,
    pauli_expectation_value_and_grad,
    pauli_sum_expectation,
)
from tnc_tpu.queries.handlers import (  # noqa: F401
    ExpectationQueryHandler,
    MarginalQueryHandler,
    SampleQueryHandler,
    attach_query_handlers,
)
from tnc_tpu.queries.marginal import (  # noqa: F401
    bind_marginal,
    marginal_sweep,
    wildcard_mask,
)
from tnc_tpu.queries.sampling import (  # noqa: F401
    ChainSampler,
    sample_bitstrings,
)

# NOTE: the dense-oracle helpers live in ``tnc_tpu.queries.statevector``
# (not re-exported here: the module shares its name with its main
# function, and the module is the stable import path).
