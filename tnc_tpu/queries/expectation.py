"""Pauli-string expectation values over sandwich networks.

⟨ψ|P|ψ⟩ for a Pauli string ``P = P₁⊗…⊗Pₙ`` is one contraction of the
circuit ++ adjoint sandwich with the Pauli operators inserted between
the layers (:meth:`~tnc_tpu.builders.circuit_builder.Circuit.
into_expectation_value_network`). Every Pauli string shares the SAME
network structure — only the 2×2 observable leaf values differ — so
this module treats the observable layer exactly like the serving
layer treats bras: the structure plans and compiles once
(:func:`~tnc_tpu.serve.rebind.bind_template` on an
observable-placeholder :class:`~tnc_tpu.builders.circuit_builder.
SandwichTemplate`, plan cache honored) and the terms of a Pauli sum
stack along a batch leg into ONE dispatch
(:mod:`tnc_tpu.ops.batched`).

Gradients ride the existing autodiff-capable jax executors: the
sandwich is an ordinary contraction program, so
``jax.value_and_grad`` through :func:`~tnc_tpu.ops.backends._run_steps`
(or the batched step runner for Pauli sums) differentiates the
expectation w.r.t. any leaf tensor — both circuit layers carry a
parameterized gate (the ket-layer leaf and its adjoint mirror), and
the cotangent convention ``df = Re(sum(g * dT))`` composes them into
d/dθ via the chain rule (see ``tests/test_queries.py``).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.builders.circuit_builder import (
    PAULI_MATRICES,
    Circuit,
    SandwichTemplate,
)
from tnc_tpu.queries.statevector import normalize_pauli

__all__ = [
    "ExpectationProgram",
    "bind_expectation",
    "normalize_terms",
    "pauli_expectation",
    "pauli_sum_expectation",
    "pauli_expectation_value_and_grad",
]


def stacked_observables(paulis: Sequence[str]) -> np.ndarray:
    """Observable leaf values for a batch of Pauli strings:
    ``(B, n, 2, 2)`` in qubit order, in the sandwich leaf layout —
    values come from the ONE layout rule
    (:func:`~tnc_tpu.builders.circuit_builder.observable_leaf_data`,
    which stores the operator transpose), so the batched rebind path
    can never skew from the template networks."""
    from tnc_tpu.builders.circuit_builder import observable_leaf_data

    return np.stack(
        [
            np.stack(
                [
                    observable_leaf_data(PAULI_MATRICES[c]).into_data()
                    for c in pauli
                ]
            )
            for pauli in paulis
        ]
    )


def normalize_terms(
    terms, num_qubits: int
) -> tuple[tuple[complex, str], ...]:
    """Canonicalize a Pauli-sum spec: an iterable of ``(coeff, pauli)``
    pairs (or a bare Pauli string = one unit-coefficient term)."""
    if isinstance(terms, str):
        terms = [(1.0, terms)]
    out = []
    for coeff, pauli in terms:
        out.append((complex(coeff), normalize_pauli(pauli, num_qubits)))
    if not out:
        raise ValueError("a Pauli sum needs at least one term")
    return tuple(out)


class ExpectationProgram:
    """A compiled sandwich program with rebindable observable leaves —
    the ⟨ψ|P|ψ⟩ counterpart of :class:`~tnc_tpu.serve.rebind.
    BoundProgram` (which it wraps: same planning, plan-cache and
    slicing machinery; only the rebound leaf values differ)."""

    def __init__(self, bound) -> None:
        template: SandwichTemplate = bound.template
        if "?" in template.spec:
            raise ValueError(
                "expectation programs rebind observables, not bras "
                "(template spec must be all 'p')"
            )
        self.bound = bound
        self.num_qubits = template.num_qubits

    def values(
        self, paulis: Sequence[str], backend=None
    ) -> np.ndarray:
        """⟨ψ|P|ψ⟩ for every Pauli string, one batched dispatch
        (complex ``(B,)``; imaginary parts are roundoff for the
        Hermitian Pauli alphabet)."""
        from tnc_tpu.ops.backends import JaxBackend, NumpyBackend
        from tnc_tpu.ops.batched import stacked_rows

        paulis = [normalize_pauli(p, self.num_qubits) for p in paulis]
        if not paulis:
            return np.zeros((0,), dtype=np.complex128)
        bound = self.bound
        if backend is None:
            backend = NumpyBackend()
        slots = bound.bra_slots  # observable slots (shared slot contract)
        stacked = stacked_observables(paulis)  # (B, n, 2, 2)
        buffers = list(bound.arrays)
        for i, slot in enumerate(slots):
            buffers[slot] = np.ascontiguousarray(stacked[:, i])
        b = len(paulis)
        if bound.sliced is not None:
            # budget-sliced structures run the slice loop per term
            obs.counter_add("queries.expectation.dispatch", mode="sliced")
            rows = stacked_rows(
                lambda per: backend.execute_sliced(bound.sliced, per),
                buffers, slots, b, bound.program.result_shape,
            )
        elif isinstance(backend, (NumpyBackend, JaxBackend)):
            obs.counter_add("queries.expectation.dispatch", mode="batched")
            rows = backend.execute_batched(bound.program, buffers, slots)
        else:
            obs.counter_add("queries.expectation.dispatch", mode="loop")
            rows = stacked_rows(
                lambda per: backend.execute(bound.program, per),
                buffers, slots, b, bound.program.result_shape,
            )
        return np.asarray(rows).reshape(b).astype(np.complex128)

    def pauli_sum(
        self, terms, backend=None
    ) -> tuple[complex, np.ndarray]:
        """``(sum_t coeff_t ⟨ψ|P_t|ψ⟩, per-term values)`` — the terms
        share this one structure and batch like bras."""
        terms = normalize_terms(terms, self.num_qubits)
        vals = self.values([p for _, p in terms], backend)
        total = complex(sum(c * v for (c, _), v in zip(terms, vals)))
        return total, vals


def bind_expectation(
    circuit: Circuit,
    pathfinder=None,
    plan_cache=None,
    target_size: float | None = None,
) -> ExpectationProgram:
    """Plan/compile the observable-placeholder sandwich of ``circuit``
    (consumed — finalizer semantics; ``copy()`` first to keep it)."""
    from tnc_tpu.serve.rebind import bind_template

    template = circuit.into_sandwich_template("p" * circuit.num_qubits())
    return ExpectationProgram(
        bind_template(template, pathfinder, plan_cache, target_size)
    )


def pauli_expectation(
    circuit: Circuit,
    pauli: str,
    pathfinder=None,
    backend=None,
    plan_cache=None,
    target_size: float | None = None,
) -> complex:
    """⟨ψ|P|ψ⟩ for one Pauli string (``circuit`` consumed).

    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("x"), [reg.qubit(0)])
    >>> pauli_expectation(c, "zi")
    (-1+0j)
    """
    prog = bind_expectation(circuit, pathfinder, plan_cache, target_size)
    return complex(prog.values([pauli], backend)[0])


def pauli_sum_expectation(
    circuit: Circuit,
    terms,
    pathfinder=None,
    backend=None,
    plan_cache=None,
    target_size: float | None = None,
) -> complex:
    """``sum_t coeff_t ⟨ψ|P_t|ψ⟩`` with every term sharing one planned
    sandwich structure and one batched dispatch (``circuit``
    consumed)."""
    prog = bind_expectation(circuit, pathfinder, plan_cache, target_size)
    total, _vals = prog.pauli_sum(terms, backend)
    return total


def pauli_expectation_value_and_grad(
    circuit: Circuit,
    terms,
    wrt: Sequence[int] | None = None,
    dtype: str = "complex64",
):
    """Value and gradient of ``f = Re(sum_t coeff_t ⟨ψ|P_t|ψ⟩)`` w.r.t.
    selected sandwich leaf tensors, through the existing
    autodiff-capable jax executors (``circuit`` consumed).

    The terms batch along the observable leaves exactly like the
    forward path (one structure, one traced program). ``wrt`` indexes
    the sandwich's flat leaf order — the first ``L`` slots are the
    circuit layer (kets then gates, build order), the next ``L`` their
    adjoint mirrors, and the trailing ``n`` the observable slots
    (which carry the batch leg and cannot be differentiated here); the
    default differentiates every circuit-layer AND adjoint-layer gate
    leaf. A parameterized gate θ appears in BOTH layers: with ``g_ket``
    and ``g_adj`` the two cotangents, ``df/dθ = Re(sum(g_ket * dG/dθ))
    + Re(sum(g_adj * d(G†)/dθ))`` (cotangent convention of
    :mod:`tnc_tpu.ops.autodiff`).

    Returns ``(value, per_term_values, grads)`` where ``value`` is the
    real scalar and ``grads[i]`` is the cotangent for ``wrt[i]``.
    """
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.autodiff import _validate_wrt
    from tnc_tpu.ops.backends import _run_steps
    from tnc_tpu.ops.batched import run_steps_batched, thread_batch
    from tnc_tpu.ops.program import flat_leaf_tensors
    from tnc_tpu.serve.rebind import plan_structure

    n = circuit.num_qubits()
    n_circuit = len(circuit.tensor_network.tensors)
    terms = normalize_terms(terms, n)
    template = circuit.into_sandwich_template("p" * n)
    tn = template.network
    leaves = flat_leaf_tensors(tn)
    obs_slots = list(range(len(leaves) - n, len(leaves)))
    obs_set = set(obs_slots)

    path, _slicing, program, _sliced, _result = plan_structure(tn)
    arrays = [
        jnp.asarray(leaf.data.into_data(), dtype=dtype) for leaf in leaves
    ]

    if wrt is None:
        # every gate leaf, both layers (kets and observables excluded)
        wrt = [
            s
            for s in range(2 * n_circuit)
            if len(leaves[s].legs) > 1
        ]
    wrt = _validate_wrt(wrt, len(arrays))
    for s in wrt:
        if s in obs_set:
            raise ValueError(
                "observable slots carry the Pauli-term batch leg; "
                "not differentiable here"
            )

    coeffs = jnp.asarray([c for c, _ in terms], dtype=dtype)
    stacked = jnp.asarray(
        stacked_observables([p for _, p in terms]), dtype=dtype
    )  # (B, n, 2, 2)
    flags, threadable = thread_batch(program, obs_slots)

    def forward(diff_arrays):
        buffers = list(arrays)
        for slot, arr in zip(wrt, diff_arrays):
            buffers[slot] = arr
        for i, slot in enumerate(obs_slots):
            buffers[slot] = stacked[:, i]
        if threadable:
            vals = run_steps_batched(
                jnp, program, list(buffers), flags
            ).reshape(-1)
        else:

            def single(obs_values):
                per = list(buffers)
                for i, slot in enumerate(obs_slots):
                    per[slot] = obs_values[i]
                return _run_steps(jnp, program, per).reshape(-1)[0]

            vals = jax.vmap(single)(stacked)
        return jnp.sum(jnp.real(coeffs * vals)), vals

    diff_in = tuple(arrays[slot] for slot in wrt)
    (value, vals), grads = jax.value_and_grad(forward, has_aux=True)(
        diff_in
    )
    return (
        float(value),
        np.asarray(vals).reshape(len(terms)),
        [np.asarray(g) for g in grads],
    )
