"""Dense statevector oracle for query-engine exactness pins.

Every query type the engine serves — amplitudes, chain-rule sampling
conditionals, Pauli expectation values, marginal probabilities — has a
brute-force ``O(2^n)`` definition over the dense statevector. This
module computes those definitions directly from an (un-finalized)
:class:`~tnc_tpu.builders.circuit_builder.Circuit`, replaying its gate
tensors against a ``(2,)*n`` state array in ``complex128``, so tests
and smoke scripts can pin the tensor-network answers against ground
truth without a second circuit description.

Conventions: qubit 0 is the MOST significant bit — ``amplitude(sv,
bits)`` reads ``sv.reshape(-1)[int(bits, 2)]`` — matching the
bitstring order of :meth:`Circuit.into_amplitude_network`.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from tnc_tpu.builders.circuit_builder import (
    PAULI_MATRICES,
    Circuit,
    normalize_bitstring,
)


def statevector(circuit: Circuit) -> np.ndarray:
    """The dense state C|0…0⟩ of an **un-finalized** circuit as a
    ``(2,)*n`` complex128 array (axis ``q`` = qubit ``q``).

    The circuit is read, not consumed: the builder's tensor list holds
    the |0⟩ kets (one leg each, allocation order) followed by the gate
    tensors (legs = new ++ old) in append order, which is exactly a
    replay script. Use :meth:`Circuit.copy` first if you need the
    oracle AND a finalizer from one circuit.

    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("x"), [reg.qubit(0)])
    >>> statevector(c).reshape(-1).tolist()
    [0j, 0j, (1+0j), 0j]
    """
    if circuit._finalized:
        raise ValueError(
            "statevector needs an un-finalized circuit (copy before "
            "calling a finalizer)"
        )
    n = circuit.num_qubits()
    state = np.zeros((2,) * n if n else (1,), dtype=np.complex128)
    state.reshape(-1)[0] = 1.0

    edge_qubit: dict[int, int] = {}
    next_ket = 0
    for tensor in circuit.tensor_network.tensors:
        legs = list(tensor.legs)
        if len(legs) == 1:  # an initial |0⟩ ket
            edge_qubit[legs[0]] = next_ket
            next_ket += 1
            continue
        k = len(legs) // 2
        new, old = legs[:k], legs[k:]
        qubits = [edge_qubit[e] for e in old]
        for e, q in zip(new, qubits):
            edge_qubit[e] = q
        gate = np.asarray(tensor.data.into_data(), dtype=np.complex128)
        # contract the gate's in-legs with the state's qubit axes; the
        # out-legs land first, then move back to the qubit positions
        out = np.tensordot(gate, state, axes=(list(range(k, 2 * k)), qubits))
        state = np.moveaxis(out, list(range(k)), qubits)
    return state


def amplitude(state: np.ndarray, bits: str | Iterable) -> complex:
    """⟨bits|state⟩ for a fully determined bitstring."""
    bits = normalize_bitstring(bits, state.ndim)
    if "*" in bits:
        raise ValueError("amplitude needs a fully determined bitstring")
    return complex(state[tuple(int(c) for c in bits)])


def marginal_probability(state: np.ndarray, pattern: str | Iterable) -> float:
    """p(determined positions of ``pattern``), the born-rule mass
    summed over every ``*`` position.

    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> marginal_probability(statevector(c), "0*")
    0.4999999999999999
    """
    pattern = normalize_bitstring(pattern, state.ndim)
    probs = np.abs(state) ** 2
    index = tuple(
        slice(None) if c == "*" else int(c) for c in pattern
    )
    return float(np.sum(probs[index]))


def conditional_distribution(
    state: np.ndarray, prefix: str
) -> tuple[float, float]:
    """Unnormalized chain-rule conditionals for the next qubit after a
    sampled ``prefix``: ``(p(prefix + '0'), p(prefix + '1'))`` with
    every later qubit marginalized — the dense counterpart of one
    sampler step (:mod:`tnc_tpu.queries.sampling`)."""
    n = state.ndim
    k = len(prefix)
    if k >= n:
        raise ValueError(f"prefix length {k} leaves no qubit to sample")
    tail = "*" * (n - k - 1)
    return (
        marginal_probability(state, prefix + "0" + tail),
        marginal_probability(state, prefix + "1" + tail),
    )


def apply_paulis(state: np.ndarray, pauli: str) -> np.ndarray:
    """P|state⟩ for a Pauli string (one of ``ixyz`` per qubit)."""
    out = state
    for q, c in enumerate(pauli):
        if c == "i":
            continue
        out = np.moveaxis(
            np.tensordot(PAULI_MATRICES[c], out, axes=([1], [q])), 0, q
        )
    return out


def pauli_expectation(state: np.ndarray, pauli: str) -> complex:
    """⟨state|P|state⟩ by dense math (complex; imaginary part is
    roundoff for Hermitian P)."""
    return complex(
        np.vdot(state.reshape(-1), apply_paulis(state, pauli).reshape(-1))
    )


def sample_oracle(
    state: np.ndarray, n_samples: int, rng: np.random.Generator
) -> list[str]:
    """Chain-rule sampling over the dense conditionals with the SAME
    draw discipline as :class:`~tnc_tpu.queries.sampling.ChainSampler`
    (one uniform vector per qubit position, sample-major) — a seeded
    oracle run and a seeded sampler run over exact-arithmetic circuits
    produce identical streams."""
    n = state.ndim
    prefixes = [""] * n_samples
    for _k in range(n):
        u = rng.random(n_samples)
        for i in range(n_samples):
            p0, p1 = conditional_distribution(state, prefixes[i])
            total = p0 + p1
            p1n = p1 / total if total > 0.0 else 0.5
            prefixes[i] += "1" if u[i] < p1n else "0"
    return prefixes


def probabilities(state: np.ndarray) -> np.ndarray:
    """|state|^2 flattened to ``(2**n,)`` (index = ``int(bits, 2)``)."""
    return (np.abs(state) ** 2).reshape(-1)


def pauli_string_matrix(pauli: str) -> np.ndarray:
    """The dense ``(2^n, 2^n)`` operator of a Pauli string (test-sized
    ``n`` only)."""
    out = np.array([[1.0 + 0.0j]])
    for c in pauli:
        out = np.kron(out, PAULI_MATRICES[c])
    return out


def normalize_pauli(pauli: str | Sequence[str], num_qubits: int) -> str:
    """Canonicalize a Pauli-string spec: lowercase, length-checked,
    alphabet ``ixyz`` (errors name the offending position).

    >>> normalize_pauli("IXz", 3)
    'ixz'
    """
    chars = [str(c).lower() for c in pauli]
    if len(chars) != num_qubits:
        raise ValueError(
            f"Pauli string length {len(chars)} != qubit count {num_qubits}"
        )
    for pos, c in enumerate(chars):
        if c not in PAULI_MATRICES:
            raise ValueError(
                f"invalid Pauli character {c!r} at position {pos} "
                "(only 'i', 'x', 'y' and 'z' are allowed)"
            )
    return "".join(chars)
