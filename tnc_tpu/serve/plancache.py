"""Persistent, LRU-bounded plan cache for the serving path.

Planning is the expensive, bitstring-independent part of an amplitude
query: a path search over the circuit structure, optional
slice-and-reconfigure, and the hoist split. This cache persists exactly
that — ``{path, slicing, hoist split, executor config}`` as plain JSON
(never pickle: a corrupted or adversarial entry must degrade to a
replan, not arbitrary code) — keyed by a **structure digest** of the
network's flat leaves (legs + bond dims), which every bitstring of a
circuit shares. A repeat circuit therefore performs zero pathfinding
(no ``plan.find_path`` span), and because the rebuilt
:class:`~tnc_tpu.ops.program.ContractionProgram` has the same
signature, a warm process-level jit cache also skips compilation.

Discipline (shared with the other on-disk artifact stores):

- digests come from the one canonical helper
  (:func:`tnc_tpu.utils.digest.stable_digest` — also behind
  ``resilience.checkpoint.signature_hash`` and
  ``benchmark.cache.cache_key``), stable across hash seeds and dict
  ordering;
- every entry records ``program_sig`` = the rebuilt program's
  ``signature_digest()``, validated after rebuild — a plan whose
  compiler output drifted (planner/compiler version change) is
  invalidated rather than trusted;
- writes are atomic (temp file + ``os.replace``);
- the cache is LRU-bounded by entry count (mtime = last use; loads
  touch it), with corrupted entries deleted and counted, never raised.

**Shared store.** The directory is safe to share between N serving
replicas (processes, containers mounting one volume): entries are
content-addressed by the structure digest, every writer publishes
through its own uniquely named temp file + atomic ``os.replace`` (two
replicas racing on one key leave whichever complete entry landed last —
never an interleaved file), and readers are lock-free (``os.replace``
guarantees a reader sees either the old or the new complete entry).
N replicas of one fleet therefore plan each structure **once**: the
first replica to finish planning publishes, every other replica's
lookup is a planner-span-free hit. :func:`PlanCache.entry_fingerprint`
gives replicas a cheap change probe — the background replanner's
improved plans (published through the same store) become visible to
every replica, and a
:class:`~tnc_tpu.serve.replan.SharedCacheWatcher` adopts them into a
running service.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from pathlib import Path

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.contractionpath.slicing import Slicing
from tnc_tpu.tensornetwork.tensor import CompositeTensor
from tnc_tpu.utils.digest import stable_digest

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1


def network_structure_digest(
    tn: CompositeTensor, target_size: float | None = None
) -> str:
    """Stable digest of the network's contraction-relevant structure:
    every flat leaf's (legs, dims), in slot order. Bitstring-independent
    by construction — bra *values* never enter the digest — so all
    2^n amplitude networks of one circuit share a key.

    ``target_size`` (the caller's peak-memory budget) is part of the
    key: a plan is only reusable under the budget it was made for — an
    unsliced plan cached without a budget must never answer a
    budget-constrained lookup (it would OOM the device the budget
    modeled). Planner *identity* is deliberately not keyed: a cache
    directory is assumed to serve one planner configuration, like the
    benchmark plan cache's scheme prefix."""
    from tnc_tpu.ops.program import flat_leaf_tensors

    leaves = flat_leaf_tensors(tn)
    return stable_digest(
        "tnc-plan-v%d" % FORMAT_VERSION,
        tuple((tuple(t.legs), tuple(t.bond_dims)) for t in leaves),
        float(target_size) if target_size is not None else None,
    )


class PlanCache:
    """On-disk plan store: ``<dir>/<structure-digest>.json`` entries.

    >>> import tempfile
    >>> cache = PlanCache(tempfile.mkdtemp(), max_entries=2)
    >>> plan = {"version": 1, "pairs": [[0, 1]], "program_sig": "x"}
    >>> cache.store("k1", plan)
    >>> cache.load("k1")["pairs"]
    [[0, 1]]
    >>> cache.load("missing") is None
    True
    """

    def __init__(self, directory: str | Path, max_entries: int = 256):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_entries = max(1, int(max_entries))
        # explicit per-key hit counts (process-local; the on-disk LRU
        # touch only *implies* heat via mtime): what the background
        # replanner reads to pick which entries deserve hyper-time
        self._hits: dict[str, int] = {}
        self._hits_lock = threading.Lock()
        # process-local event counters mirroring the obs families —
        # stats() and the service's /metrics surface read these, so
        # cache efficacy is observable with obs tracing off
        self._counts = {
            k: 0
            for k in (
                "hit", "miss", "store", "evicted", "corrupt",
                "invalidated", "store_failed",
            )
        }

    def _count(self, key: str) -> None:
        with self._hits_lock:
            self._counts[key] = self._counts.get(key, 0) + 1
        obs.counter_add(f"serve.plan_cache.{key}")

    def stats(self) -> dict:
        """Process-local cache efficacy: event counts (hit / miss /
        store / evicted / corrupt / invalidated / store_failed) plus
        the current on-disk entry count."""
        with self._hits_lock:
            counts = dict(self._counts)
        return {"counts": counts, "entries": len(self)}

    def _path(self, key: str) -> Path:
        return self.directory / f"{key}.json"

    def key_for_network(
        self, tn: CompositeTensor, target_size: float | None = None
    ) -> str:
        return network_structure_digest(tn, target_size)

    # -- entries -----------------------------------------------------------

    def record_for(
        self,
        path: ContractionPath,
        program,
        slicing: Slicing | None = None,
        sliced_program=None,
        executor: dict | None = None,
        flops: float | None = None,
        peak: float | None = None,
        finder: str | None = None,
        target_size: float | None = None,
        predicted_seconds: float | None = None,
    ) -> dict:
        """Build the JSON plan record for a freshly planned structure:
        path pairs, optional slicing + hoist split (computed from
        ``sliced_program`` when given), executor config, and the
        program-signature digest the entry is validated against.
        ``finder``/``predicted_seconds`` record plan *provenance* — the
        background replanner only spends hyper-optimizer time on entries
        a fast greedy planner produced, and swaps strictly on a
        predicted-cost win."""
        plan: dict = {
            "version": FORMAT_VERSION,
            "pairs": path.to_obj(),
            "slicing": slicing.to_obj() if slicing is not None else None,
            "hoist": None,
            "executor": dict(executor) if executor else None,
            "program_sig": program.signature_digest(),
            "created_at": time.time(),
            "finder": finder,
            "target_size": (
                float(target_size) if target_size is not None else None
            ),
        }
        if predicted_seconds is not None:
            plan["predicted_seconds"] = float(predicted_seconds)
        if sliced_program is not None:
            from tnc_tpu.ops.hoist import hoist_split_counts

            plan["hoist"] = hoist_split_counts(sliced_program)
            plan["sliced_sig"] = sliced_program.signature_digest()
        if flops is not None:
            plan["flops"] = float(flops)
        if peak is not None:
            plan["peak"] = float(peak)
        return plan

    def validate(self, plan: dict, program) -> bool:
        """True when ``program`` (rebuilt from the cached path) matches
        the signature the plan was stored with."""
        return plan.get("program_sig") == program.signature_digest()

    @staticmethod
    def plan_path(plan: dict) -> ContractionPath:
        return ContractionPath.from_obj(plan["pairs"])

    @staticmethod
    def plan_slicing(plan: dict) -> Slicing | None:
        obj = plan.get("slicing")
        return Slicing.from_obj(obj) if obj else None

    # -- storage -----------------------------------------------------------

    def load(self, key: str) -> dict | None:
        """The cached plan, or None (absent / corrupt / wrong version —
        corruption is deleted and counted, never raised: a bad entry
        degrades to a replan)."""
        target = self._path(key)
        try:
            with open(target, "r", encoding="utf-8") as fh:
                plan = json.load(fh)
            if (
                not isinstance(plan, dict)
                or plan.get("version") != FORMAT_VERSION
                or not isinstance(plan.get("pairs"), list)
            ):
                raise ValueError(f"unusable plan entry: {plan!r:.80}")
        except FileNotFoundError:
            self._count("miss")
            return None
        except Exception as exc:  # noqa: BLE001 — any corruption → replan
            logger.warning(
                "plan cache entry %s unreadable (%s: %s); dropping it",
                target, type(exc).__name__, exc,
            )
            self._count("corrupt")
            self._count("miss")
            try:
                target.unlink(missing_ok=True)
            except OSError:
                pass
            return None
        self._count("hit")
        with self._hits_lock:
            self._hits[key] = self._hits.get(key, 0) + 1
        try:  # LRU touch: mtime records last use
            os.utime(target)
        except OSError:
            pass
        return plan

    def hits(self, key: str) -> int:
        """Process-local hit count for ``key`` (successful loads)."""
        with self._hits_lock:
            return self._hits.get(key, 0)

    def hot_keys(self, limit: int = 8) -> list[str]:
        """Keys by descending hit count — the explicit heat ranking the
        LRU mtimes only imply. The single-structure
        :class:`~tnc_tpu.serve.replan.BackgroundReplanner` gates on
        per-key :meth:`hits` (``min_hits``); this ranking is the hook
        for multi-structure deployments and dashboards.

        >>> import tempfile
        >>> c = PlanCache(tempfile.mkdtemp())
        >>> c.store("a", {"version": 1, "pairs": []})
        >>> _ = c.load("a"); _ = c.load("a"); _ = c.load("missing")
        >>> c.hot_keys()
        ['a']
        """
        with self._hits_lock:
            ranked = sorted(self._hits.items(), key=lambda kv: (-kv[1], kv[0]))
        return [k for k, n in ranked[: max(limit, 0)] if n > 0]

    def entry_fingerprint(self, key: str) -> str | None:
        """Cheap content probe for ``key``'s on-disk entry: a digest of
        the entry's raw bytes, or ``None`` when absent/unreadable.
        Replicas poll this to notice another replica's publish (a
        background replanner's swap, a fresh plan) without parsing the
        JSON — the read is lock-free (``os.replace`` publishes whole
        files, so the bytes are always one complete entry)."""
        try:
            with open(self._path(key), "rb") as fh:
                return stable_digest("plan-bytes", fh.read())
        except OSError:
            return None

    def store(self, key: str, plan: dict) -> None:
        """Atomic write + LRU eviction down to ``max_entries``.

        Best-effort, mirroring :meth:`load`: the cache is an
        optimization, so a write failure (disk full, permissions, dir
        removed) is logged and counted — never raised. The caller holds
        the freshly planned program in memory either way.

        Safe under concurrent writers (N replicas sharing the
        directory): the temp file is uniquely named per writer (pid +
        random suffix), so two replicas racing on one key can never
        interleave bytes — the last complete ``os.replace`` wins."""
        target = self._path(key)
        tmp = target.with_name(
            f"{key}.{os.getpid()}.{uuid.uuid4().hex[:8]}.json.tmp"
        )
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(plan, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, target)
        except OSError as exc:
            logger.warning(
                "plan cache store of %s failed (%s: %s); serving from "
                "the in-memory plan", target, type(exc).__name__, exc,
            )
            self._count("store_failed")
            try:  # don't strand the partial temp file
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            return
        self._count("store")
        self._evict()

    def invalidate(self, key: str) -> None:
        try:
            self._path(key).unlink(missing_ok=True)
        except OSError:
            pass
        with self._hits_lock:
            self._hits.pop(key, None)
        self._count("invalidated")

    def _entries(self) -> list[Path]:
        return [
            p for p in self.directory.glob("*.json") if p.is_file()
        ]

    def _evict(self) -> None:
        # reap orphaned temp files a crashed writer left behind (never
        # fresh ones — another replica may be mid-publish right now)
        now = time.time()
        for orphan in self.directory.glob("*.json.tmp"):
            try:
                if now - orphan.stat().st_mtime > 3600.0:
                    orphan.unlink(missing_ok=True)
            except OSError:
                continue
        entries = self._entries()
        if len(entries) <= self.max_entries:
            return
        def mtime(p: Path) -> float:
            try:
                return p.stat().st_mtime
            except OSError:
                return 0.0
        entries.sort(key=mtime)
        for victim in entries[: len(entries) - self.max_entries]:
            try:
                victim.unlink(missing_ok=True)
                self._count("evicted")
                logger.info("plan cache evicted %s (LRU)", victim.name)
            except OSError:
                continue
            # heat follows the entry out: hits()/hot_keys() must not
            # rank keys the cache no longer holds, and the dict must
            # not grow one entry per structure ever served
            with self._hits_lock:
                self._hits.pop(victim.stem, None)

    def __len__(self) -> int:
        return len(self._entries())
