"""Anytime background replanning for the serving path.

A cache miss answers from a fast greedy plan so the first request never
waits on a hyper-optimizer — but without this module that plan is
frozen: the service keeps dispatching whatever a cache miss happened to
get, even though PLANNER_QUALITY.json records multi-order-of-magnitude
flop gaps between greedy and hyper plans on hard structures.

:class:`BackgroundReplanner` closes the loop. A low-priority daemon
thread watches an attached :class:`~tnc_tpu.serve.service.
ContractionService` and, **between requests** (it only works while the
queue is empty), hyper-optimizes the service's bound structure once it
is hot enough (``min_hits`` against the structure's request/cache heat;
:meth:`~tnc_tpu.serve.plancache.PlanCache.hot_keys` exposes the same
ranking for multi-structure deployments and dashboards). A candidate
plan replaces the incumbent only when its predicted cost beats it by
``margin`` under the replanner's objective (predicted *seconds* under a
:class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` when one is given,
naive-op flops otherwise — never wall-clock luck).

Swap safety:

- the **same atomic-write path** as any plan store
  (:meth:`PlanCache.store`: temp file + ``os.replace``) publishes the
  improved plan, under the same structure digest — which embeds the
  ``target_size`` budget, so the replanner re-plans under the budget
  the entry was keyed with and can never swap an over-budget plan into
  a budget-constrained slot;
- the new plan's ``program_sig`` is recorded exactly like a fresh
  plan's, so later processes rebuild-and-validate it normally;
- the in-memory :class:`~tnc_tpu.serve.rebind.BoundProgram` is rebuilt
  from the cache entry (zero pathfinding — the normal cache-hit path)
  on the replanner thread, then staged via
  :meth:`ContractionService.swap_bound`; the dispatcher adopts it at a
  batch boundary, so every request runs wholly under one plan and
  amplitudes stay correct through the swap (both plans contract the
  same network).

Counters: ``serve.replan.attempt`` / ``serve.replan.swap`` /
``serve.replan.reject`` (+ the service-side ``serve.replan.adopted``).

Fleet visibility: because swaps publish through the shared on-disk
store, a :class:`SharedCacheWatcher` on any OTHER replica notices the
new entry (a cheap byte-fingerprint probe) and adopts it into its own
running service through the identical rebuild-and-swap path — one
replica's background search improves every replica sharing the cache
directory (``serve.replan.shared_adopt``).

Budget-constrained structures get the Hyperoptimizer's **joint
tree+slice search** (its default with a ``target_size``): the
background search optimizes the sliced total directly and hands its
slice set to a seeded thin ``slice_and_reconfigure`` repair
(:func:`~tnc_tpu.serve.rebind.plan_structure`), so the plans that
stream into live replicas through the shared cache are sliced-optimal,
not flop-optimal-then-sliced.
"""

from __future__ import annotations

import logging
import threading

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_cost import (
    CalibratedObjective,
    FlopsObjective,
    PathObjective,
)
from tnc_tpu.contractionpath.contraction_path import ContractionPath
from tnc_tpu.ops.program import flat_leaf_tensors
from tnc_tpu.serve.rebind import (
    bind_template,
    plan_signature,
    plan_structure,
)

logger = logging.getLogger(__name__)

#: finders whose plans are already search-quality: the replanner leaves
#: them alone (replanning a hyper plan with the same hyper is a no-op
#: that burns background CPU forever)
_FAST_FINDERS = (None, "", "Greedy", "Cotengrust")


def plan_predicted_cost(
    inputs, replace_pairs, slicing, objective: PathObjective
) -> float:
    """Predicted cost of a stored plan (flat replace path + optional
    slicing) under ``objective`` — the comparison key for swap
    decisions, computed identically for incumbent and candidate."""
    pairs = list(replace_pairs)
    if slicing is not None and slicing.num_slices > 1:
        return objective.sliced_path_cost(inputs, pairs, slicing)
    return objective.path_cost(inputs, ContractionPath.simple(pairs))


class SharedCacheWatcher:
    """Adopt plan-cache publishes made by OTHER replicas.

    A fleet of serving replicas shares one
    :class:`~tnc_tpu.serve.plancache.PlanCache` directory; when any of
    them (usually the one running a :class:`BackgroundReplanner`)
    publishes an improved plan for this service's structure, the watcher
    sees the entry's byte fingerprint change, rebuilds a
    :class:`~tnc_tpu.serve.rebind.BoundProgram` through the normal
    cache-hit path (zero pathfinding), and stages it via
    :meth:`~tnc_tpu.serve.service.ContractionService.swap_bound` — the
    same batch-boundary adoption as a local replan, so amplitudes stay
    correct through the swap. An entry whose rebuilt program matches
    the serving one (a same-plan re-publish, or our own store) is
    skipped.

    >>> SharedCacheWatcher.__name__
    'SharedCacheWatcher'
    """

    def __init__(
        self,
        service,
        plan_cache,
        poll_interval_s: float = 0.25,
    ):
        self.service = service
        self.plan_cache = plan_cache
        self.poll_interval_s = float(poll_interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        bound = service.bound
        self._key = plan_cache.key_for_network(
            bound.template.network, bound.target_size
        )
        # baseline: whatever is on disk NOW is what this service serves
        # (or close enough — adopting it immediately would be a no-op
        # swap anyway, caught by the signature check)
        self._seen = plan_cache.entry_fingerprint(self._key)
        # a publish whose adoption keeps raising (corrupt/incompatible
        # foreign entry) is abandoned after max_failures consecutive
        # attempts — the full rebuild must not re-run 4x/second forever.
        # A NEW publish (different fingerprint) re-arms the watcher.
        self.max_failures = 5
        self._fail_count = 0
        self._last_fp = None
        self.stats = {"adopts": 0, "skips": 0, "abandons": 0}

    def start(self) -> "SharedCacheWatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tnc-serve-cachewatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=60.0)

    def __enter__(self) -> "SharedCacheWatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def poll_once(self) -> bool:
        """One fingerprint probe; True when a foreign publish was
        adopted (exposed for deterministic tests — the thread loop is
        just this on a timer)."""
        fp = self.plan_cache.entry_fingerprint(self._key)
        self._last_fp = fp
        if fp is None or fp == self._seen:
            return False
        # _seen advances only after the publish is fully handled — a
        # rebuild/swap that raises here (transient I/O on the shared
        # volume, a rejected swap) is retried on the next poll instead
        # of being silently dropped until some future publish
        bound = self.service.bound
        new_bound = bind_template(
            bound.template, None, self.plan_cache, bound.target_size,
            bound.reuse.store if bound.reuse is not None else None,
        )
        if plan_signature(new_bound) == plan_signature(bound):
            # same plan re-published (or our own write): nothing to adopt
            self._seen = fp
            self.stats["skips"] += 1
            return False
        self.service.swap_bound(new_bound)
        self._seen = fp
        self.stats["adopts"] += 1
        obs.counter_add("serve.replan.shared_adopt")
        logger.info(
            "adopted shared-cache plan for %s (foreign publish)",
            self._key[:12],
        )
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
                self._fail_count = 0
            except Exception:  # noqa: BLE001 — the watcher must survive
                self._fail_count += 1
                if (
                    self._fail_count >= self.max_failures
                    and self._last_fp is not None
                ):
                    # abandon exactly the publish that kept failing:
                    # advancing _seen to its fingerprint stops the
                    # rebuild churn; any later publish re-arms
                    self._seen = self._last_fp
                    self._fail_count = 0
                    self.stats["abandons"] += 1
                    obs.counter_add("serve.replan.shared_abandon")
                    logger.exception(
                        "shared-cache publish for %s abandoned after %d "
                        "failed adoptions (re-armed by the next publish)",
                        self._key[:12], self.max_failures,
                    )
                else:
                    logger.exception("shared-cache watch poll failed")


class BackgroundReplanner:
    """Hyper-optimize hot plan-cache entries between requests.

    >>> # constructed against a running service; see tests/test_serve.py
    >>> BackgroundReplanner.__name__
    'BackgroundReplanner'
    """

    def __init__(
        self,
        service,
        plan_cache,
        optimizer=None,
        cost_model=None,
        margin: float = 0.95,
        min_hits: int = 0,
        poll_interval_s: float = 0.02,
    ):
        """``optimizer``: the improving pathfinder (default: a bounded
        :class:`~tnc_tpu.contractionpath.paths.hyper.Hyperoptimizer`
        sized for background work). Each structure gets ONE search:
        the optimizer is seeded/deterministic, so its verdict — swap
        or reject — is final and re-attempting would redo identical
        work ("anytime" means the service answers from the fast plan
        immediately and adopts the improvement whenever the background
        search lands, not unbounded improvement rounds; pass a larger
        ``optimizer`` for a deeper single search).
        ``cost_model``: a fitted :class:`~tnc_tpu.obs.calibrate.
        CalibratedCostModel` — swap decisions then compare predicted
        seconds; without one they compare flops. ``margin``: the
        candidate must be strictly cheaper than ``margin * incumbent``
        (default 5% better) so plan churn never oscillates on noise.
        ``min_hits``: leave the structure alone until it is hot — the
        larger of its plan-cache hit count and the service's completed
        request count must reach this (a cache-missed structure has
        zero cache hits by definition, so request traffic is what
        proves it hot)."""
        self.service = service
        self.plan_cache = plan_cache
        self.cost_model = cost_model
        self.objective: PathObjective = (
            CalibratedObjective(cost_model)
            if cost_model is not None
            else FlopsObjective()
        )
        self._default_optimizer = optimizer is None
        if optimizer is None:
            from tnc_tpu.contractionpath.paths.hyper import Hyperoptimizer

            optimizer = Hyperoptimizer(
                ntrials=4,
                polish_rounds=2,
                polish_steps=1000,
                reconfigure_budget=5.0,
                objective=(
                    self.objective if cost_model is not None else None
                ),
            )
        self.optimizer = optimizer
        self.margin = float(margin)
        self.min_hits = int(min_hits)
        self.poll_interval_s = float(poll_interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._done_keys: set[str] = set()
        # memoized (bound object, its cache key): the poll loop runs
        # ~50x/s and must not recompute the full network structure
        # digest every tick just to find the key in _done_keys
        self._keyed_bound = None
        self._keyed_key: str | None = None
        self.stats = {
            "attempts": 0, "swaps": 0, "rejects": 0, "measured_margins": 0,
            "delegated": 0,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BackgroundReplanner":
        if self._thread is not None:
            return self
        self.service._replanner = self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tnc-serve-replan", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=60.0)

    def __enter__(self) -> "BackgroundReplanner":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- cost-truth integration --------------------------------------------

    def measured_incumbent(self) -> float | None:
        """The serving plan's measured mean dispatch seconds from the
        cost-truth scoreboard — the margin's incumbent cost when warm.
        None without a seconds objective (measured seconds are not
        comparable to a flops objective), without cost-truth, or while
        the scoreboard row is cold."""
        if self.cost_model is None:
            return None
        fn = getattr(self.service, "measured_plan_seconds", None)
        return fn() if fn is not None else None

    def adopt_cost_model(self, model) -> None:
        """Adopt a new cost-model generation (the service calls this at
        the batch boundary where it adopts one): the seconds objective
        re-prices under the new constants, and settled per-structure
        verdicts re-open — a plan rejected under stale pricing may win
        under the truth. No-op for a flops-objective replanner (its
        decisions never consumed the model)."""
        if model is None or self.cost_model is None:
            return
        self.cost_model = model
        self.objective = CalibratedObjective(model)
        if self._default_optimizer and hasattr(self.optimizer, "objective"):
            self.optimizer.objective = self.objective
        self._done_keys.clear()

    # -- worker ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            # low priority: only think while the service is idle
            if self.service.queue_depth() > 0:
                continue
            try:
                self._attempt_once()
            except Exception:  # noqa: BLE001 — the worker must survive
                logger.exception("background replan attempt failed")
                # abandon the structure: without this a persistent
                # planning failure re-runs a full hyper search every
                # poll interval, burning a core and spamming the log
                try:
                    bound = self.service.bound
                    self._done_keys.add(
                        self.plan_cache.key_for_network(
                            bound.template.network, bound.target_size
                        )
                    )
                except Exception:  # noqa: BLE001 — key derivation too
                    pass

    def _candidate_bound(self):
        """The service's current bound, if it still deserves replanning
        work; ``None`` otherwise."""
        bound = self.service.bound
        if bound is self._keyed_bound:
            key = self._keyed_key
        else:
            key = self.plan_cache.key_for_network(
                bound.template.network, bound.target_size
            )
            self._keyed_bound, self._keyed_key = bound, key
        if key in self._done_keys:
            return None, key
        if not bound.plan:
            # no cache record: the serving plan's provenance and true
            # cost are unknown (the structure was bound without this
            # cache, e.g. an explicit bind_circuit(pathfinder=...)) —
            # pricing a greedy reconstruction as the incumbent could
            # swap OUT a better plan than it swaps in. Leave it alone.
            self._done_keys.add(key)
            return None, key
        if bound.plan.get("finder") not in _FAST_FINDERS:
            return None, key  # already search-quality
        if self.min_hits > 0:
            # heat = cache hits OR served requests: a cache-missed
            # structure never load()s again in-process, so its traffic
            # is the only signal that it is worth hyper time
            served = self.service.stats()["counts"].get("completed", 0)
            if max(self.plan_cache.hits(key), served) < self.min_hits:
                return None, key
        return bound, key

    def _attempt_once(self) -> bool:
        """One anytime improvement round; True when a swap happened."""
        bound, key = self._candidate_bound()
        if bound is None:
            return False
        self.stats["attempts"] += 1
        obs.counter_add("serve.replan.attempt")

        # with a planner fleet attached, hot-key searches fan out over
        # idle replicas instead of running one local hyper trial set —
        # one code path for replanning and fleet planning, no race on
        # the same cache key. The local search below stays the
        # no-fleet fallback.
        pod = getattr(self.service, "_plansvc", None)
        if pod is not None and pod.supports(bound):
            self.stats["delegated"] += 1
            obs.counter_add("serve.replan.delegated")
            swapped = pod.delegate(bound, key)
            if swapped:
                self.stats["swaps"] += 1
            self._done_keys.add(key)
            return swapped

        if (
            self._default_optimizer
            and getattr(self.optimizer, "target_size", None)
            != bound.target_size
        ):
            # budget-constrained structure: the default hyper must pick
            # its winner by sliced cost under the structure's budget
            # (sliced_score), not raw flops — otherwise the candidate is
            # the exact misranking its own selection warns about
            self.optimizer.target_size = bound.target_size
        tn = bound.template.network
        leaves = flat_leaf_tensors(tn)
        path, slicing, program, sliced, result = plan_structure(
            tn, self.optimizer, bound.target_size,
            cost_model=self.cost_model,
        )
        candidate_cost = plan_predicted_cost(
            leaves, path.toplevel, slicing, self.objective
        )

        # _candidate_bound guarantees a cache record: the incumbent is
        # priced from the plan actually serving, never a reconstruction
        incumbent_path = ContractionPath.from_obj(bound.plan["pairs"])
        incumbent_slicing = self.plan_cache.plan_slicing(bound.plan)
        incumbent_cost = plan_predicted_cost(
            leaves, incumbent_path.toplevel, incumbent_slicing,
            self.objective,
        )
        # cost-truth scoreboard: when the incumbent's MEASURED dispatch
        # seconds are warm, the margin compares against reality instead
        # of the prediction — a plan that predicts well but measures
        # badly becomes beatable. Seconds-objective only (a measured
        # second cannot be compared against a flop count); cold
        # scoreboard falls back to the prediction.
        measured = self.measured_incumbent()
        if measured is not None:
            incumbent_cost = measured
            self.stats["measured_margins"] += 1
            obs.counter_add("serve.replan.measured_margin")

        if not candidate_cost < self.margin * incumbent_cost:
            self.stats["rejects"] += 1
            obs.counter_add("serve.replan.reject")
            # this optimizer's verdict is in; don't spin on the key
            self._done_keys.add(key)
            logger.info(
                "replan rejected for %s: candidate %.3e !< %.2f * "
                "incumbent %.3e", key[:12], candidate_cost, self.margin,
                incumbent_cost,
            )
            return False

        # publish: the SAME atomic-write path every fresh plan uses,
        # under the same (structure, budget) key
        plan = self.plan_cache.record_for(
            path,
            program,
            slicing=slicing,
            sliced_program=sliced,
            flops=result.flops,
            peak=result.size,
            finder=type(self.optimizer).__name__,
            target_size=bound.target_size,
            predicted_seconds=(
                candidate_cost if self.cost_model is not None else None
            ),
        )
        self.plan_cache.store(key, plan)
        # rebuild the in-memory BoundProgram through the normal
        # cache-hit path (zero pathfinding) and stage the swap
        new_bound = bind_template(
            bound.template, None, self.plan_cache, bound.target_size,
            bound.reuse.store if bound.reuse is not None else None,
        )
        if plan_signature(new_bound) != program.signature_digest():
            # the store was best-effort and evidently did not stick
            # (disk full, cache dir gone): the rebuild fell back to a
            # fresh default plan, which is NOT the improvement we
            # priced — swapping it in (and counting a hyper swap)
            # would be a lie. Abandon quietly; the incumbent stands.
            self.stats["rejects"] += 1
            obs.counter_add("serve.replan.store_lost")
            self._done_keys.add(key)
            logger.warning(
                "replan swap for %s abandoned: improved plan did not "
                "survive the cache round-trip (store failed?)", key[:12],
            )
            return False
        self.service.swap_bound(new_bound)
        self._done_keys.add(key)
        self.stats["swaps"] += 1
        obs.counter_add("serve.replan.swap")
        logger.info(
            "replan swap for %s: predicted cost %.3e -> %.3e (%s)",
            key[:12], incumbent_cost, candidate_cost, self.objective.name,
        )
        return True
