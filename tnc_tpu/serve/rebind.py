"""Bra rebinding: many bitstrings through one compiled program.

An amplitude network's *structure* is bitstring-independent — the
planner's path, the compiled :class:`~tnc_tpu.ops.program.
ContractionProgram`, its signature (and therefore the jit cache key),
and every gate leaf are shared by all ``2^n`` bitstrings; only the
2-element ⟨0|/⟨1| bra leaves differ. This module treats the program as
a reusable symbolic expression bound to fresh bra leaf data per request
(the EinExprs view, arXiv:2403.18030): a :class:`BoundProgram` is built
once per circuit structure and each query is O(contract-residual) — no
replanning, no retracing.

Batching: ``B`` bitstrings stack their one-hot bras along a new leading
batch leg. The primary path *threads that leg through the affected
PairSteps* — :func:`thread_batch` marks, per step, which operands carry
it, and :func:`apply_step_batched` issues one batched matmul per
touched step (``xp.matmul`` broadcasts the un-batched operand), so the
whole batch is one dispatch and steps the batch leg never reaches run
exactly once. Per-batch-entry GEMMs see the same operands in the same
order as the singleton program, so on the numpy oracle a batch of B
bit-compares to B sequential contractions (pinned by
``tests/test_serve.py``). A step that cannot carry the leg (its
batched operand has a staged device prep plan, whose op shapes are
baked flat) degrades the whole program to the vmap/stacked-dispatch
fallback (:meth:`JaxBackend.execute_batched` on device, a per-entry
loop on the host oracle).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Iterable, Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.builders.circuit_builder import BASIS_STATES, AmplitudeTemplate
from tnc_tpu.ops.backends import Backend, JaxBackend, NumpyBackend
from tnc_tpu.ops.batched import (  # noqa: F401 — re-exported serving API
    apply_step_batched,
    run_steps_batched,
    stacked_rows,
    thread_batch,
)
from tnc_tpu.ops.program import (
    ContractionProgram,
    build_program,
    flat_leaf_tensors,
)
from tnc_tpu.ops.sliced import build_sliced_program

logger = logging.getLogger(__name__)

def pow2_bucket(n: int) -> int:
    """Round a batch size up to the next power of two — THE bucketing
    rule for batched serving shapes: XLA compiles one executable per
    padded batch shape (below), and the SLO drift detector groups
    dispatch measurements by the same rule
    (:func:`tnc_tpu.serve.service.batch_bucket`) so its buckets stay in
    one-to-one correspondence with compiled executables.

    >>> [pow2_bucket(n) for n in (1, 2, 3, 8, 9)]
    [1, 2, 4, 8, 16]
    """
    return 1 << max(int(n) - 1, 0).bit_length()


def stacked_bras(batch_bits: Sequence[str]) -> np.ndarray:
    """One-hot bra values for a batch: ``(B, n_det, 2)``, qubit order.
    Values come from the builder's canonical
    :data:`~tnc_tpu.builders.circuit_builder.BASIS_STATES` table (one
    definition for kets, bras and sweep values alike).

    >>> stacked_bras(["01"]).tolist()[0]
    [[(1+0j), 0j], [0j, (1+0j)]]
    """
    return np.stack(
        [np.stack([BASIS_STATES[c] for c in bits]) for bits in batch_bits]
    )


# One traced threaded-batch executable per (program, flags); retraces
# per batch size like the vmap path. Locked: services dispatch from a
# worker thread while tests touch the cache from the main thread.
_THREADED_JIT_CACHE: "OrderedDict[tuple, Any]" = OrderedDict()
_THREADED_JIT_CACHE_MAX = 128
_THREADED_JIT_LOCK = threading.Lock()


def _jit_threaded(program: ContractionProgram, flags) -> Any:
    import jax
    import jax.numpy as jnp

    key = (program.signature(), flags)
    with _THREADED_JIT_LOCK:
        fn = _THREADED_JIT_CACHE.get(key)
        if fn is not None:
            _THREADED_JIT_CACHE.move_to_end(key)
    obs.counter_add(
        "jit_cache.hit" if fn is not None else "jit_cache.miss"
    )
    if fn is None:

        def run(buffers):
            return run_steps_batched(jnp, program, list(buffers), flags)

        fn = jax.jit(run)
        with _THREADED_JIT_LOCK:
            _THREADED_JIT_CACHE[key] = fn
            while len(_THREADED_JIT_CACHE) > _THREADED_JIT_CACHE_MAX:
                _THREADED_JIT_CACHE.popitem(last=False)
    return fn


@dataclass
class BoundProgram:
    """A compiled amplitude program with rebindable bra leaves.

    Built once per circuit *structure* (:func:`bind_template`); each
    :meth:`amplitudes` call swaps per-request bra values into the bra
    slots and dispatches — no replanning, no retracing (the program
    signature, and therefore every jit cache key, is shared).
    """

    template: AmplitudeTemplate
    program: ContractionProgram
    arrays: list[np.ndarray]  # leaf data; bra slots hold placeholders
    bra_slots: tuple[int, ...]  # one per determined qubit, qubit order
    batch_flags: tuple[tuple[bool, bool], ...]
    threadable: bool  # batch leg threads through every touched step
    plan: dict = field(default_factory=dict)  # plan-cache record (if any)
    # the budget this structure was planned under (part of the cache
    # key): a replanner must re-plan under the SAME budget for the swap
    # to be safe
    target_size: float | None = None
    # HBM-constrained structures carry a sliced plan: each request runs
    # the slice loop (stacked dispatch; the batch leg stops here)
    sliced: Any = None  # SlicedProgram | None
    # cross-request reuse (bind_template(..., reuse_store=)): `program`
    # is then the per-request RESIDUAL and the cached-subtree inputs are
    # materialized per backend environment from the content-addressed
    # store (see tnc_tpu.serve.reuse)
    reuse: Any = None  # ReuseBinding | None
    # device-resident bitstring-invariant leaves, keyed by
    # (dtype, device): staged once, reused by every threaded-jax
    # dispatch — only the (B, n_det, 2) bras transfer per batch
    _resident: dict = field(default_factory=dict, repr=False, compare=False)

    @property
    def result_shape(self) -> tuple[int, ...]:
        return tuple(self.program.result_shape)

    def _serving_arrays(self, backend) -> list[np.ndarray]:
        """The request-invariant input arrays for ``backend``: the bound
        leaf data, or — under cross-request reuse — the residual's
        inputs with cached subtrees materialized (store-first) for this
        backend's numeric environment."""
        if self.reuse is None:
            return self.arrays
        return self.reuse.arrays_for(backend)

    def _batch_buffers(
        self, batch_bits: Sequence[str], arrays: Sequence[np.ndarray]
    ) -> list[np.ndarray]:
        bras = stacked_bras(batch_bits)  # (B, n_det, 2)
        buffers = list(arrays)
        for i, slot in enumerate(self.bra_slots):
            buffers[slot] = np.ascontiguousarray(bras[:, i])
        return buffers

    def amplitudes(
        self,
        bitstrings: Sequence[str | Iterable],
        backend: Backend | None = None,
    ) -> np.ndarray:
        """Amplitudes for a batch of request bitstrings, one dispatch.

        Returns ``(B,) + result_shape`` (open-leg axes in the program's
        result-leg order — scalar amplitudes for fully determined
        templates). On the numpy backend the batched result
        bit-compares to B sequential singleton contractions.
        """
        return self.amplitudes_det(
            [self.template.request_bits(b) for b in bitstrings], backend
        )

    def amplitudes_det(
        self,
        batch_bits: Sequence[str],
        backend: Backend | None = None,
        slice_range: tuple[int, int] | None = None,
        ckpt: str | None = None,
        on_slice=None,
    ) -> np.ndarray:
        """:meth:`amplitudes` over already-validated determined-position
        bit strings (``template.request_bits`` output) — the service
        dispatches these directly so per-request validation runs once,
        at admission, not again on the batching hot path.

        ``slice_range=(lo, hi)`` (sliced structures only): each
        request's amplitude is the **partial sum** over that contiguous
        slice shard — the multi-host serving shape, where every host
        covers a range and the root adds the range partials in range
        order (:mod:`tnc_tpu.serve.multihost`).

        ``ckpt`` / ``on_slice`` (sliced structures, backends with
        ``supports_slice_hooks``): slice-boundary checkpointing and
        cooperative preemption for the elastic serving layer
        (:mod:`tnc_tpu.serve.elastic`) — a killed or preempted slice
        loop resumes bit-identically from its persisted cursor. Silently
        dropped on backends without the hooks (the run is then simply
        not resumable)."""
        if backend is None:
            backend = NumpyBackend()
        if slice_range is not None and self.sliced is None:
            raise ValueError(
                "slice_range only applies to sliced structures "
                "(this bound program has no slicing)"
            )
        if not getattr(backend, "supports_slice_hooks", False):
            ckpt = None
            on_slice = None
        if not batch_bits:
            return np.zeros((0,) + self.result_shape, dtype=np.complex128)
        arrays = self._serving_arrays(backend)
        if not self.bra_slots:
            # fully-open template: every request is the same statevector
            if self.sliced is not None:
                # the slice loop (not the flat program) is the
                # executable for a sliced structure — and a range shard
                # must return the range PARTIAL, never the full sum
                # (the root adds one partial per host)
                kw = {} if slice_range is None else {"slice_range": slice_range}
                if ckpt is not None:
                    kw["ckpt"] = ckpt
                if on_slice is not None:
                    kw["on_slice"] = on_slice
                out = np.asarray(
                    backend.execute_sliced(self.sliced, list(arrays), **kw)
                )
            else:
                out = np.asarray(
                    backend.execute(self.program, list(arrays))
                )
            return np.broadcast_to(out, (len(batch_bits),) + out.shape).copy()
        buffers = self._batch_buffers(batch_bits, arrays)
        b = len(batch_bits)

        if self.sliced is not None:
            # sliced structures: one slice-loop execution per request
            # (stacked dispatch — the batch leg would multiply the
            # already-HBM-bound per-slice peak)
            obs.counter_add("serve.rebind.dispatch", mode="sliced")
            # kwarg only when actually sharding: a backend subclass
            # predating slice_range keeps serving whole-range requests
            kw = {} if slice_range is None else {"slice_range": slice_range}
            if ckpt is not None:
                kw["ckpt"] = ckpt
            if on_slice is not None:
                kw["on_slice"] = on_slice
            return stacked_rows(
                lambda per: backend.execute_sliced(self.sliced, per, **kw),
                buffers, self.bra_slots, b, self.result_shape,
            )

        if isinstance(backend, NumpyBackend):
            obs.counter_add(
                "serve.rebind.dispatch",
                mode="threaded" if self.threadable else "loop",
            )
            out = backend.execute_batched(self.program, buffers, self.bra_slots)
            return out.reshape((b,) + self.result_shape)

        if isinstance(backend, JaxBackend):
            if self.threadable and not backend.split_complex:
                from tnc_tpu.ops.backends import place_buffers

                obs.counter_add("serve.rebind.dispatch", mode="threaded")
                # bucket the batch axis to the next power of two (pad
                # with copies of the last request, sliced off below):
                # XLA compiles one executable per shape, and service
                # traffic otherwise produces a fresh trace per distinct
                # batch size
                padded = pow2_bucket(b)
                if padded != b:
                    obs.counter_add("serve.rebind.batch_padded")
                    for slot in self.bra_slots:
                        fill = np.broadcast_to(
                            buffers[slot][-1], (padded - b, 2)
                        )
                        buffers[slot] = np.concatenate(
                            [buffers[slot], fill]
                        )
                fn = _jit_threaded(self.program, self.batch_flags)
                # gate leaves are bitstring-invariant: stage them to the
                # device ONCE and reuse across dispatches (the jitted fn
                # never donates); only the bras transfer per batch
                res_key = (str(backend.dtype), backend.device)
                resident = self._resident.get(res_key)
                if resident is None:
                    bra_set = set(self.bra_slots)
                    resident = {
                        s: buf
                        for s, buf in enumerate(
                            place_buffers(
                                arrays, backend.dtype, False,
                                backend.device,
                            )
                        )
                        if s not in bra_set
                    }
                    self._resident[res_key] = resident
                bra_dev = place_buffers(
                    [buffers[s] for s in self.bra_slots],
                    backend.dtype, False, backend.device,
                )
                bra_of = dict(zip(self.bra_slots, bra_dev))
                dev = [
                    bra_of[s] if s in bra_of else resident[s]
                    for s in range(len(buffers))
                ]
                out = np.asarray(fn(dev))[:b]
                return out.reshape((b,) + self.result_shape)
            obs.counter_add("serve.rebind.dispatch", mode="vmap")
            out = backend.execute_batched(
                self.program, buffers, self.bra_slots
            )
            return np.asarray(out).reshape((b,) + self.result_shape)

        # unknown backend: stacked dispatch (same results, B dispatches)
        obs.counter_add("serve.rebind.dispatch", mode="loop")
        return stacked_rows(
            lambda per: backend.execute(self.program, per),
            buffers, self.bra_slots, b, self.result_shape,
        )


def plan_signature(bound: BoundProgram) -> str:
    """The *plan* identity of a bound structure: the pre-split program's
    signature digest. Under cross-request reuse ``bound.program`` is the
    residual — whose signature depends on the store split, not just the
    plan — so replanner/watcher identity checks go through here.

    >>> # cold bindings: identical to program.signature_digest()
    """
    if bound.reuse is not None:
        return bound.reuse.cold_signature
    return bound.program.signature_digest()


def plan_structure(
    tn, pathfinder=None, target_size: float | None = None, cost_model=None
):
    """Plan one amplitude structure: find a path, slice to the budget
    when needed, compile. Returns ``(path, slicing, program,
    sliced_program, result)`` — the shared planning step behind
    :func:`bind_template`'s cache-miss branch and the background
    replanner (:mod:`tnc_tpu.serve.replan`), so both produce plans with
    identical semantics and cache records.

    A slicing-aware pathfinder (the Hyperoptimizer's joint mode)
    exposes its winning slice set as ``last_slicing``; the budget
    repair here is then *seeded* with it — a thin validation pass over
    the plan the search already priced, not a fresh post-pass slicing
    search. ``cost_model`` keeps the repair's leg scoring in the same
    predicted-seconds domain as a calibrated replanner."""
    from tnc_tpu.contractionpath.contraction_path import ContractionPath

    if pathfinder is None:
        from tnc_tpu.contractionpath.paths import Greedy, OptMethod

        pathfinder = Greedy(OptMethod.GREEDY)
    result = pathfinder.find_path(tn)
    slicing = None
    if target_size is not None and result.size > target_size:
        from tnc_tpu.contractionpath.slicing import slice_and_reconfigure

        seed = getattr(pathfinder, "last_slicing", None)
        replace_pairs, slicing = slice_and_reconfigure(
            list(tn.tensors), result.ssa_path.toplevel, target_size,
            cost_model=cost_model,
            seed_slices=seed.legs if seed is not None else None,
        )
        if slicing.num_slices <= 1:
            slicing = None
        path = ContractionPath.simple(list(replace_pairs))
    else:
        path = result.replace_path()
    program = build_program(tn, path)
    sliced = (
        build_sliced_program(tn, path, slicing)
        if slicing is not None
        else None
    )
    return path, slicing, program, sliced, result


def bind_template(
    template: AmplitudeTemplate,
    pathfinder=None,
    plan_cache=None,
    target_size: float | None = None,
    reuse_store=None,
) -> BoundProgram:
    """Plan (or load a cached plan for) ``template`` and compile it into
    a :class:`BoundProgram`.

    With a :class:`~tnc_tpu.serve.plancache.PlanCache`, a repeat
    structure loads its path from disk and performs **zero pathfinding**
    (no ``plan.find_path`` span) — and since the rebuilt program's
    signature is unchanged, a warm process-level jit cache also skips
    compilation.

    ``target_size``: peak-intermediate budget (elements). When the
    planned path exceeds it, the structure is sliced
    (``slice_and_reconfigure``) and the slicing + hoist split persist
    in the plan record; serving then runs the slice loop per request.

    ``reuse_store``: an :class:`~tnc_tpu.serve.reuse.IntermediateStore`
    — the bound program is split into content-addressed cached
    subtrees plus a per-request residual; value-identical subtrees
    (shared circuit prefixes across an angle sweep) are contracted
    once store-wide and reloaded by every later binding. Results stay
    bit-identical to the cold path.
    """
    from tnc_tpu.contractionpath.contraction_path import ContractionPath

    tn = template.network
    leaves = flat_leaf_tensors(tn)
    n_det = len(template.determined)
    bra_slots = tuple(range(len(leaves) - n_det, len(leaves)))

    plan: dict = {}
    key = None
    pairs = None
    slicing = None
    if plan_cache is not None:
        # the budget is part of the key: a plan cached without (or with a
        # different) target_size must not answer this lookup
        key = plan_cache.key_for_network(tn, target_size)
        plan = plan_cache.load(key) or {}
        pairs = plan.get("pairs")
    if pairs is None:
        path, slicing, program, sliced, result = plan_structure(
            tn, pathfinder, target_size
        )
        if plan_cache is not None:
            plan = plan_cache.record_for(
                path,
                program,
                slicing=slicing,
                sliced_program=sliced,
                flops=result.flops,
                peak=result.size,
                finder=(
                    type(pathfinder).__name__
                    if pathfinder is not None
                    else "Greedy"
                ),
                target_size=target_size,
            )
            plan_cache.store(key, plan)
    else:
        try:
            path = ContractionPath.from_obj(pairs)
            slicing = plan_cache.plan_slicing(plan)
            program = build_program(tn, path)
            valid = plan_cache.validate(plan, program)
            sliced = (
                build_sliced_program(tn, path, slicing)
                if valid and slicing is not None and slicing.num_slices > 1
                else None
            )
            if sliced is not None and plan.get("sliced_sig") not in (
                None, sliced.signature_digest()
            ):
                # the sliced compilation drifted from what the plan was
                # stored with (slicer/compiler version change)
                valid = False
        except Exception as exc:  # noqa: BLE001 — any bad entry → replan
            # valid JSON but semantically corrupt (out-of-range pairs,
            # planner drift): the cache contract is degrade-to-replan,
            # never raise — and never leave the poison pill on disk
            logger.warning(
                "cached plan %s does not rebuild (%s: %s); replanning",
                key, type(exc).__name__, exc,
            )
            valid = False
        if not valid:
            plan_cache.invalidate(key)
            return bind_template(
                template, pathfinder, plan_cache, target_size, reuse_store
            )

    arrays = [leaf.data.into_data() for leaf in leaves]
    reuse = None
    if reuse_store is not None and bra_slots:
        from tnc_tpu.serve.reuse import ReuseBinding, compute_split

        split = compute_split(program, arrays, bra_slots, sliced=sliced)
        if split is not None:
            reuse = ReuseBinding(
                split, reuse_store, arrays, program.signature_digest()
            )
            program = split.residual
            sliced = split.residual_sliced
            bra_slots = split.bra_slots
            arrays = split.placeholder_arrays(reuse.base_arrays)
    flags, threadable = thread_batch(program, bra_slots)
    return BoundProgram(
        template=template,
        program=program,
        arrays=arrays,
        bra_slots=bra_slots,
        batch_flags=flags,
        threadable=threadable,
        plan=plan,
        sliced=sliced,
        target_size=target_size,
        reuse=reuse,
    )


def bind_circuit(
    circuit,
    mask: str | Iterable | None = None,
    pathfinder=None,
    plan_cache=None,
    target_size: float | None = None,
    reuse_store=None,
) -> BoundProgram:
    """``into_amplitude_template`` + :func:`bind_template` in one call
    (consumes ``circuit``, finalizer semantics)."""
    return bind_template(
        circuit.into_amplitude_template(mask), pathfinder, plan_cache,
        target_size, reuse_store,
    )
