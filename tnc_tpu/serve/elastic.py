"""Elastic preemptible fleet: membership, reassignment, preemption, scaling.

This module is the coordination brain behind four serving behaviours that
the static cluster path (``serve/multihost.py``) cannot express on its own:

- **Live membership** — ``live_processes`` folds a ``FleetRegistry``
  roster into the set of process indices that are currently beating, and
  ``assign_ranges`` shards a round's work across exactly those members.
  The dispatcher consults both *per collective round*, so a worker that
  joins or leaves between rounds changes the next round's shard map
  without any restart.

- **Mid-request reassignment** — when a worker dies *inside* a round the
  root's bounded gather yields a ``GatherLost`` sentinel; the root then
  re-runs the lost slice range locally, resuming from the dead worker's
  ``SliceCheckpoint`` on shared storage so the recomputed partial is
  bit-identical to what the worker would have produced (the checkpoint
  restores the accumulator bitwise and the remaining slices replay in
  the same order).  Counted under ``serve.elastic.reassigned``.

- **Priority preemption** — long sliced contractions run through
  ``preemptible_amplitudes``: an ``on_slice`` gate asks "is someone more
  important waiting?" at every slice-range checkpoint boundary; a True
  answer forces a checkpoint save and raises ``SliceYield``, the waiting
  priority work runs in the interlude, and the preempted contraction
  resumes from its checkpoint — bit-identical to the never-preempted
  golden because the accumulator round-trips bitwise.

- **Scaling signals** — ``ElasticController`` folds queue depth, SLO
  burn rate and roster size into scale-up / scale-down decisions with a
  cooldown, surfaced both as advisory hooks (for external autoscalers)
  and through ``LocalAutoscaler``, a subprocess-backed actuator that
  spawns / retires heartbeat workers (``python -m tnc_tpu.serve.elastic
  --worker``) against the same registry directory.

Everything here is plain-Python and importable without jax: the module
is deliberately free of transport imports so ``multihost.py`` can lazily
reach ``count_event`` / ``live_processes`` / ``assign_ranges`` without a
cycle, and so the scheduler math is unit-testable in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Mapping, Sequence

from tnc_tpu import obs

__all__ = [
    "count_event",
    "counters",
    "reset_counters",
    "live_processes",
    "assign_ranges",
    "weighted_fair_order",
    "ElasticConfig",
    "ElasticController",
    "LocalAutoscaler",
    "preemptible_amplitudes",
    "PreemptionExhaustedError",
]


# ---------------------------------------------------------------------------
# cross-layer event counters
# ---------------------------------------------------------------------------
#
# multihost.py (reassignment) and service.py (preemption) both tally here
# so ``stats()["elastic"]`` has one coherent ledger regardless of which
# layer observed the event.  The obs registry gets the same increments
# (``serve.elastic.*``) for Prometheus; this dict exists because obs can
# be globally disabled while stats() must still count.

_COUNTS: dict[str, int] = {}
_COUNTS_LOCK = threading.Lock()


def count_event(name: str, n: int = 1) -> None:
    """Tally an elastic event (``reassigned``, ``preempted``, ...)."""
    with _COUNTS_LOCK:
        _COUNTS[name] = _COUNTS.get(name, 0) + int(n)


def counters() -> dict[str, int]:
    """Snapshot of the cumulative elastic event tallies."""
    with _COUNTS_LOCK:
        return dict(_COUNTS)


def reset_counters() -> None:
    """Zero the tallies (test isolation)."""
    with _COUNTS_LOCK:
        _COUNTS.clear()


# ---------------------------------------------------------------------------
# live membership
# ---------------------------------------------------------------------------


def live_processes(
    registry,
    n: int,
    root: int = 0,
    stale_after_s: float | None = None,
) -> set[int]:
    """Process indices currently alive according to ``registry``.

    Heartbeat payloads published by ``serve_cluster`` / the worker entry
    carry ``"process": <index>``; a row counts as live when its state is
    ``"live"`` (optionally re-judged against a caller-supplied
    ``stale_after_s`` tighter/looser than the registry default).  The
    root is always a member — it is the process doing the asking, and a
    roster that has lost the root's own entry (slow shared volume) must
    not zero out the whole fleet.  Rows without a usable process index
    or out of ``[0, n)`` are ignored.
    """
    live = {int(root)}
    try:
        roster = registry.roster()
    except Exception:
        obs.counter_add("serve.elastic.roster_errors")
        return live
    for row in roster.get("replicas", ()):
        payload = row.get("payload") or {}
        proc = payload.get("process")
        if proc is None:
            continue
        try:
            proc = int(proc)
        except (TypeError, ValueError):
            continue
        if not (0 <= proc < int(n)):
            continue
        if stale_after_s is not None:
            alive = float(row.get("age_s", 0.0)) <= float(stale_after_s)
        else:
            alive = row.get("state") == "live"
        if alive:
            live.add(proc)
    return live


def assign_ranges(
    n_items: int,
    live: set[int] | Sequence[int],
    n: int,
) -> list[tuple[int, int]]:
    """Shard ``[0, n_items)`` across the live members of an ``n``-process
    cluster.  Returns a length-``n`` list of ``(lo, hi)`` per process
    slot; dead slots get ``(0, 0)`` and live slots receive contiguous
    ascending ranges in process order, so the root's in-order
    concatenation of partials is independent of *which* processes are
    alive.  With no live member (degenerate roster) everything lands on
    process 0.

    >>> assign_ranges(10, {0, 2}, 3)  # slot 1 is dead
    [(0, 5), (0, 0), (5, 10)]
    >>> assign_ranges(10, set(), 3)  # degenerate roster -> root
    [(0, 10), (0, 0), (0, 0)]
    """
    from tnc_tpu.serve.multihost import shard_ranges

    n = max(int(n), 1)
    members = sorted({int(p) for p in live if 0 <= int(p) < n})
    if not members:
        members = [0]
    parts = shard_ranges(n_items, len(members))
    out: list[tuple[int, int]] = [(0, 0)] * n
    for slot, rng in zip(members, parts):
        out[slot] = rng
    return out


# ---------------------------------------------------------------------------
# weighted-fair scheduling
# ---------------------------------------------------------------------------


def weighted_fair_order(
    items: Sequence,
    tenant_of: Callable[[object], str],
    priority_of: Callable[[object], int],
    weights: Mapping[str, float] | None = None,
    default_weight: float = 1.0,
) -> list[int]:
    """Indices of ``items`` in dispatch order: priority classes first
    (higher wins), then weighted-fair interleave across tenants within a
    class, FIFO within each tenant.

    Fairness is stride scheduling: the k-th request of a tenant with
    weight ``w`` gets virtual finish time ``k / w``, and requests are
    served in ascending virtual time — a weight-2 tenant gets two slots
    for every one of a weight-1 tenant, regardless of who queued first.
    Arrival order (the index itself) breaks exact ties so the order is
    total and deterministic.
    """
    weights = weights or {}
    strides: dict[str, float] = {}
    keyed = []
    for i, item in enumerate(items):
        tenant = tenant_of(item)
        w = float(weights.get(tenant, default_weight))
        if w <= 0.0:
            w = default_weight if default_weight > 0 else 1.0
        vft = strides.get(tenant, 0.0) + 1.0 / w
        strides[tenant] = vft
        keyed.append((-int(priority_of(item)), vft, i))
    keyed.sort()
    return [i for (_, _, i) in keyed]


# ---------------------------------------------------------------------------
# preemptible execution
# ---------------------------------------------------------------------------


class PreemptionExhaustedError(RuntimeError):
    """A preemptible contraction yielded more times than the configured
    bound — the priority lane is starving it, which is a scheduling bug,
    not a reason to spin forever."""


def preemptible_amplitudes(
    bound,
    bits,
    backend=None,
    *,
    ckpt,
    should_yield: Callable[[int], bool],
    interlude: Callable[[], None] | None = None,
    max_yields: int = 1000,
):
    """Run ``bound.amplitudes_det(bits)`` so it can yield at slice-range
    checkpoint boundaries and resume bit-identically.

    ``should_yield(cursor)`` is consulted after every completed slice
    (except the last — finishing beats yielding); returning True forces
    a checkpoint save and raises ``SliceYield`` out of the executor,
    after which ``interlude()`` runs (the priority work) and the
    contraction restarts — the checkpoint restores the accumulator
    bitwise, so the final rows equal the never-preempted golden.  Yields
    are tallied under ``serve.elastic.preempted``.
    """
    from tnc_tpu.ops.sliced import SliceYield

    yields = 0
    while True:
        try:
            return bound.amplitudes_det(
                bits, backend, ckpt=ckpt, on_slice=should_yield
            )
        except SliceYield as y:
            yields += 1
            count_event("preempted")
            obs.counter_add("serve.elastic.preempted")
            if yields >= int(max_yields):
                raise PreemptionExhaustedError(
                    f"sliced contraction preempted {yields} times without "
                    f"completing (cursor {y.cursor})"
                ) from y
            if interlude is not None:
                interlude()


# ---------------------------------------------------------------------------
# scaling controller
# ---------------------------------------------------------------------------


@dataclass
class ElasticConfig:
    """Knobs for the elastic serving path (``ContractionService``
    consumes this via ``enable_elastic``)."""

    # shared directory for slice-range checkpoints (reassignment +
    # preemption resume); None disables both resume paths
    ckpt_dir: str | None = None
    # tenant -> weighted-fair weight (unlisted tenants get 1.0)
    tenant_weights: dict[str, float] = field(default_factory=dict)
    # tenant -> max queued requests (unlisted tenants are uncapped)
    tenant_quotas: dict[str, int] = field(default_factory=dict)
    # priority strictly greater than a running batch's preempts it
    preempt_enabled: bool = True
    # safety bound on yields per contraction
    max_yields: int = 1000


class ElasticController:
    """Advisory scale controller: folds queue depth, SLO burn rate and
    roster size into ``scale_up`` / ``scale_down`` / ``hold`` decisions.

    Pure signal→decision math with an injectable clock; actuation is
    someone else's job (``LocalAutoscaler`` locally, or external
    infrastructure through the ``on_decision`` hooks).  A cooldown
    separates consecutive non-hold decisions so a noisy queue cannot
    flap the fleet.
    """

    def __init__(
        self,
        min_replicas: int = 1,
        max_replicas: int = 8,
        scale_up_depth: int = 8,
        scale_down_depth: int = 0,
        burn_threshold: float = 2.0,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_depth = int(scale_up_depth)
        self.scale_down_depth = int(scale_down_depth)
        self.burn_threshold = float(burn_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._last_action_t: float | None = None
        self._lock = threading.Lock()
        self.last_decision: dict = {"action": "hold", "reason": "init"}
        self.on_decision: list[Callable[[dict], None]] = []

    @staticmethod
    def burn_from_slo(slo_stats: Mapping | None) -> float:
        """Worst long-window burn rate across objectives in an
        ``SLOEngine.stats()`` snapshot (0.0 when absent/malformed)."""
        worst = 0.0
        if not isinstance(slo_stats, Mapping):
            return worst
        for row in slo_stats.get("objectives", ()) or ():
            for w in row.get("windows", ()) or ():
                try:
                    worst = max(worst, float(w.get("burn_long", 0.0)))
                except (TypeError, ValueError):
                    continue
        return worst

    def decide(
        self,
        queue_depth: int,
        live_replicas: int,
        burn: float = 0.0,
        t: float | None = None,
    ) -> dict:
        """One control step.  Scale-up wins when the queue is deep *or*
        the SLO budget is burning fast (capacity is the only lever this
        controller has); scale-down needs the queue drained *and* burn
        quiet.  The returned dict is also stored as ``last_decision``
        and fanned to the advisory hooks."""
        now = self._clock() if t is None else float(t)
        depth = int(queue_depth)
        live = max(int(live_replicas), 0)
        action, reason = "hold", "steady"
        target = live
        if depth >= self.scale_up_depth or burn >= self.burn_threshold:
            if live < self.max_replicas:
                action = "scale_up"
                target = min(live + 1, self.max_replicas)
                reason = (
                    f"queue_depth={depth}" if depth >= self.scale_up_depth
                    else f"burn={burn:.2f}"
                )
            else:
                reason = "at_max"
        elif depth <= self.scale_down_depth and burn < 1.0:
            if live > self.min_replicas:
                action = "scale_down"
                target = max(live - 1, self.min_replicas)
                reason = "idle"
            else:
                reason = "at_min"
        with self._lock:
            if action != "hold" and self._last_action_t is not None:
                if now - self._last_action_t < self.cooldown_s:
                    action, reason = "hold", "cooldown"
                    target = live
            if action != "hold":
                self._last_action_t = now
            decision = {
                "action": action,
                "target": int(target),
                "live": live,
                "queue_depth": depth,
                "burn": round(float(burn), 4),
                "reason": reason,
            }
            self.last_decision = decision
        obs.gauge_set("serve.elastic.scale_target", float(target))
        if action != "hold":
            obs.counter_add("serve.elastic.decisions", action=action)
            count_event(action)
        for hook in list(self.on_decision):
            try:
                hook(dict(decision))
            except Exception:
                obs.counter_add("serve.elastic.hook_errors")
        return decision


# ---------------------------------------------------------------------------
# local autoscaler (subprocess-backed actuator)
# ---------------------------------------------------------------------------


class LocalAutoscaler:
    """Actuates controller decisions by spawning / retiring local
    heartbeat worker subprocesses (``python -m tnc_tpu.serve.elastic
    --worker``) against a shared registry directory.

    This is the single-box stand-in for a real preemptible capacity
    pool: the subprocess boundary makes join / leave / SIGKILL
    observable through exactly the same heartbeat files a multi-host
    fleet would use, so membership tests exercise the production code
    path.  Workers are indexed ``base_process + k``; ``scale_to``
    reconciles the desired count against the live children.
    """

    def __init__(
        self,
        fleet_dir: str,
        base_process: int = 1,
        interval_s: float = 0.5,
        python: str | None = None,
    ):
        self.fleet_dir = str(fleet_dir)
        self.base_process = int(base_process)
        self.interval_s = float(interval_s)
        self.python = python or sys.executable
        self._procs: dict[int, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def _spawn(self, index: int) -> subprocess.Popen:
        cmd = [
            self.python, "-m", "tnc_tpu.serve.elastic", "--worker",
            "--fleet-dir", self.fleet_dir,
            "--process", str(index),
            "--interval", str(self.interval_s),
        ]
        return subprocess.Popen(
            cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
        )

    def _reap(self) -> None:
        dead = [i for i, p in self._procs.items() if p.poll() is not None]
        for i in dead:
            del self._procs[i]

    def count(self) -> int:
        with self._lock:
            self._reap()
            return len(self._procs)

    def scale_to(self, n_workers: int) -> int:
        """Reconcile to ``n_workers`` live children; returns the actual
        count after reconciliation."""
        n_workers = max(int(n_workers), 0)
        with self._lock:
            self._reap()
            while len(self._procs) < n_workers:
                nxt = self.base_process
                while nxt in self._procs:
                    nxt += 1
                self._procs[nxt] = self._spawn(nxt)
                obs.counter_add("serve.elastic.workers_spawned")
            while len(self._procs) > n_workers:
                idx = max(self._procs)
                self._terminate(self._procs.pop(idx))
                obs.counter_add("serve.elastic.workers_retired")
            return len(self._procs)

    def apply(self, decision: Mapping) -> int:
        """Actuate a controller decision dict (``scale_up`` adds one
        worker, ``scale_down`` removes one, anything else reconciles to
        the current count)."""
        with self._lock:
            self._reap()
            have = len(self._procs)
        action = decision.get("action")
        if action == "scale_up":
            return self.scale_to(have + 1)
        if action == "scale_down":
            return self.scale_to(max(have - 1, 0))
        return self.scale_to(have)

    @staticmethod
    def _terminate(proc: subprocess.Popen, grace_s: float = 3.0) -> None:
        if proc.poll() is not None:
            return
        try:
            proc.terminate()
            proc.wait(timeout=grace_s)
        except Exception:
            try:
                proc.kill()
                proc.wait(timeout=grace_s)
            except Exception:
                pass

    def stop(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            self._terminate(p)

    def __enter__(self) -> "LocalAutoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# worker entry point
# ---------------------------------------------------------------------------


def _worker_main(args: argparse.Namespace) -> int:
    """Heartbeat worker: joins the registry under a process index and
    beats until terminated.  SIGTERM retires the entry (clean leave);
    SIGKILL leaves it to go stale (crash) — which is exactly the
    distinction membership tests need to observe."""
    from tnc_tpu.obs.fleet import FleetRegistry

    name = args.name or f"elastic-w{args.process}"
    registry = FleetRegistry(args.fleet_dir, name=name)
    payload = {"process": int(args.process), "role": "elastic-worker",
               "pid": os.getpid()}
    stop = threading.Event()

    def _on_term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    registry.heartbeat(payload)
    sys.stdout.write(json.dumps({"joined": name,
                                 "process": int(args.process)}) + "\n")
    sys.stdout.flush()
    try:
        while not stop.wait(float(args.interval)):
            registry.heartbeat(payload)
    except KeyboardInterrupt:
        pass
    registry.retire()
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tnc_tpu.serve.elastic",
        description="Elastic fleet utilities (heartbeat worker entry).",
    )
    parser.add_argument("--worker", action="store_true",
                        help="run as a heartbeat worker until SIGTERM")
    parser.add_argument("--fleet-dir", default=None,
                        help="FleetRegistry directory (required for --worker)")
    parser.add_argument("--process", type=int, default=1,
                        help="process index published in the heartbeat")
    parser.add_argument("--name", default=None,
                        help="replica name (default elastic-w<process>)")
    parser.add_argument("--interval", type=float, default=0.5,
                        help="heartbeat interval seconds")
    args = parser.parse_args(argv)
    if args.worker:
        if not args.fleet_dir:
            parser.error("--worker requires --fleet-dir")
        return _worker_main(args)
    parser.error("nothing to do (pass --worker)")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
