"""Cross-request numeric reuse: content-addressed intermediate tensors.

The plan cache (:mod:`tnc_tpu.serve.plancache`) reuses *structure*
across requests and :mod:`tnc_tpu.ops.hoist` reuses slice-invariant
values *within* one request. This module closes the remaining gap —
fleet traffic is dominated by near-duplicates (one ansatz, many angle
settings; a circuit at growing depth) whose contraction trees share
whole value-identical subtrees, yet every request re-contracts them.

Three layers, bottom up:

- **Subtree digests** (:func:`compute_split`): every contraction-tree
  node gets a value-aware digest over (step shape record, operand
  digests), grounded in leaf digests over (shape, dtype, bytes). Slot
  ids are *excluded*, so two plans that contract the same values
  through the same shapes produce the same key regardless of slot
  layout — the EinExprs view (arXiv:2403.18030) of a subtree as a
  symbolic expression, keyed here by content instead of by name.
- **Prefix/residual split** (:class:`ReuseSplit`): the marking pass of
  :func:`tnc_tpu.ops.hoist.hoist_sliced_program` run with "volatile"
  (bra leaves, sliced leaves) in place of "variant". Volatile steps
  become the per-request residual (fresh slot space, hoist's exact
  remap); every non-volatile value is addressable in the store. The
  residual's cached inputs are materialized once per backend
  environment and reused by every request — and, via the store, by
  every *other* request whose tree contains the same value.
- **The store** (:class:`IntermediateStore`): byte-budgeted LRU memory
  tier over an optional host-disk npz tier with the plan cache's
  atomic-replace discipline (unique tmp names, digest validated on
  load, corrupt entries deleted and counted — degrade to recontract,
  never raise). Admission is cost-model-priced: a subtree is stored
  only when recontracting it costs more than loading it back
  (:meth:`IntermediateStore.admit`).

Bitwise contract: a materialized node program has
``result_shape == out_store`` of its final step and canonical legs, so
``backend.execute`` returns exactly the stored intermediate buffer the
cold path would have produced at that tree position; the residual's
consuming steps reshape from the same stored layout. Reused amplitudes
therefore bit-compare to cold-contracted ones on the numpy, jax
threaded and sliced paths (pinned by ``tests/test_reuse.py`` and
``scripts/reuse_smoke.py``); split-complex — which XLA re-fuses
across the extra jit boundary — agrees to float32 tolerance only
(docs/serving.md "Computation reuse").

>>> import numpy as np
>>> store = IntermediateStore(max_bytes=1 << 16)
>>> store.put("node", np.ones(2, dtype=np.complex128))
>>> store.get("node")
array([1.+0.j, 1.+0.j])
>>> store.get("absent") is None
True
>>> [store.stats()[k] for k in ("hit", "miss", "store")]
[1, 1, 1]
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, replace
from pathlib import Path
from typing import Any, Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.ops.program import (
    ContractionProgram,
    PairStep,
    step_flops,
    steps_bytes,
    steps_flops,
)
from tnc_tpu.utils.digest import stable_digest

logger = logging.getLogger(__name__)

# Bump to invalidate every digest/spill entry (step-record or spill
# format change).
REUSE_VERSION = 1

_SPILL_SUFFIX = ".npz"
_TMP_ORPHAN_S = 3600.0


def leaf_digest(arr: np.ndarray) -> str:
    """Value digest of a leaf buffer: shape, dtype and raw bytes."""
    a = np.ascontiguousarray(arr)
    return stable_digest(
        "reuse-leaf-v%d" % REUSE_VERSION,
        tuple(int(d) for d in a.shape),
        str(a.dtype),
        a.tobytes(),
    )


def _step_record(st: PairStep) -> tuple:
    """The slot-id-free shape record of a step — everything an executor
    uses except *which* slots the operands live in."""
    return (
        st.a_view, st.a_perm, st.a_dot, st.a_cfirst,
        st.b_view, st.b_perm, st.b_dot, st.b_cfirst,
        st.swap, st.out_store, st.a_ops, st.b_ops,
    )


def step_digest(st: PairStep, lhs_digest: str, rhs_digest: str) -> str:
    """Value digest of a step node from its operands' value digests."""
    return stable_digest(
        "reuse-step-v%d" % REUSE_VERSION,
        _step_record(st),
        lhs_digest,
        rhs_digest,
    )


def backend_env_key(backend: Any) -> tuple:
    """Numeric-environment discriminator for store keys: two
    environments share an entry only when their executors produce
    bitwise-identical intermediates."""
    if backend is None:
        return ("numpy", "complex128")
    name = getattr(backend, "name", type(backend).__name__)
    key: tuple = (str(name), str(getattr(backend, "dtype", "")))
    if name == "jax":
        key += (
            bool(getattr(backend, "split_complex", False)),
            str(getattr(backend, "precision", "")),
            str(getattr(backend, "device", None)),
        )
    return key


def store_key(env: tuple, node_digest: str) -> str:
    """On-disk / in-memory key of one node value in one environment."""
    return stable_digest("reuse-entry-v%d" % REUSE_VERSION, env, node_digest)


class IntermediateStore:
    """Content-addressed store of materialized contraction subtrees.

    Memory tier: ``OrderedDict`` LRU bounded by ``max_bytes`` (hits
    refresh recency, so a hot shared prefix survives a stream of
    one-use suffix values). Disk tier (optional ``directory``):
    write-through npz spill with the plan cache's atomic discipline —
    unique tmp name + fsync + ``os.replace`` so concurrent writers
    never tear an entry, payload digest validated on load so corrupt
    or stale files become a counted miss (file deleted), never an
    exception.

    Admission (:meth:`admit`): with a
    :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel`, store a
    subtree only when recontraction is priced above ``store_margin``
    times the cost of loading its output back; without one, a plain
    ``min_flops`` floor.
    """

    COUNT_KEYS = (
        "hit", "miss", "store", "evicted", "corrupt", "store_failed",
    )

    def __init__(
        self,
        directory: str | os.PathLike | None = None,
        max_bytes: int = 256 * 1024 * 1024,
        max_disk_bytes: int | None = None,
        cost_model: Any = None,
        store_margin: float = 2.0,
        min_flops: float = 0.0,
    ):
        self.directory = Path(directory) if directory is not None else None
        if self.directory is not None:
            self.directory.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self.max_disk_bytes = (
            int(max_disk_bytes) if max_disk_bytes is not None else None
        )
        self.cost_model = cost_model
        self.store_margin = float(store_margin)
        self.min_flops = float(min_flops)
        self._mem: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._counts: dict[str, float] = {k: 0 for k in self.COUNT_KEYS}
        self._counts["flops_saved"] = 0.0
        self._counts["flops_computed"] = 0.0
        self._counts["steps_computed"] = 0.0

    # --- accounting -----------------------------------------------------

    def _count(self, key: str, value: float = 1.0, **labels) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + value
        obs.counter_add(f"serve.reuse.{key}", value, **labels)

    def note_computed(self, flops: float, n_steps: int) -> None:
        """Record a cold node materialization (for the bench's pinned
        cost-model A/B: total compute the reuse path actually paid)."""
        with self._lock:
            self._counts["flops_computed"] += float(flops)
            self._counts["steps_computed"] += float(n_steps)

    def bytes_held(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def stats(self) -> dict:
        with self._lock:
            out: dict[str, Any] = {
                k: (int(v) if k in self.COUNT_KEYS else float(v))
                for k, v in self._counts.items()
            }
            out["bytes_held"] = int(self._bytes)
            out["entries"] = len(self._mem)
        out["prefix_flops_saved"] = out.pop("flops_saved")
        return out

    def clear_memory(self) -> None:
        """Drop the memory tier (disk spill untouched) — the restart /
        second-replica shape, used by tests to force disk loads."""
        with self._lock:
            self._mem.clear()
            self._bytes = 0

    # --- admission ------------------------------------------------------

    def admit(
        self,
        flops: float,
        nbytes: float,
        n_steps: int = 1,
        out_nbytes: float = 0.0,
    ) -> bool:
        """Should a subtree of this cost be stored? With a cost model:
        recontraction seconds must exceed ``store_margin`` × the
        seconds to stream its output back. Without: a flop floor."""
        if self.cost_model is not None:
            recontract = self.cost_model.op_seconds(
                float(flops), nbytes=float(nbytes),
                dispatches=float(max(n_steps, 1)),
            )
            reload_s = self.cost_model.op_seconds(
                0.0, nbytes=float(out_nbytes), dispatches=1.0
            )
            return recontract > self.store_margin * reload_s
        return float(flops) >= self.min_flops

    # --- memory + disk tiers --------------------------------------------

    def get(self, key: str, flops: float = 0.0) -> np.ndarray | None:
        """Look up one node value. Returned arrays are shared — callers
        must treat them as immutable (executors only read leaf
        buffers). ``flops`` credits the prefix-flops-saved counter on a
        hit."""
        with self._lock:
            arr = self._mem.get(key)
            if arr is not None:
                self._mem.move_to_end(key)
                self._counts["hit"] += 1
                self._counts["flops_saved"] += float(flops)
        if arr is not None:
            obs.counter_add("serve.reuse.hit", tier="memory")
            return arr
        if self.directory is not None:
            arr = self._load_spill(key)
            if arr is not None:
                with self._lock:
                    self._counts["hit"] += 1
                    self._counts["flops_saved"] += float(flops)
                obs.counter_add("serve.reuse.hit", tier="disk")
                self._insert_mem(key, arr)
                return arr
        self._count("miss")
        return None

    def put(self, key: str, arr: np.ndarray, flops: float = 0.0) -> None:
        a = np.ascontiguousarray(arr)
        self._insert_mem(key, a)
        self._count("store")
        if self.directory is not None:
            self._spill(key, a)
            self._evict_disk()

    def _insert_mem(self, key: str, a: np.ndarray) -> None:
        evicted = 0
        with self._lock:
            if key in self._mem:
                self._mem.move_to_end(key)
            else:
                self._mem[key] = a
                self._bytes += a.nbytes
            while self._bytes > self.max_bytes and self._mem:
                _, old = self._mem.popitem(last=False)
                self._bytes -= old.nbytes
                self._counts["evicted"] += 1
                evicted += 1
        if evicted:
            obs.counter_add("serve.reuse.evicted", float(evicted), tier="memory")

    def _spill_path(self, key: str) -> Path:
        assert self.directory is not None
        return self.directory / f"{key}{_SPILL_SUFFIX}"

    def _spill(self, key: str, a: np.ndarray) -> None:
        target = self._spill_path(key)
        tmp = self.directory / (
            f"{key}.{os.getpid()}.{uuid.uuid4().hex[:8]}{_SPILL_SUFFIX}.tmp"
        )
        try:
            payload = stable_digest(
                "reuse-spill-v%d" % REUSE_VERSION,
                tuple(int(d) for d in a.shape),
                str(a.dtype),
                a.tobytes(),
            )
            with open(tmp, "wb") as fh:
                np.savez(
                    fh,
                    value=a,
                    key=np.array(key),
                    sha=np.array(payload),
                    version=np.array(REUSE_VERSION),
                )
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, target)
        except OSError as exc:
            # spill is best-effort: the memory tier already has the
            # value and recontraction remains correct
            self._count("store_failed")
            logger.warning("reuse spill of %s failed: %s", key, exc)
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass

    def _load_spill(self, key: str) -> np.ndarray | None:
        path = self._spill_path(key)
        if not path.exists():
            return None
        try:
            with np.load(path, allow_pickle=False) as data:
                a = np.ascontiguousarray(data["value"])
                want_key = str(data["key"])
                sha = str(data["sha"])
                version = int(data["version"])
            payload = stable_digest(
                "reuse-spill-v%d" % REUSE_VERSION,
                tuple(int(d) for d in a.shape),
                str(a.dtype),
                a.tobytes(),
            )
            if version != REUSE_VERSION or want_key != key or sha != payload:
                raise ValueError("digest mismatch")
        except Exception as exc:  # noqa: BLE001 — any bad spill → miss
            # corrupt / stale / truncated entry: delete the poison pill,
            # count it, and let the caller recontract
            self._count("corrupt")
            logger.warning(
                "corrupt reuse spill %s (%s: %s); deleting",
                path.name, type(exc).__name__, exc,
            )
            try:
                path.unlink(missing_ok=True)
            except OSError:
                pass
            return None
        return a

    def _evict_disk(self) -> None:
        assert self.directory is not None
        now = time.time()
        entries: list[tuple[float, int, Path]] = []
        total = 0
        try:
            for p in self.directory.iterdir():
                try:
                    st = p.stat()
                except OSError:
                    continue
                if p.name.endswith(".tmp"):
                    # orphaned writer tmp (crashed process): reap old ones
                    if now - st.st_mtime > _TMP_ORPHAN_S:
                        try:
                            p.unlink(missing_ok=True)
                        except OSError:
                            pass
                    continue
                if p.suffix == _SPILL_SUFFIX:
                    entries.append((st.st_mtime, st.st_size, p))
                    total += st.st_size
        except OSError:
            return
        if self.max_disk_bytes is None:
            return
        entries.sort()  # oldest mtime first
        evicted = 0
        for _, size, p in entries:
            if total <= self.max_disk_bytes:
                break
            try:
                p.unlink(missing_ok=True)
            except OSError:
                continue
            total -= size
            evicted += 1
        if evicted:
            with self._lock:
                self._counts["evicted"] += evicted
            obs.counter_add("serve.reuse.evicted", float(evicted), tier="disk")


# ---------------------------------------------------------------------------
# prefix/residual split


@dataclass
class ReuseSplit:
    """The environment-independent split of one bound structure.

    ``steps``/``operands`` describe the original (slice-reduced when
    sliced) program's tree; every non-volatile step index appears in
    ``eval_order`` with a value digest and subtree cost; ``cached_idx``
    are the step indices whose values feed the residual as inputs."""

    residual: ContractionProgram
    residual_sliced: Any  # SlicedProgram | None
    sources: tuple[tuple[str, Any], ...]  # ("leaf", slot) | ("cached", idx)
    bra_slots: tuple[int, ...]  # bra positions in the residual slot space
    steps: tuple[PairStep, ...]
    operands: tuple[tuple[tuple, tuple], ...]
    node_digest: dict[int, str]
    node_flops: dict[int, float]
    node_bytes: dict[int, float]
    node_steps: dict[int, int]
    cached_idx: tuple[int, ...]
    eval_order: tuple[int, ...]  # all non-volatile step indices, ascending
    prefix_flops: float
    residual_flops: float

    def placeholder_arrays(self, base_arrays: Sequence[np.ndarray]) -> list:
        """Residual-slot-space array list with zero placeholders in the
        cached slots — shapes are real (the node's ``out_store``), so
        structural consumers (``thread_batch``, array comparisons) see
        the true layout without any materialization."""
        out: list[np.ndarray] = []
        for kind, ref in self.sources:
            if kind == "leaf":
                out.append(base_arrays[ref])
            else:
                shape = tuple(self.steps[ref].out_store)
                out.append(np.zeros(shape, dtype=np.complex128))
        return out


def compute_split(
    program: ContractionProgram,
    arrays: Sequence[np.ndarray],
    bra_slots: Sequence[int],
    sliced: Any = None,
) -> ReuseSplit | None:
    """Split a bound structure into store-addressable subtrees plus a
    per-request residual, or ``None`` when the split is trivial (no
    steps, nothing volatile, or everything volatile).

    Volatile values are the per-request bra leaves — plus, for sliced
    structures, the sliced leaves (their values change per slice, so
    they can never be cached across requests; the split then runs over
    the slice-reduced program and the residual keeps the slice loop).
    """
    prog = sliced.program if sliced is not None else program
    steps = prog.steps
    n = prog.num_inputs

    vol_leaf = set(int(s) for s in bra_slots)
    if sliced is not None:
        vol_leaf |= {s for s in range(n) if sliced.slot_slices[s]}

    # --- marking pass (hoist's, with volatile in place of variant) ------
    volatile: dict[tuple, bool] = {
        ("leaf", s): s in vol_leaf for s in range(n)
    }
    cur: dict[int, tuple] = {s: ("leaf", s) for s in range(n)}
    operands: list[tuple[tuple, tuple]] = []
    step_vol: list[bool] = []
    for i, st in enumerate(steps):
        va, vb = cur[st.lhs], cur[st.rhs]
        is_vol = volatile[va] or volatile[vb]
        operands.append((va, vb))
        step_vol.append(is_vol)
        out = ("step", i)
        volatile[out] = is_vol
        cur[st.lhs] = out
        cur[st.rhs] = ("dead", i)

    if not steps or all(step_vol) or not any(step_vol):
        return None

    # --- value digests + subtree costs, bottom-up (no recursion) -------
    leafd: dict[int, str] = {}

    def _leaf_d(s: int) -> str:
        d = leafd.get(s)
        if d is None:
            d = leaf_digest(arrays[s])
            leafd[s] = d
        return d

    node_digest: dict[int, str] = {}
    node_flops: dict[int, float] = {}
    node_bytes: dict[int, float] = {}
    node_steps: dict[int, int] = {}

    def _val_cost(v: tuple) -> tuple[float, float, int]:
        if v[0] == "leaf":
            return 0.0, 0.0, 0
        return node_flops[v[1]], node_bytes[v[1]], node_steps[v[1]]

    for i, st in enumerate(steps):
        if step_vol[i]:
            continue
        va, vb = operands[i]
        da = node_digest[va[1]] if va[0] == "step" else _leaf_d(va[1])
        db = node_digest[vb[1]] if vb[0] == "step" else _leaf_d(vb[1])
        node_digest[i] = step_digest(st, da, db)
        fa, ba, sa = _val_cost(va)
        fb, bb, sb = _val_cost(vb)
        node_flops[i] = fa + fb + step_flops(st)
        node_bytes[i] = ba + bb + steps_bytes([st])
        node_steps[i] = sa + sb + 1

    # --- residual: volatile steps on a fresh slot space (hoist remap) --
    res_slot_of: dict[tuple, int] = {}
    sources: list[tuple[str, Any]] = []
    res_slot_slices: list[tuple] = []
    res_steps: list[PairStep] = []

    def res_input(v: tuple) -> int:
        slot = len(sources)
        res_slot_of[v] = slot
        if v[0] == "leaf":
            sources.append(("leaf", v[1]))
            res_slot_slices.append(
                sliced.slot_slices[v[1]] if sliced is not None else ()
            )
        else:  # non-volatile intermediate: materialized from the store
            sources.append(("cached", v[1]))
            res_slot_slices.append(())
        return slot

    for i, st in enumerate(steps):
        if not step_vol[i]:
            continue
        va, vb = operands[i]
        la = res_slot_of.get(va)
        if la is None:
            la = res_input(va)
        lb = res_slot_of.get(vb)
        if lb is None:
            lb = res_input(vb)
        res_steps.append(replace(st, lhs=la, rhs=lb))
        res_slot_of[("step", i)] = la

    final_val = cur[prog.result_slot]
    assert volatile[final_val], "volatile steps exist, so the result is volatile"
    residual = ContractionProgram(
        num_inputs=len(sources),
        steps=tuple(res_steps),
        result_slot=res_slot_of[final_val],
        result_legs=prog.result_legs,
        result_shape=prog.result_shape,
        stored_result_shape=prog.stored_result_shape,
        canonical_legs=prog.canonical_legs,
    )
    residual_sliced = None
    if sliced is not None:
        from tnc_tpu.ops.sliced import SlicedProgram

        residual_sliced = SlicedProgram(
            residual, sliced.slicing, tuple(res_slot_slices)
        )

    cached_idx = tuple(ref for kind, ref in sources if kind == "cached")
    if not cached_idx:
        return None
    new_bra = tuple(res_slot_of[("leaf", s)] for s in bra_slots)
    return ReuseSplit(
        residual=residual,
        residual_sliced=residual_sliced,
        sources=tuple(sources),
        bra_slots=new_bra,
        steps=steps,
        operands=tuple(operands),
        node_digest=node_digest,
        node_flops=node_flops,
        node_bytes=node_bytes,
        node_steps=node_steps,
        cached_idx=cached_idx,
        eval_order=tuple(sorted(node_digest)),
        prefix_flops=sum(node_flops[i] for i in cached_idx),
        residual_flops=steps_flops(res_steps),
    )


def _node_program(
    split: ReuseSplit, idx: int, memo: dict[int, np.ndarray]
) -> tuple[ContractionProgram, tuple[tuple[str, int], ...]]:
    """Standalone program computing node ``idx`` from the boundary of
    leaves and already-materialized node values. ``result_shape`` is
    the node's stored shape with identity canonical legs, so
    ``backend.execute`` returns exactly the intermediate buffer the
    full program would hold at this tree position."""
    region: set[int] = set()
    stack = [idx]
    while stack:
        j = stack.pop()
        if j in region:
            continue
        region.add(j)
        for v in split.operands[j]:
            if v[0] == "step" and v[1] not in memo:
                stack.append(v[1])

    local_of: dict[tuple, int] = {}
    srcs: list[tuple[str, int]] = []
    lsteps: list[PairStep] = []

    def add_input(v: tuple) -> int:
        slot = len(srcs)
        local_of[v] = slot
        srcs.append(("step" if v[0] == "step" else "leaf", v[1]))
        return slot

    for j in sorted(region):
        st = split.steps[j]
        va, vb = split.operands[j]
        la = local_of.get(va)
        if la is None:
            la = add_input(va)
        lb = local_of.get(vb)
        if lb is None:
            lb = add_input(vb)
        lsteps.append(replace(st, lhs=la, rhs=lb))
        local_of[("step", j)] = la

    shape = tuple(split.steps[idx].out_store)
    prog = ContractionProgram(
        num_inputs=len(srcs),
        steps=tuple(lsteps),
        result_slot=local_of[("step", idx)],
        result_legs=tuple(range(len(shape))),
        result_shape=shape,
        stored_result_shape=shape,
        canonical_legs=tuple(range(len(shape))),
    )
    return prog, tuple(srcs)


def materialize(
    split: ReuseSplit,
    store: IntermediateStore,
    arrays: Sequence[np.ndarray],
    backend: Any,
) -> dict[int, np.ndarray]:
    """Resolve every cached residual input for one backend environment.

    Admitted nodes are evaluated bottom-up (store lookup first, one
    ``serve.reuse.materialize`` span per cold compute), so *interior*
    values get snapshotted too — that is what lets a later request
    whose tree shares only a deeper subtree still hit. Non-admitted
    interior nodes fold into their consuming ancestor's program (tree
    paths consume each value exactly once, so nothing is recomputed).
    """
    if backend is None:
        from tnc_tpu.ops.backends import NumpyBackend

        backend = NumpyBackend()
    env = backend_env_key(backend)
    memo: dict[int, np.ndarray] = {}
    needed = set(split.cached_idx)
    for i in split.eval_order:
        flops = split.node_flops[i]
        out_nbytes = float(np.prod(split.steps[i].out_store, dtype=float) * 16)
        admitted = store.admit(
            flops, split.node_bytes[i], split.node_steps[i], out_nbytes
        )
        if not admitted and i not in needed:
            continue
        key = store_key(env, split.node_digest[i])
        arr = store.get(key, flops=flops) if admitted else None
        if arr is None:
            prog, srcs = _node_program(split, i, memo)
            vals = [
                memo[ref] if kind == "step" else arrays[ref]
                for kind, ref in srcs
            ]
            region_flops = steps_flops(prog.steps)
            with obs.span(
                "serve.reuse.materialize",
                node=split.node_digest[i][:16],
                steps=len(prog.steps),
                flops=float(region_flops),
            ):
                arr = np.asarray(backend.execute(prog, vals))
            store.note_computed(region_flops, len(prog.steps))
            if admitted:
                store.put(key, arr, flops=flops)
        memo[i] = arr
    return {i: memo[i] for i in split.cached_idx}


class ReuseBinding:
    """Per-:class:`~tnc_tpu.serve.rebind.BoundProgram` reuse state: the
    split, the shared store, the full (pre-split) leaf arrays, and one
    materialized residual array list per backend environment."""

    def __init__(
        self,
        split: ReuseSplit,
        store: IntermediateStore,
        base_arrays: Sequence[np.ndarray],
        cold_signature: str,
    ):
        self.split = split
        self.store = store
        self.base_arrays = list(base_arrays)
        # the pre-split program's signature digest: replanner identity
        # checks compare plans, not residuals (rebind with a different
        # store state would otherwise look like a different plan)
        self.cold_signature = cold_signature
        self._env_arrays: dict[tuple, list[np.ndarray]] = {}
        self._lock = threading.Lock()

    def arrays_for(self, backend: Any) -> list[np.ndarray]:
        """The residual's input arrays for one backend environment,
        materializing (store-first) on first use."""
        key = backend_env_key(backend)
        with self._lock:
            got = self._env_arrays.get(key)
        if got is not None:
            return got
        values = materialize(self.split, self.store, self.base_arrays, backend)
        out = [
            self.base_arrays[ref] if kind == "leaf" else values[ref]
            for kind, ref in self.split.sources
        ]
        with self._lock:
            return self._env_arrays.setdefault(key, out)
