"""Multi-host sharded serving: one fleet, one queue, N host processes.

The single-host :class:`~tnc_tpu.serve.service.ContractionService`
micro-batches requests into one dispatch. This module spreads that
dispatch across every process of a ``jax.distributed.initialize``
fleet:

- **batched bras shard across hosts** — the root process micro-batches
  as usual, then fans the batch's bitstrings out in contiguous shards
  (:func:`shard_ranges`); every process answers its shard with its own
  locally compiled :class:`~tnc_tpu.serve.rebind.BoundProgram`, and the
  rows gather back at the root. Each amplitude is computed wholly on
  one host by the identical program, so the fleet's answers are
  **bit-identical** to a single-host run;
- **slice ranges shard across hosts** — an HBM-sliced structure's
  per-request slice loop splits into contiguous ranges
  (``amplitudes_det(..., slice_range=)``), each host sums its range,
  and the root adds the range partials *in range order*. The
  association of the sum differs from the single-host sequential loop,
  so range-sharded amplitudes agree to accumulation rounding (not
  bitwise) — the trade for an ``x num_hosts`` wall-clock win on deep
  slice loops.

Transport: every control and data message rides the coordination-KV
:func:`~tnc_tpu.parallel.partitioned.broadcast_object` channel (the
same reliable TCP path ``jax.distributed.initialize`` established —
PR 7 retired the silently-corrupting gloo collective for exactly this
role), with ``wait_forever`` so an idle fleet blocks on the next
command indefinitely instead of timing out. All processes execute the
same collective sequence in the same order by construction: one
command broadcast, then one gather broadcast per non-root process.

Deployment shape (see ``docs/serving.md``):

- every process binds the same circuit against a **shared**
  :class:`~tnc_tpu.serve.plancache.PlanCache` directory, so the fleet
  plans once — the first process to publish wins, everyone else gets
  a planner-span-free cache hit;
- process 0 runs the :class:`~tnc_tpu.serve.service.ContractionService`
  with a :class:`ClusterDispatcher`; every other process parks in
  :func:`serve_cluster`;
- a :class:`~tnc_tpu.serve.replan.SharedCacheWatcher` per process makes
  the background replanner's swaps visible fleet-wide.
"""

from __future__ import annotations

import logging
import os
import threading
import weakref
from typing import Sequence

import numpy as np

from tnc_tpu import obs
from tnc_tpu.obs import fleet as _fleet
from tnc_tpu.parallel.partitioned import (
    GatherLost,
    broadcast_object,
    gather_objects,
)
from tnc_tpu.resilience.faultinject import fault_point
from tnc_tpu.serve.rebind import BoundProgram, bind_template

logger = logging.getLogger(__name__)


class DispatcherStoppedError(RuntimeError):
    """The ClusterDispatcher was stopped; the call never entered the
    fleet's collective sequence. A clean shutdown signal (the service's
    degrade path fails only the in-flight requests), never a sign of
    fleet desync."""


class _ShardFailure:
    """A process's shard computation failed. Gathered in place of the
    rows so the fleet's collective sequence stays in lockstep — the
    root raises AFTER the gather completes (naming the process), which
    means a transient shard error surfaces as a retryable batch failure
    instead of desynchronizing the per-process broadcast counters (the
    service's retry re-dispatches into a still-synced fleet)."""

    def __init__(self, process: int, exc: BaseException):
        self.process = process
        self.error = f"{type(exc).__name__}: {exc}"

    def __repr__(self) -> str:  # shows up in the root's raise
        return f"process {self.process}: {self.error}"


def _raise_shard_failures(parts: list) -> None:
    failures = [p for p in parts if isinstance(p, _ShardFailure)]
    if failures:
        raise RuntimeError(
            "cluster shard computation failed on "
            + "; ".join(repr(f) for f in failures)
        )


def _procs() -> tuple[int, int]:
    """(process_count, process_index) — (1, 0) without a distributed
    runtime, so every entry point degrades to local execution."""
    try:
        import jax

        return int(jax.process_count()), int(jax.process_index())
    except Exception:  # noqa: BLE001 — no jax / not initialized
        return 1, 0


def shard_ranges(n_items: int, n_parts: int) -> list[tuple[int, int]]:
    """Split ``[0, n_items)`` into ``n_parts`` contiguous ranges whose
    sizes differ by at most one (leading ranges take the remainder).
    Empty ranges are legal — a 3-request batch on an 8-host fleet
    simply idles five hosts for that round.

    >>> shard_ranges(7, 3)
    [(0, 3), (3, 5), (5, 7)]
    >>> shard_ranges(2, 4)
    [(0, 1), (1, 2), (2, 2), (2, 2)]
    """
    n_parts = max(int(n_parts), 1)
    base, extra = divmod(max(int(n_items), 0), n_parts)
    out = []
    lo = 0
    for p in range(n_parts):
        hi = lo + base + (1 if p < extra else 0)
        out.append((lo, hi))
        lo = hi
    return out


def _concat_rows(parts: Sequence) -> np.ndarray:
    """Concatenate per-process row shards, dropping EMPTY shards first:
    ``amplitudes_det([])`` returns complex128 zeros whatever the
    backend dtype, and ``np.concatenate`` promotes across all inputs —
    so a batch smaller than the fleet (idle hosts return empty shards)
    would otherwise upcast the whole batch's dtype relative to the same
    batch on a single host."""
    arrays = [np.asarray(p) for p in parts]
    filled = [a for a in arrays if a.shape[0]] or arrays[:1]
    return np.concatenate(filled, axis=0)


def _gather_rows(
    mine, me: int, n: int, root: int, timeout_s: float | None = None
) -> list | None:
    """Collective gather of per-process payloads at the root (one
    root-only-read KV round, O(n · payload) — not n broadcasts); every
    process participates, non-root processes get ``None``. ``mine`` is
    this process's payload — possibly a :class:`_ShardFailure`, which
    the root raises only after the gather completed, keeping the
    fleet's collective sequence in lockstep through shard errors.

    ``timeout_s`` bounds every wait (elastic fleets): a slot whose
    process died mid-round comes back as a
    :class:`~tnc_tpu.parallel.partitioned.GatherLost` marker instead of
    hanging the root — the caller reassigns that shard to a survivor."""
    parts = gather_objects(
        mine, root=root, timeout_s=timeout_s,
        missing_ok=timeout_s is not None,
    )
    if me == root:
        _raise_shard_failures(parts)
    return parts


def cluster_amplitudes(
    bound: BoundProgram,
    batch_bits: Sequence[str],
    backend=None,
    root: int = 0,
    ranges: Sequence[tuple[int, int]] | None = None,
    timeout_s: float | None = None,
) -> np.ndarray | None:
    """One collective bra-sharded batch: every process of the fleet
    computes a contiguous shard of ``batch_bits`` with its local
    ``bound`` and the rows gather at ``root``. Returns the full
    ``(B,) + result_shape`` array on the root process, ``None``
    elsewhere. **All processes must call this with the same batch**
    (the root's command loop guarantees that in service deployments).

    Bit-identical to a single-host ``bound.amplitudes_det``: each row
    is produced by the same program, backend, and arithmetic — sharding
    only changes *where*, never *how*.

    ``ranges`` overrides the default even split with an explicit
    per-process row assignment (the elastic dispatcher's roster-aware
    placement: stale members get empty ranges). ``timeout_s`` bounds
    the gather; a shard lost to a dead process is recomputed at the
    root (bit-identical — same program, same rows) and counted as
    ``serve.elastic.reassigned``.
    """
    n, me = _procs()
    if n == 1:
        return bound.amplitudes_det(list(batch_bits), backend)
    if ranges is None:
        ranges = shard_ranges(len(batch_bits), n)
    lo, hi = ranges[me] if me < len(ranges) else (0, 0)
    try:
        with obs.span(
            "serve.cluster_shard", mode="bras", rows=hi - lo, process=me
        ):
            mine = bound.amplitudes_det(list(batch_bits[lo:hi]), backend)
    except Exception as exc:  # noqa: BLE001 — stay in collective lockstep
        mine = _ShardFailure(me, exc)
    parts = _gather_rows(mine, me, n, root, timeout_s=timeout_s)
    if me != root:
        return None
    for src, part in enumerate(parts):
        if not isinstance(part, GatherLost):
            continue
        # the process died mid-round: its rows rerun HERE, under the
        # same program and backend, so the batch stays bit-identical
        slo, shi = ranges[src] if src < len(ranges) else (0, 0)
        logger.warning(
            "cluster_amplitudes: process %d lost mid-round; recomputing "
            "rows [%d, %d) at the root", src, slo, shi,
        )
        _note_reassigned(mode="bras")
        parts[src] = bound.amplitudes_det(
            list(batch_bits[slo:shi]), backend
        )
    return _concat_rows(parts)


def _note_reassigned(mode: str) -> None:
    """Count a lost-shard reassignment on both surfaces: the obs
    registry (``serve.elastic.reassigned`` — scraped via /metrics) and
    the elastic module's cumulative tally (``stats()["elastic"]``)."""
    obs.counter_add("serve.elastic.reassigned", mode=mode)
    from tnc_tpu.serve import elastic as _elastic

    _elastic.count_event("reassigned")


def cluster_amplitudes_sliced(
    bound: BoundProgram,
    batch_bits: Sequence[str],
    backend=None,
    root: int = 0,
    ranges: Sequence[tuple[int, int]] | None = None,
    timeout_s: float | None = None,
    ckpt_dir: str | None = None,
) -> np.ndarray | None:
    """One collective slice-range-sharded batch for an HBM-sliced
    structure: every process runs the WHOLE batch over its contiguous
    share of the slice range (``amplitudes_det(slice_range=)``) and the
    root sums the range partials in range order. Exact up to float
    accumulation association (the single-host loop adds slice-by-slice,
    the fleet adds range partials) — use :func:`cluster_amplitudes`
    when bitwise reproducibility beats slice-loop wall-clock.

    The elastic knobs (all optional, default = frozen fleet):

    - ``ranges``: explicit per-process slice-range assignment (the
      roster-aware placement — stale members get ``(0, 0)``);
    - ``timeout_s``: bounds the gather. A range lost to a dead process
      is *reassigned* to the root, which — with ``ckpt_dir`` — resumes
      from the dead worker's last slice-boundary checkpoint on the
      shared directory. The resumed partial accumulates the remaining
      slices in the same order with the same kernels, so the recovered
      batch is **bit-identical** to the unfailed run (the PR 3
      guarantee, now load-bearing for host loss);
    - ``ckpt_dir``: shared checkpoint directory; every range shard
      persists its cursor + accumulator there at the configured cadence
      (``TNC_TPU_CKPT_EVERY`` / ``TNC_TPU_CKPT_SECS``).

    Workers expose the ``cluster.worker`` fault-injection site once per
    completed slice (``phase="slice"``), so a deterministic mid-request
    worker kill is one ``TNC_TPU_FAULTS`` rule away.
    """
    n, me = _procs()
    if n == 1:
        return bound.amplitudes_det(list(batch_bits), backend)
    if bound.sliced is None:
        raise ValueError(
            "cluster_amplitudes_sliced needs a sliced bound program"
        )
    num = bound.sliced.slicing.num_slices
    if ranges is None:
        ranges = shard_ranges(num, n)
    lo, hi = ranges[me] if me < len(ranges) else (0, 0)

    def _on_slice(cursor: int, _me=me) -> bool:
        # deterministic worker-loss injection: a `kill` rule here
        # SIGKILLs this process mid-range, exactly at the configured
        # slice — the scenario the reassignment path recovers from
        fault_point("cluster.worker", phase="slice", s=cursor, process=_me)
        return False

    try:
        with obs.span(
            "serve.cluster_shard", mode="slices", slices=hi - lo, process=me
        ):
            mine = bound.amplitudes_det(
                list(batch_bits), backend, slice_range=(lo, hi),
                ckpt=ckpt_dir, on_slice=_on_slice if ckpt_dir else None,
            )
    except Exception as exc:  # noqa: BLE001 — stay in collective lockstep
        mine = _ShardFailure(me, exc)
    parts = _gather_rows(mine, me, n, root, timeout_s=timeout_s)
    if me != root:
        return None
    for src, part in enumerate(parts):
        if not isinstance(part, GatherLost):
            continue
        slo, shi = ranges[src] if src < len(ranges) else (0, 0)
        logger.warning(
            "cluster_amplitudes_sliced: process %d lost mid-round; "
            "resuming its range [%d, %d) at the root%s", src, slo, shi,
            " from checkpoint" if ckpt_dir else "",
        )
        _note_reassigned(mode="slices")
        # resume, not restart: the dead worker's checkpoint (shared
        # ckpt_dir, signature includes the range) carries its partial
        # accumulator and cursor — the surviving recompute finishes the
        # same accumulation sequence, bit-identical to the unfailed run
        parts[src] = bound.amplitudes_det(
            list(batch_bits), backend, slice_range=(slo, shi),
            ckpt=ckpt_dir,
        )
    acc = np.asarray(parts[0])
    for p in parts[1:]:
        acc = acc + np.asarray(p)
    return acc


class ClusterDispatcher:
    """Root-side batch dispatcher for a multi-host
    :class:`~tnc_tpu.serve.service.ContractionService`: plug it in as
    ``ContractionService(..., dispatcher=ClusterDispatcher())``.

    Every call broadcasts one command to the worker processes parked in
    :func:`serve_cluster` and runs the matching collective: batched
    bras shard across hosts by default; a sliced bound program shards
    its slice ranges instead (``mode="auto"``). Calls are serialized by
    an internal lock — the fleet's collective sequence must never
    interleave two batches (or a batch with :meth:`stop`).

    ``stop()`` drains the in-flight collective round (the internal lock
    serializes it behind the round), then broadcasts the shutdown
    command and releases the workers; call it after stopping the
    service. A stopped dispatcher raises
    :class:`DispatcherStoppedError` — requests racing the shutdown fail
    cleanly instead of desynchronizing the fleet.

    Elastic operation (all optional):

    - ``registry`` (a :class:`~tnc_tpu.obs.fleet.FleetRegistry` on the
      fleet's shared directory): the dispatcher consults the live
      roster **per collective round** instead of the frozen process
      list — a worker whose heartbeat went stale gets an empty
      assignment (and its lost in-flight range is resumed at the root),
      a worker that recovers is assigned work again next round;
    - ``timeout_s``: bounds every broadcast/gather wait of a round
      (timeouts classify TRANSIENT through
      :func:`~tnc_tpu.resilience.retry.classify_exception`);
    - ``ckpt_dir``: shared slice-range checkpoint directory — the
      mid-request reassignment resume substrate
      (:func:`cluster_amplitudes_sliced`).
    """

    def __init__(
        self,
        mode: str = "auto",
        root: int = 0,
        registry=None,
        stale_after_s: float | None = None,
        timeout_s: float | None = None,
        ckpt_dir: str | None = None,
    ):
        if mode not in ("auto", "bras", "slices"):
            raise ValueError(f"unknown dispatch mode {mode!r}")
        self.mode = mode
        self.root = int(root)
        self.registry = registry
        self.stale_after_s = stale_after_s
        self.timeout_s = timeout_s
        self.ckpt_dir = ckpt_dir
        self._lock = threading.Lock()
        self._stopped = False
        self._seq = 0  # dispatch sequence, rides the TraceContext
        # the most recent round's per-process assignment (observability:
        # the service heartbeat ships it to serve_top --fleet)
        self.last_ranges: list | None = None
        # (weakref to bound, sig): an `is` check on the live object —
        # never id(), which CPython recycles across swap generations
        self._sig_cache: tuple | None = None

    def _resolve(self, bound: BoundProgram) -> str:
        if self.mode != "auto":
            return self.mode
        return "slices" if bound.sliced is not None else "bras"

    def _round_ranges(
        self, mode: str, bound: BoundProgram, bits: list, n: int
    ) -> list | None:
        """Per-round roster-aware assignment: contiguous ranges over the
        LIVE members only (stale/dead processes get ``(0, 0)``), or
        ``None`` (= even split over all n) without a registry."""
        if self.registry is None or n <= 1:
            return None
        from tnc_tpu.serve import elastic as _elastic

        live = _elastic.live_processes(
            self.registry, n, root=self.root,
            stale_after_s=self.stale_after_s,
        )
        n_items = (
            bound.sliced.slicing.num_slices
            if mode == "slices" else len(bits)
        )
        return _elastic.assign_ranges(n_items, live, n)

    def _plan_sig(self, bound: BoundProgram) -> str:
        """The bound's program signature, memoized per bound object —
        rides every command so the workers can prove (and restore, via
        the shared plan cache) plan agreement before computing."""
        cached = self._sig_cache
        if cached is not None and cached[0]() is bound:
            return cached[1]
        sig = bound.program.signature_digest()
        self._sig_cache = (weakref.ref(bound), sig)
        return sig

    def __call__(self, bound: BoundProgram, bits: list, backend=None):
        n, me = _procs()
        if me != self.root:
            raise RuntimeError(
                "ClusterDispatcher must run on the root process; workers "
                "belong in serve_cluster()"
            )
        mode = self._resolve(bound)
        with self._lock:
            if self._stopped:
                raise DispatcherStoppedError("ClusterDispatcher is stopped")
            self._seq += 1
            # injectable collective boundary: a `slow` rule here holds
            # the round open (the stop()-drain regression), a raising
            # kind exercises the poison path deterministically
            fault_point("cluster.broadcast", side="root", seq=self._seq)
            # cross-host trace propagation: the service set this batch's
            # identity (request ids, kind, plan generation) in a
            # thread-local around the dispatcher call; ship it with the
            # command so every worker's spans carry the root's rids
            ctx = _fleet.current_dispatch_context()
            trace = _fleet.TraceContext(
                riders=ctx.riders if ctx is not None else "",
                kind=ctx.kind if ctx is not None else mode,
                generation=ctx.generation if ctx is not None else 0,
                seq=self._seq,
                root_process=me,
                root_pid=os.getpid(),
            ).to_obj()
            ranges = self._round_ranges(mode, bound, bits, n)
            self.last_ranges = ranges
            if n > 1:
                # the per-round elastic envelope rides the command as a
                # 5th element; older workers reading 4-tuples keep
                # working when it is absent (frozen-fleet deployments)
                extra = None
                if (
                    ranges is not None
                    or self.timeout_s is not None
                    or self.ckpt_dir is not None
                ):
                    extra = {
                        "ranges": ranges,
                        "timeout_s": self.timeout_s,
                        "ckpt_dir": self.ckpt_dir,
                    }
                cmd = (mode, list(bits), self._plan_sig(bound), trace)
                if extra is not None:
                    cmd = cmd + (extra,)
                try:
                    broadcast_object(
                        cmd, root=self.root, timeout_s=self.timeout_s
                    )
                except Exception as exc:
                    # a failed COMMAND broadcast leaves the fleet's
                    # collective sequence in an unknown state — poison
                    # the dispatcher loudly rather than hang the next
                    # batch against desynced workers
                    self._stopped = True
                    raise RuntimeError(
                        "cluster command broadcast failed; the fleet's "
                        "collective sequence is unknown — dispatcher "
                        "stopped (restart the fleet)"
                    ) from exc
            obs.counter_add("serve.cluster.batches", mode=mode)
            if mode == "slices":
                return cluster_amplitudes_sliced(
                    bound, bits, backend, root=self.root,
                    ranges=ranges, timeout_s=self.timeout_s,
                    ckpt_dir=self.ckpt_dir,
                )
            return cluster_amplitudes(
                bound, bits, backend, root=self.root,
                ranges=ranges, timeout_s=self.timeout_s,
            )

    def stop(self, drain_timeout_s: float | None = None) -> None:
        """Release the worker processes (idempotent), DRAINING first:
        the lock serializes this call behind any in-flight collective
        round, so the stop command can never interleave with (or
        orphan) a round's broadcast/gather sequence — the shutdown race
        a bare flag check used to leave open.

        ``drain_timeout_s`` bounds the drain: when the in-flight round
        is wedged past it, the dispatcher is poisoned (no stop command
        can be safely broadcast into an unknown collective state) and
        :class:`TimeoutError` is raised — classify and escalate, the
        fleet needs a restart."""
        n, _me = _procs()
        if drain_timeout_s is None:
            self._lock.acquire()
        elif not self._lock.acquire(timeout=float(drain_timeout_s)):
            # can't join the collective sequence safely: poison so no
            # later call tries to; the flag write is atomic and the
            # in-flight round's holder re-checks under the lock only on
            # the NEXT round, which will now refuse cleanly
            self._stopped = True
            raise TimeoutError(
                f"ClusterDispatcher.stop: in-flight round did not drain "
                f"within {drain_timeout_s}s; dispatcher poisoned"
            )
        try:
            if self._stopped:
                return
            self._stopped = True
            if n > 1:
                broadcast_object(
                    ("stop", None, None, None), root=self.root,
                    timeout_s=self.timeout_s,
                )
        finally:
            self._lock.release()


def serve_cluster(
    bound: BoundProgram,
    backend=None,
    root: int = 0,
    plan_cache=None,
    telemetry_port: int | None = None,
    telemetry_host: str = "127.0.0.1",
    fleet_dir: str | None = None,
    heartbeat_s: float = 2.0,
) -> int:
    """Worker-process serving loop: park on the root's command channel
    and answer each batch's shard until the root's
    :meth:`ClusterDispatcher.stop`. Returns the number of batches
    served. Every process must hold a ``bound`` for the SAME circuit
    structure (bind through one shared plan cache so only the first
    process pays the planner).

    ``telemetry_port`` (0 = ephemeral) exposes THIS replica's live
    telemetry (:class:`~tnc_tpu.obs.http.TelemetryServer`) while it
    serves: ``/metrics`` renders the process-local obs registry (shard
    spans, worker rebind/batch counters), ``/healthz`` reports the
    worker's role/process index/batches served. The root process gets
    its endpoint from :meth:`~tnc_tpu.serve.service.ContractionService.
    serve_telemetry` instead — one scrape target per replica either
    way. The endpoint stops (port released) when the loop exits.

    ``fleet_dir`` (or ``TNC_TPU_FLEET_DIR``) joins this worker to the
    shared :class:`~tnc_tpu.obs.fleet.FleetRegistry`: a background
    :class:`~tnc_tpu.obs.fleet.Heartbeat` republishes identity, batches
    served, the in-flight state and the scrape URL every
    ``heartbeat_s`` seconds, and the entry retires (clean leave) when
    the loop exits. With a registry joined, ``/healthz`` reports the
    replica identity and heartbeat age, and every ``/metrics`` family
    carries a ``replica=`` label — the root's
    :class:`~tnc_tpu.obs.fleet.FleetAggregator` federates both.

    Every command carries the root's plan signature; a mismatch (the
    root's service adopted a background-replanner/shared-cache swap)
    makes the worker rebuild its bound through ``plan_cache`` — a
    cache hit on the swap the root already published, zero pathfinding
    — BEFORE computing, so every shard of a batch runs under one plan
    (the fleet-wide batch-atomicity the bit-identity claim needs).
    Without a ``plan_cache`` a signature mismatch raises instead of
    silently computing under a stale plan.
    """
    n, me = _procs()
    if n == 1 or me == root:
        raise RuntimeError(
            "serve_cluster is the NON-root side of a multi-process fleet"
        )
    progress = {"served": 0, "inflight": 0}
    identity = _fleet.replica_identity()
    name = _fleet.replica_name(identity)
    fleet_dir = fleet_dir or os.environ.get("TNC_TPU_FLEET_DIR") or None
    registry = (
        _fleet.FleetRegistry(fleet_dir, name=name) if fleet_dir else None
    )
    telemetry = None
    if telemetry_port is not None:
        from tnc_tpu.obs.http import TelemetryServer

        telemetry = TelemetryServer(
            host=telemetry_host,
            port=telemetry_port,
            health_fn=lambda: {
                "status": "ok",
                "role": "worker",
                "process": me,
                "replica": identity,
                "heartbeat_age_s": (
                    registry.last_heartbeat_age_s()
                    if registry is not None else None
                ),
                "batches_served": progress["served"],
            },
            base_labels={"replica": name},
        ).start()
    heartbeat = None
    if registry is not None:
        heartbeat = _fleet.Heartbeat(
            registry,
            provider=lambda: {
                "role": "worker",
                # the distributed process index: what the elastic
                # dispatcher's roster-aware placement keys live
                # membership on (obs/fleet knows replicas, the
                # collective knows process slots — this joins them)
                "process": me,
                "queue_depth": 0,
                "inflight": progress["inflight"],
                "batches_served": progress["served"],
                "url": telemetry.url if telemetry is not None else None,
            },
            interval_s=heartbeat_s,
        ).start()
    try:
        return _serve_cluster_loop(
            bound, backend, root, plan_cache, n, me, progress
        )
    finally:
        if heartbeat is not None:
            heartbeat.stop()  # retires the registry entry: clean leave
        if telemetry is not None:
            telemetry.stop()


def _serve_cluster_loop(
    bound, backend, root, plan_cache, n, me, progress
) -> int:
    served = 0
    my_sig = bound.program.signature_digest()
    while True:
        # injectable worker-loss boundary: `kill` drops this worker
        # between rounds (a clean leave the roster notices), `slow`
        # delays its next park — the hung-collective scenario the
        # root's bounded gather must survive
        fault_point("cluster.worker", phase="round", process=me)
        msg = broadcast_object(None, root=root, wait_forever=True)
        cmd, payload, want_sig = msg[0], msg[1], msg[2]
        # 4th element since the fleet plane: the root's TraceContext
        # (absent from an older root's 3-tuple — adoption just skips)
        trace = _fleet.TraceContext.from_obj(
            msg[3] if len(msg) > 3 else None
        )
        # 5th element since the elastic fleet: the per-round envelope
        # (roster-aware range assignment, wait bounds, shared ckpt dir)
        extra = msg[4] if len(msg) > 4 and isinstance(msg[4], dict) else {}
        ranges = extra.get("ranges")
        timeout_s = extra.get("timeout_s")
        ckpt_dir = extra.get("ckpt_dir")
        fault_point("cluster.broadcast", side="worker", process=me)
        if cmd == "stop":
            logger.info("serve_cluster: stop after %d batches", served)
            return served
        if want_sig is not None and want_sig != my_sig:
            try:
                if plan_cache is None:
                    raise RuntimeError(
                        "root's plan signature changed but this worker "
                        "has no plan_cache to rebuild from — bind "
                        "through the fleet's shared cache to follow "
                        "plan swaps"
                    )
                new_bound = bind_template(
                    bound.template, None, plan_cache, bound.target_size
                )
                new_sig = new_bound.program.signature_digest()
                if want_sig != new_sig:
                    raise RuntimeError(
                        "worker rebuilt from the shared plan cache but "
                        "still disagrees with the root's plan signature "
                        "— cache divergence or version skew; refusing "
                        "to serve a mixed-plan batch"
                    )
            except Exception as exc:  # noqa: BLE001 — stay in lockstep
                # join the batch's gather with a failure sentinel and
                # keep looping: the root raises a retryable batch error
                # naming this process; a worker that raised here would
                # instead hang the whole fleet's next collective
                logger.exception("serve_cluster: plan-swap adoption failed")
                _gather_rows(
                    _ShardFailure(me, exc), me, n, root, timeout_s=timeout_s
                )
                continue
            bound, my_sig = new_bound, new_sig
            obs.counter_add("serve.cluster.worker_rebinds")
            logger.info("serve_cluster: adopted root's plan swap")
        if cmd not in ("slices", "bras"):
            # unknown command: the fleet is version-skewed — stop loud
            raise RuntimeError(f"serve_cluster: unknown command {cmd!r}")
        progress["inflight"] = len(payload) if payload is not None else 0
        # adopt the root's trace context: this worker's serve.dispatch
        # span (and, via the ambient trace args, every partitioned.* /
        # slice span nested under it) carries the ROOT's request ids,
        # so the merged fleet timeline attributes this host's dispatch
        # wall time to the same rids the root's rollup uses
        with _fleet.adopt_trace_context(trace), obs.span(
            "serve.dispatch",
            batch=len(payload) if payload is not None else 0,
            kind=trace.kind if trace is not None else cmd,
            riders=trace.riders if trace is not None else "",
            generation=trace.generation if trace is not None else 0,
            seq=trace.seq if trace is not None else 0,
            remote=1,
            process=me,
        ):
            if cmd == "slices":
                cluster_amplitudes_sliced(
                    bound, payload, backend, root=root,
                    ranges=ranges, timeout_s=timeout_s, ckpt_dir=ckpt_dir,
                )
            else:
                cluster_amplitudes(
                    bound, payload, backend, root=root,
                    ranges=ranges, timeout_s=timeout_s,
                )
        served += 1
        progress["served"] = served
        progress["inflight"] = 0
        obs.counter_add("serve.cluster.worker_batches")
