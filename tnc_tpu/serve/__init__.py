"""tnc_tpu.serve — query serving: plan cache, bra rebinding, batched
queries, micro-batching front end.

The serving pipeline, front to back:

- :class:`ContractionService` (``service.py``) — async MIXED request
  queue (amplitudes + the :mod:`tnc_tpu.queries` query types:
  bitstring sampling, Pauli expectation values, marginal sweeps, each
  with a per-type batching key), micro-batching window, deadlines,
  admission control, retry + batch→singleton degradation.
- :class:`BoundProgram` / :func:`bind_circuit` (``rebind.py``) — one
  compiled program per circuit *structure*; per-request bra leaf data
  is rebound (and B requests batched into one dispatch) without
  replanning or retracing.
- :class:`PlanCache` (``plancache.py``) — persistent, LRU-bounded
  ``{path, slicing, hoist split, executor config}`` store keyed by a
  stable structure digest; repeat circuits skip the planner entirely.
- :class:`IntermediateStore` / :func:`compute_split` (``reuse.py``) —
  cross-request numeric reuse: value-aware subtree digests split every
  bound plan into a content-addressed cached prefix (contracted once
  store-wide, LRU memory + atomic npz host tiers, cost-model
  admission) plus a per-request residual; the service dispatcher
  additionally collapses duplicate queue riders into one dispatch.
- :class:`BackgroundReplanner` (``replan.py``) — anytime improvement:
  cache misses serve from a fast greedy plan, a low-priority worker
  hyper-optimizes hot structures between requests and atomically swaps
  in plans whose predicted cost wins; :class:`SharedCacheWatcher`
  adopts other replicas' published plans into a running service.
- :class:`FidelityRouter` (``service.py``) — fidelity tiers:
  ``submit*(..., rtol=)`` routes tolerant requests to the boundary-MPS
  chi-ladder tier (:mod:`tnc_tpu.approx`) under its own batching key,
  returns :class:`ApproxAnswer` ``(value, err, chi_used)``, and
  escalates tolerance misses to the exact pipeline (counted, capped).
- multi-host fan-out (``multihost.py``) — the root process shards
  micro-batched bras (bit-identical) or slice ranges across every
  process of a ``jax.distributed`` fleet via
  :class:`ClusterDispatcher` / :func:`serve_cluster`, results
  gathering at the root over the coordination-KV transport.
- elastic fleet (``elastic.py``) — live membership (per-round
  roster-aware slice-range assignment), mid-request reassignment
  (a dead worker's range resumes from its checkpoint at the root,
  bit-identically), priority preemption (``submit(tenant=,
  priority=)`` + weighted-fair scheduling, long sliced contractions
  yield at checkpoint boundaries), and load-aware scaling
  (:class:`ElasticController` advisory decisions +
  :class:`LocalAutoscaler` subprocess actuation).

See ``docs/serving.md`` and ``docs/planning.md``.
"""

from tnc_tpu.serve.plancache import (  # noqa: F401
    PlanCache,
    network_structure_digest,
)
from tnc_tpu.serve.rebind import (  # noqa: F401
    BoundProgram,
    bind_circuit,
    bind_template,
    plan_signature,
    plan_structure,
    stacked_bras,
    thread_batch,
)
from tnc_tpu.serve.reuse import (  # noqa: F401
    IntermediateStore,
    ReuseBinding,
    compute_split,
)
from tnc_tpu.serve.elastic import (  # noqa: F401
    ElasticConfig,
    ElasticController,
    LocalAutoscaler,
    assign_ranges,
    live_processes,
    weighted_fair_order,
)
from tnc_tpu.serve.multihost import (  # noqa: F401
    ClusterDispatcher,
    DispatcherStoppedError,
    cluster_amplitudes,
    cluster_amplitudes_sliced,
    serve_cluster,
    shard_ranges,
)
from tnc_tpu.serve.replan import (  # noqa: F401
    BackgroundReplanner,
    SharedCacheWatcher,
)
from tnc_tpu.serve.service import (  # noqa: F401
    ApproxAnswer,
    ContractionService,
    DeadlineExceededError,
    FidelityRouter,
    QueueFullError,
    ServeError,
    ServiceClosedError,
    TenantQuotaError,
)
