"""Planner fleet: distributed, cached, always-on path search.

The joint tree+slice search made sliced rescoring cheap; what the
hardest structures need now is search *scale*. This module turns the N
replicas of a serving fleet into N× planner throughput during idle
windows, with zero new infrastructure: work distribution rides the
plan-cache directory discipline (atomic unique-tmp JSON, mtime
staleness), trial results travel as symbolic plans
(:mod:`tnc_tpu.contractionpath.symbolic` — digest-deduped, structurally
diffable), and the merged winner publishes through the normal
:class:`~tnc_tpu.serve.plancache.PlanCache` store so every
:class:`~tnc_tpu.serve.replan.SharedCacheWatcher` replica adopts it
live.

Roles and protocol (one directory per structure under the board root):

- ``structure.json`` — the trial *seed*: the network's flat leaves
  (legs + bond dims only, never tensor data), the peak budget, and the
  deterministic trial grid parameters. The first replica to publish it
  is the **coordinator**; everyone else is a **worker**. Both then run
  the same claim loop — the roles differ only in who seeded.
- ``trial-<digest>.json`` — one trial spec, created with
  ``O_CREAT|O_EXCL`` so duplicate specs (two replicas seeding the same
  grid, a re-seeded coordinator) dedupe by digest at the filesystem.
- ``lease-<digest>.json`` — a worker's claim on a trial, also
  exclusive-create. A lease whose mtime goes stale (a SIGKILL'd
  worker) is **reclaimed** by atomic takeover (unique tmp +
  ``os.replace``); racing reclaims are benign because trials are
  deterministic functions of (structure, spec) and results dedupe by
  digest.
- ``result-<digest>.json`` — the trial's
  :class:`~tnc_tpu.contractionpath.symbolic.SymbolicPlan` (or a failure
  marker, so a structurally infeasible trial terminates instead of
  being reclaimed forever), written with the plan cache's unique-tmp +
  fsync + replace pattern.

Idle gating: the in-service pod (:class:`PlannerFleet`) only works
while ``service.queue_depth() == 0`` — the exact signal
:class:`~tnc_tpu.serve.replan.BackgroundReplanner` uses, so planning
never competes with serving. The replanner itself **delegates** its
hot-key searches to the pod when one is attached (one code path for
replanning and fleet planning, no cache-key races); a standalone
worker process (``python -m tnc_tpu.serve.plansvc <board-dir>``) joins
the same board from outside any service.

Trial diversity (the coordinator's grid, :func:`seed_trials`): a
greedy baseline, SA temperature ladders, partition+slice SA moves
(arXiv:2507.20667 — ``p_partition_move`` in
:func:`~tnc_tpu.contractionpath.sliced_cost.anneal_sliced`), and
slice-aware bisection whose cut weights discount already-sliced legs.
Every trial is deterministic given (structure, spec), so a distributed
N-trial budget selects from exactly the candidate set a single-node
N-trial run would — distributed search can tie but never lose
(``scripts/planner_quality.py`` pins this).
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import threading
import time
import uuid
from dataclasses import dataclass
from pathlib import Path
from typing import Mapping, Sequence

from tnc_tpu import obs
from tnc_tpu.contractionpath.contraction_cost import (
    CalibratedObjective,
    FlopsObjective,
    contract_path_cost,
)
from tnc_tpu.contractionpath.contraction_path import (
    ContractionPath,
    ssa_replace_ordering,
)
from tnc_tpu.contractionpath.symbolic import SymbolicPlan
from tnc_tpu.tensornetwork.tensor import LeafTensor
from tnc_tpu.utils.digest import stable_digest

logger = logging.getLogger(__name__)

WIRE_VERSION = 1


# -- trial specs --------------------------------------------------------


@dataclass(frozen=True)
class TrialSpec:
    """One deterministic planner trial: which base tree to build
    (``kind``) and how hard to refine it jointly. Identity is the
    stable digest of every field — the board's dedupe key.

    >>> s = TrialSpec(kind="sa", seed=43)
    >>> TrialSpec.from_obj(s.to_obj()) == s
    True
    """

    kind: str = "sa"  # greedy | sa | sa_partition | bisect
    seed: int = 42
    sa_steps: int = 600
    sa_rounds: int = 2
    t_start: float = 0.3
    t_end: float = 0.01
    p_partition: float = 0.0
    imbalance: float = 0.1
    slice_seed: int = 0

    def digest(self) -> str:
        return stable_digest(
            "tnc-trial-v%d" % WIRE_VERSION,
            self.kind, self.seed, self.sa_steps, self.sa_rounds,
            self.t_start, self.t_end, self.p_partition, self.imbalance,
            self.slice_seed,
        )

    def to_obj(self) -> dict:
        return {
            "version": WIRE_VERSION,
            "kind": self.kind,
            "seed": self.seed,
            "sa_steps": self.sa_steps,
            "sa_rounds": self.sa_rounds,
            "t_start": self.t_start,
            "t_end": self.t_end,
            "p_partition": self.p_partition,
            "imbalance": self.imbalance,
            "slice_seed": self.slice_seed,
        }

    @classmethod
    def from_obj(cls, obj: Mapping) -> "TrialSpec":
        if not isinstance(obj, Mapping) or obj.get("version") != WIRE_VERSION:
            raise ValueError(f"unusable trial spec: {obj!r:.80}")
        return cls(
            kind=str(obj["kind"]),
            seed=int(obj["seed"]),
            sa_steps=int(obj["sa_steps"]),
            sa_rounds=int(obj["sa_rounds"]),
            t_start=float(obj["t_start"]),
            t_end=float(obj["t_end"]),
            p_partition=float(obj["p_partition"]),
            imbalance=float(obj["imbalance"]),
            slice_seed=int(obj["slice_seed"]),
        )


#: temperature ladder for the SA trial grid (hot explores, cold polishes)
_TEMP_GRID = ((0.5, 0.01), (0.3, 0.01), (0.15, 0.005))
_TRIAL_KINDS = ("sa", "sa_partition", "bisect")


def seed_trials(
    ntrials: int,
    seed: int = 42,
    sa_steps: int = 600,
    sa_rounds: int = 2,
) -> list[TrialSpec]:
    """The coordinator's deterministic diversity grid: trial 0 is the
    greedy baseline (so the merged best can never lose to the no-search
    plan), then kinds cycle through plain SA / partition+slice SA /
    slice-aware bisection across the temperature ladder, with the
    bisection imbalance drawn exactly like the Hyperoptimizer's trials
    (``Random(seed + t)``). Same (ntrials, seed) → same specs on every
    replica, so concurrent seeders dedupe to one grid.

    >>> a, b = seed_trials(5, seed=7), seed_trials(5, seed=7)
    >>> [s.digest() for s in a] == [s.digest() for s in b]
    True
    >>> len({s.digest() for s in a})
    5
    """
    specs = [
        TrialSpec(kind="greedy", seed=seed, sa_steps=0, sa_rounds=0)
    ]
    for t in range(1, max(1, int(ntrials))):
        kind = _TRIAL_KINDS[(t - 1) % len(_TRIAL_KINDS)]
        t_start, t_end = _TEMP_GRID[((t - 1) // len(_TRIAL_KINDS))
                                    % len(_TEMP_GRID)]
        lo, hi = 0.02, 0.40  # the hyper search's imbalance range
        imbalance = lo + (hi - lo) * random.Random(seed + t).random()
        specs.append(TrialSpec(
            kind=kind,
            seed=seed + t,
            sa_steps=int(sa_steps),
            sa_rounds=int(sa_rounds),
            t_start=t_start,
            t_end=t_end,
            p_partition=0.15 if kind == "sa_partition" else 0.0,
            imbalance=round(imbalance, 6),
            slice_seed=t,
        ))
    return specs


# -- trial execution ----------------------------------------------------


def _greedy_base(inputs: Sequence[LeafTensor]) -> list[tuple[int, int]]:
    from tnc_tpu.contractionpath.paths.greedy import _ssa_greedy

    return _ssa_greedy(list(inputs))


def _greedy_slice_set(
    inputs: Sequence[LeafTensor],
    base: list[tuple[int, int]],
    target_size: float,
) -> frozenset[int]:
    """The greedy plan's slice set under the budget — the discount set
    for slice-aware bisection (legs that will be sliced away anyway
    should be cheap to cut)."""
    from tnc_tpu.contractionpath.sliced_cost import (
        SlicedCostEvaluator,
        greedy_slice_to_target,
    )

    replace = ssa_replace_ordering(
        ContractionPath.simple(list(base))
    ).toplevel
    ev = SlicedCostEvaluator(inputs, list(replace))
    try:
        greedy_slice_to_target(ev, target_size)
    except ValueError:
        return frozenset()
    return ev.removed


def _bisect_base(
    inputs: Sequence[LeafTensor],
    spec: TrialSpec,
    discount_legs: frozenset[int],
) -> list[tuple[int, int]]:
    """One slice-aware bisection tree: the Hyperoptimizer's trial
    pipeline (rank<=2 absorption, recursive bisection, greedy cutoff)
    with the candidate slice set's cut weights discounted."""
    from tnc_tpu.contractionpath.paths.hyper import (
        _bisection_path_impl,
        _simplify,
    )

    dims: dict[int, int] = {}
    for t in inputs:
        for leg, dim in t.edges():
            dims[leg] = dim
    prefix, legs_map, next_id = _simplify(
        {i: frozenset(t.legs) for i, t in enumerate(inputs)}, dims
    )
    core_ids = sorted(legs_map)
    rng = random.Random(spec.seed)
    return prefix + _bisection_path_impl(
        core_ids, legs_map, dims, next_id, rng, spec.imbalance, 12,
        discount_legs=discount_legs or None,
    )


def run_trial(
    spec: TrialSpec,
    inputs: Sequence[LeafTensor],
    target_size: float,
    cost_model=None,
) -> SymbolicPlan:
    """Execute one trial: build the kind's base tree, refine it with
    :func:`~tnc_tpu.contractionpath.sliced_cost.joint_slice_search`
    under the budget, and wrap the winner as a wire-ready
    :class:`~tnc_tpu.contractionpath.symbolic.SymbolicPlan`.
    Deterministic given (structure, spec) — which is what lets a
    distributed trial budget select from the identical candidate set a
    single-node run would. Raises ``ValueError`` when the budget is
    unreachable even from the greedy base."""
    from tnc_tpu.contractionpath.sliced_cost import (
        SlicedCostEvaluator,
        joint_slice_search,
    )

    inputs = list(inputs)
    greedy = _greedy_base(inputs)
    if spec.kind == "bisect":
        discount = _greedy_slice_set(inputs, greedy, target_size)
        bases = [_bisect_base(inputs, spec, discount), greedy]
    else:
        bases = [greedy]

    last_err: Exception | None = None
    for base in bases:
        try:
            pairs, slicing, cost = joint_slice_search(
                inputs,
                base,
                target_size,
                cost_model=cost_model,
                sa_steps=spec.sa_steps,
                sa_rounds=spec.sa_rounds,
                seed=spec.seed ^ (spec.slice_seed << 8),
                temps=(spec.t_start, spec.t_end),
                p_partition_move=spec.p_partition,
            )
        except ValueError as exc:  # this base can't reach the budget
            last_err = exc
            continue
        replace = ssa_replace_ordering(
            ContractionPath.simple(list(pairs))
        ).toplevel
        ev = SlicedCostEvaluator(
            inputs, list(replace), removed=slicing.legs,
            cost_model=cost_model,
        )
        return SymbolicPlan.from_search(
            pairs,
            slicing.legs,
            slicing.dims,
            cost,
            sliced_total=ev.sliced_total(),
            peak=ev.peak(),
            provenance={"trial": spec.to_obj(), "digest": spec.digest()},
        )
    raise ValueError(f"no trial base reaches the budget: {last_err}")


def run_trials_local(
    inputs: Sequence[LeafTensor],
    target_size: float,
    specs: Sequence[TrialSpec],
    cost_model=None,
) -> list[SymbolicPlan | None]:
    """Run a spec list in-process (the single-node arm of the
    distributed-vs-local quality comparison; infeasible trials map to
    ``None``)."""
    out: list[SymbolicPlan | None] = []
    for spec in specs:
        try:
            out.append(run_trial(spec, inputs, target_size, cost_model))
        except ValueError:
            out.append(None)
    return out


def best_plan(
    plans: Sequence[SymbolicPlan | None],
) -> SymbolicPlan | None:
    """The cheapest unique candidate: dedupe by structural digest
    (identical plans found by different trials count once), then min by
    recorded cost with the digest as a deterministic tiebreak."""
    unique: dict[str, SymbolicPlan] = {}
    for plan in plans:
        if plan is None or math.isinf(plan.cost):
            continue
        key = plan.digest()
        if key not in unique or plan.cost < unique[key].cost:
            unique[key] = plan
    if not unique:
        return None
    return min(unique.values(), key=lambda p: (p.cost, p.digest()))


# -- the on-disk trial board --------------------------------------------


class TrialBoard:
    """One structure's fan-out directory: structure seed, trial specs,
    leases, results — every write atomic (unique tmp + ``os.replace``
    or exclusive-create), every read tolerant (corrupt files deleted
    and counted, never raised), exactly the
    :class:`~tnc_tpu.serve.plancache.PlanCache` discipline.

    >>> import tempfile
    >>> b = TrialBoard(tempfile.mkdtemp(), owner="w0")
    >>> spec = TrialSpec(kind="greedy", seed=1, sa_steps=0, sa_rounds=0)
    >>> b.post_trial(spec), b.post_trial(spec)  # digest-deduped
    (True, False)
    >>> b.claim(spec.digest()), b.claim(spec.digest())
    (True, False)
    >>> b.done()
    False
    """

    STRUCTURE = "structure.json"

    def __init__(
        self,
        directory: str | Path,
        stale_after_s: float = 10.0,
        owner: str | None = None,
    ):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.stale_after_s = float(stale_after_s)
        self.owner = owner or f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.stats = {
            k: 0
            for k in (
                "posts", "dedup", "claims", "reclaims", "results",
                "failures", "corrupt",
            )
        }

    # -- atomic write helper -------------------------------------------

    def _write_atomic(self, target: Path, obj: dict) -> None:
        tmp = target.with_name(
            f"{target.name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
        )
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(obj, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, target)

    def _read_json(self, target: Path) -> dict | None:
        """Tolerant read: absent → None; corrupt → unlink + count,
        never raise (a torn or tampered board file degrades to "that
        record does not exist yet")."""
        try:
            with open(target, "r", encoding="utf-8") as fh:
                obj = json.load(fh)
            if not isinstance(obj, dict):
                raise ValueError("not a JSON object")
            return obj
        except FileNotFoundError:
            return None
        except Exception as exc:  # noqa: BLE001 — corruption → drop
            logger.warning(
                "board file %s unreadable (%s: %s); dropping it",
                target, type(exc).__name__, exc,
            )
            self.stats["corrupt"] += 1
            obs.counter_add("serve.plansvc.corrupt")
            try:
                target.unlink(missing_ok=True)
            except OSError:
                pass
            return None

    # -- structure seed -------------------------------------------------

    def publish_structure(
        self,
        inputs: Sequence[LeafTensor],
        target_size: float,
        key: str | None = None,
        extra: Mapping | None = None,
    ) -> bool:
        """Seed the board (coordinator role): the flat leaves as
        (legs, dims) lists — enough to rebuild cost-evaluation
        ``LeafTensor`` stand-ins in any process, never tensor data —
        plus the budget. First publisher wins (exclusive create)."""
        target = self.directory / self.STRUCTURE
        doc = {
            "version": WIRE_VERSION,
            "key": key,
            "target_size": float(target_size),
            "leaves": [
                [list(t.legs), [int(d) for _, d in t.edges()]]
                for t in inputs
            ],
            **dict(extra or {}),
        }
        try:
            fd = os.open(
                target, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644
            )
        except FileExistsError:
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
            fh.flush()
            os.fsync(fh.fileno())
        return True

    def load_structure(self) -> dict | None:
        """The seed with ``inputs`` rebuilt as ``LeafTensor`` stand-ins
        (legs + dims only), or None while unseeded."""
        doc = self._read_json(self.directory / self.STRUCTURE)
        if doc is None or doc.get("version") != WIRE_VERSION:
            return None
        try:
            doc["inputs"] = [
                LeafTensor(list(legs), list(dims))
                for legs, dims in doc["leaves"]
            ]
        except Exception:  # noqa: BLE001 — unusable seed → unseeded
            self.stats["corrupt"] += 1
            return None
        return doc

    # -- trials ---------------------------------------------------------

    def post_trial(self, spec: TrialSpec) -> bool:
        """Exclusive-create ``trial-<digest>.json`` — duplicate specs
        (same grid seeded twice) dedupe at the filesystem."""
        target = self.directory / f"trial-{spec.digest()}.json"
        try:
            fd = os.open(
                target, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644
            )
        except FileExistsError:
            self.stats["dedup"] += 1
            obs.counter_add("serve.plansvc.trial_dedup")
            return False
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(spec.to_obj(), fh)
            fh.flush()
            os.fsync(fh.fileno())
        self.stats["posts"] += 1
        obs.counter_add("serve.plansvc.trial_posted")
        return True

    def trials(self) -> list[TrialSpec]:
        out = []
        for path in sorted(self.directory.glob("trial-*.json")):
            obj = self._read_json(path)
            if obj is None:
                continue
            try:
                out.append(TrialSpec.from_obj(obj))
            except Exception:  # noqa: BLE001 — bad spec → drop
                self.stats["corrupt"] += 1
                try:
                    path.unlink(missing_ok=True)
                except OSError:
                    pass
        return out

    # -- leases ---------------------------------------------------------

    def _lease_path(self, digest: str) -> Path:
        return self.directory / f"lease-{digest}.json"

    def claim(self, digest: str) -> bool:
        """Claim a trial: exclusive-create its lease, or — when the
        existing lease's mtime has gone stale (its worker died) — take
        it over atomically. Racing reclaims are benign: trials are
        deterministic, so two workers running one spec publish
        identical results that dedupe by digest."""
        target = self._lease_path(digest)
        doc = {"owner": self.owner, "pid": os.getpid(), "at": time.time()}
        try:
            fd = os.open(
                target, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644
            )
        except FileExistsError:
            try:
                age = time.time() - target.stat().st_mtime
            except OSError:
                return False  # vanished mid-probe: someone else acted
            if age <= self.stale_after_s:
                return False
            try:
                self._write_atomic(target, doc)
            except OSError:
                return False
            self.stats["reclaims"] += 1
            obs.counter_add("serve.plansvc.lease_reclaimed")
            return True
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        self.stats["claims"] += 1
        obs.counter_add("serve.plansvc.lease_claimed")
        return True

    def renew(self, digest: str) -> None:
        """Refresh the lease mtime (a long trial's keep-alive)."""
        try:
            os.utime(self._lease_path(digest))
        except OSError:
            pass

    # -- results --------------------------------------------------------

    def _result_path(self, digest: str) -> Path:
        return self.directory / f"result-{digest}.json"

    def post_result(
        self, digest: str, plan: SymbolicPlan | None, error: str = ""
    ) -> None:
        """Publish a trial's outcome atomically. ``plan=None`` writes a
        failure marker — an infeasible trial *terminates* (counts as
        done) instead of being lease-reclaimed forever."""
        if plan is None:
            doc = {
                "version": WIRE_VERSION, "failed": True,
                "error": error[:500], "owner": self.owner,
            }
            self.stats["failures"] += 1
            obs.counter_add("serve.plansvc.trial_failed")
        else:
            doc = plan.to_obj()
            doc["trial"] = digest
            doc["owner"] = self.owner
            self.stats["results"] += 1
            obs.counter_add("serve.plansvc.trial_result")
        self._write_atomic(self._result_path(digest), doc)

    def results(self) -> list[SymbolicPlan]:
        """Every successful trial result, digest-validated on parse
        (a corrupt or tampered plan drops, never loads)."""
        out = []
        for path in sorted(self.directory.glob("result-*.json")):
            obj = self._read_json(path)
            if obj is None or obj.get("failed"):
                continue
            try:
                out.append(SymbolicPlan.from_obj(obj))
            except Exception as exc:  # noqa: BLE001 — bad plan → drop
                logger.warning(
                    "trial result %s rejected (%s: %s)",
                    path.name, type(exc).__name__, exc,
                )
                self.stats["corrupt"] += 1
                obs.counter_add("serve.plansvc.corrupt")
                try:
                    path.unlink(missing_ok=True)
                except OSError:
                    pass
        return out

    def result_digests(self) -> set[str]:
        return {
            p.name[len("result-"):-len(".json")]
            for p in self.directory.glob("result-*.json")
        }

    def pending(self) -> list[TrialSpec]:
        """Trials with no result yet (leased or not — the claim loop
        decides what is actually takeable)."""
        done = self.result_digests()
        return [s for s in self.trials() if s.digest() not in done]

    def done(self) -> bool:
        """True once every posted trial has an outcome (results and
        failure markers both count)."""
        trials = self.trials()
        return bool(trials) and not self.pending()


def work_board(
    board: TrialBoard,
    cost_model=None,
    max_trials: int | None = None,
    should_stop=None,
    hold_after_claim: bool = False,
) -> int:
    """The worker side of the protocol: claim pending trials, run them,
    publish results; returns the number of trials this call ran. Used
    identically by the in-service pod, the synchronous delegate path,
    and the standalone CLI — one code path, three entry points.

    ``hold_after_claim`` (tests): claim one trial, print its digest,
    then block forever — the SIGKILL target for the lease-reclaim
    lifecycle test."""
    doc = board.load_structure()
    if doc is None:
        return 0
    inputs = doc["inputs"]
    target_size = doc["target_size"]
    ran = 0
    while max_trials is None or ran < max_trials:
        if should_stop is not None and should_stop():
            break
        claimed = None
        for spec in board.pending():
            if board.claim(spec.digest()):
                claimed = spec
                break
        if claimed is None:
            break
        if hold_after_claim:
            print(f"CLAIMED {claimed.digest()}", flush=True)
            while True:  # parked until SIGKILL
                time.sleep(60.0)
        digest = claimed.digest()
        board.renew(digest)
        with obs.span("plansvc.trial") as sp:
            sp.add(kind=claimed.kind, seed=claimed.seed)
            try:
                plan = run_trial(claimed, inputs, target_size, cost_model)
            except Exception as exc:  # noqa: BLE001 — post the failure
                logger.warning(
                    "trial %s (%s) failed: %s", digest[:12], claimed.kind,
                    exc,
                )
                board.post_result(digest, None, error=str(exc))
                ran += 1
                continue
            sp.add(cost=plan.cost, num_slices=plan.num_slices)
        board.post_result(digest, plan)
        ran += 1
    return ran


# -- the in-service planner pod -----------------------------------------


class PlannerFleet:
    """The planner pod a serving replica attaches
    (:meth:`~tnc_tpu.serve.service.ContractionService.enable_plansvc`):
    a daemon thread that — only while the request queue is empty —
    seeds this structure's trial board (first replica wins the
    coordinator role), claims and runs trials like any worker, and,
    once the board drains, merges the global best through the normal
    plan-cache publish + rebuild + ``swap_bound`` path, so every
    shared-cache-watching replica adopts it live.

    >>> PlannerFleet.__name__
    'PlannerFleet'
    """

    def __init__(
        self,
        service,
        plan_cache,
        directory: str | Path | None = None,
        ntrials: int = 6,
        seed: int = 42,
        margin: float = 0.98,
        cost_model=None,
        sa_steps: int = 600,
        sa_rounds: int = 2,
        poll_interval_s: float = 0.05,
        stale_after_s: float = 10.0,
        owner: str | None = None,
    ):
        """``margin``: the merged best must be strictly cheaper than
        ``margin * incumbent`` to swap (same no-churn discipline as the
        background replanner). ``directory`` defaults to a ``plansvc/``
        sibling inside the plan-cache directory, so a fleet sharing the
        cache volume shares the boards with zero extra config."""
        self.service = service
        self.plan_cache = plan_cache
        self.cost_model = cost_model
        self.objective = (
            CalibratedObjective(cost_model)
            if cost_model is not None
            else FlopsObjective()
        )
        root = (
            Path(directory)
            if directory is not None
            else Path(plan_cache.directory) / "plansvc"
        )
        self.root = root
        self.ntrials = int(ntrials)
        self.seed = int(seed)
        self.margin = float(margin)
        self.sa_steps = int(sa_steps)
        self.sa_rounds = int(sa_rounds)
        self.poll_interval_s = float(poll_interval_s)
        self.stale_after_s = float(stale_after_s)
        self.owner = owner
        self.role = "idle"
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._boards: dict[str, TrialBoard] = {}
        self._merge_lock = threading.Lock()
        self._merged_keys: set[str] = set()
        self._seeded_keys: set[str] = set()
        self._keyed_bound = None
        self._keyed_key: str | None = None
        self._counts = {
            k: 0
            for k in (
                "trials_run", "seeded", "merges", "swaps", "rejects",
                "merge_failures",
            )
        }
        self.best_cost: float | None = None
        self.best_delta: float = 0.0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "PlannerFleet":
        if self._thread is not None:
            return self
        self.service._plansvc = self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tnc-serve-plansvc", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=60.0)

    def __enter__(self) -> "PlannerFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- board plumbing -------------------------------------------------

    def board_for(self, key: str) -> TrialBoard:
        board = self._boards.get(key)
        if board is None:
            board = TrialBoard(
                self.root / key,
                stale_after_s=self.stale_after_s,
                owner=self.owner,
            )
            self._boards[key] = board
        return board

    def supports(self, bound) -> bool:
        """Whether the fleet can plan this bound: the joint search
        needs a peak budget, and a swap needs the incumbent's cache
        record (the replanner's own refusal rule)."""
        return bound.target_size is not None and bool(bound.plan)

    def _bound_and_key(self):
        bound = self.service.bound
        if bound is self._keyed_bound:
            return bound, self._keyed_key
        key = self.plan_cache.key_for_network(
            bound.template.network, bound.target_size
        )
        self._keyed_bound, self._keyed_key = bound, key
        return bound, key

    def _ensure_seeded(self, board: TrialBoard, bound, key: str) -> None:
        from tnc_tpu.ops.program import flat_leaf_tensors

        if key in self._seeded_keys:
            return
        self._seeded_keys.add(key)
        if board.load_structure() is None:
            leaves = flat_leaf_tensors(bound.template.network)
            if board.publish_structure(
                leaves, bound.target_size, key=key,
                extra={"seed": self.seed, "ntrials": self.ntrials},
            ):
                self.role = "coordinator"
                self._counts["seeded"] += 1
                obs.counter_add("serve.plansvc.seeded")
        elif self.role == "idle":
            self.role = "worker"
        for spec in seed_trials(
            self.ntrials, seed=self.seed,
            sa_steps=self.sa_steps, sa_rounds=self.sa_rounds,
        ):
            board.post_trial(spec)

    # -- the idle-window loop -------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            if self.service.queue_depth() > 0:
                continue  # the replanner's idleness gate, verbatim
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — the pod must survive
                logger.exception("plansvc tick failed")
                _, key = self._bound_and_key()
                with self._merge_lock:
                    self._merged_keys.add(key)

    def _tick(self) -> None:
        bound, key = self._bound_and_key()
        with self._merge_lock:
            if key in self._merged_keys:
                return
        if not self.supports(bound) or (
            bound.plan.get("finder") not in _fast_finders()
        ):
            with self._merge_lock:
                self._merged_keys.add(key)
            return
        board = self.board_for(key)
        self._ensure_seeded(board, bound, key)
        ran = work_board(
            board,
            cost_model=self.cost_model,
            max_trials=1,
            should_stop=lambda: (
                self._stop.is_set() or self.service.queue_depth() > 0
            ),
        )
        self._counts["trials_run"] += ran
        if board.done():
            self.merge(bound, key, board)

    # -- delegation (the replanner's fleet path) ------------------------

    def delegate(self, bound, key: str) -> bool:
        """Synchronous fleet search for the replanner: seed (or join)
        the structure's board, work it until every trial has an
        outcome — stale-lease reclaims bound how long a dead worker
        can stall this — then merge-and-swap. Returns True when the
        merged best was swapped in. One code path with the pod loop:
        both sides run :func:`work_board` against the same board, so a
        replanner-delegated search and an idle-window fleet search are
        indistinguishable on disk."""
        board = self.board_for(key)
        self._ensure_seeded(board, bound, key)
        while not board.done():
            if self._stop.is_set():
                return False
            ran = work_board(
                board, cost_model=self.cost_model, max_trials=1,
                should_stop=self._stop.is_set,
            )
            self._counts["trials_run"] += ran
            if ran == 0 and not board.done():
                # everything pending is validly leased elsewhere: wait
                # for results (or for the leases to go stale)
                time.sleep(min(self.poll_interval_s, 0.05))
        return self.merge(bound, key, board)

    # -- merge + publish ------------------------------------------------

    def merge(self, bound, key: str, board: TrialBoard) -> bool:
        """Merge the board's global best into the serving plan through
        the background replanner's exact publish tail: re-price the
        candidate locally (never trust wire costs for a swap), apply
        the margin, publish via ``PlanCache.record_for``/``store``,
        rebuild through the normal cache-hit path, verify the rebuilt
        signature, and stage the swap at a batch boundary."""
        with self._merge_lock:
            if key in self._merged_keys:
                return False
            self._merged_keys.add(key)
        self._counts["merges"] += 1
        obs.counter_add("serve.plansvc.merge")
        try:
            return self._merge_impl(bound, key, board)
        except Exception:  # noqa: BLE001 — a failed merge must not
            # kill the pod loop; the incumbent keeps serving
            logger.exception("plansvc merge for %s failed", key[:12])
            self._counts["merge_failures"] += 1
            obs.counter_add("serve.plansvc.merge_failed")
            return False

    def _merge_impl(self, bound, key: str, board: TrialBoard) -> bool:
        from tnc_tpu.ops.program import build_program, flat_leaf_tensors
        from tnc_tpu.ops.sliced import build_sliced_program
        from tnc_tpu.serve.rebind import bind_template, plan_signature
        from tnc_tpu.serve.replan import plan_predicted_cost

        winner = best_plan(board.results())
        if winner is None:
            logger.info("plansvc board %s drained with no usable result",
                        key[:12])
            return False
        tn = bound.template.network
        leaves = flat_leaf_tensors(tn)
        path = ssa_replace_ordering(
            ContractionPath.simple([list(p) for p in winner.pairs])
        )
        slicing = winner.slicing()
        candidate_cost = plan_predicted_cost(
            leaves, path.toplevel, slicing, self.objective
        )
        incumbent_path = ContractionPath.from_obj(bound.plan["pairs"])
        incumbent_slicing = self.plan_cache.plan_slicing(bound.plan)
        incumbent_cost = plan_predicted_cost(
            leaves, incumbent_path.toplevel, incumbent_slicing,
            self.objective,
        )
        self.best_cost = candidate_cost
        if incumbent_cost > 0:
            self.best_delta = 1.0 - candidate_cost / incumbent_cost
        if not candidate_cost < self.margin * incumbent_cost:
            self._counts["rejects"] += 1
            obs.counter_add("serve.plansvc.reject")
            logger.info(
                "plansvc merge rejected for %s: best %.3e !< %.2f * "
                "incumbent %.3e", key[:12], candidate_cost, self.margin,
                incumbent_cost,
            )
            return False
        flops, peak = contract_path_cost(leaves, path, True)
        program = build_program(tn, path)
        sliced = (
            build_sliced_program(tn, path, slicing)
            if slicing is not None
            else None
        )
        plan = self.plan_cache.record_for(
            path,
            program,
            slicing=slicing,
            sliced_program=sliced,
            flops=flops,
            peak=peak,
            finder="PlannerFleet",
            target_size=bound.target_size,
            predicted_seconds=(
                candidate_cost if self.cost_model is not None else None
            ),
        )
        self.plan_cache.store(key, plan)
        new_bound = bind_template(
            bound.template, None, self.plan_cache, bound.target_size,
            bound.reuse.store if bound.reuse is not None else None,
        )
        if plan_signature(new_bound) != program.signature_digest():
            # the store did not survive the cache round-trip (disk
            # full, dir gone): swapping the fallback rebuild in would
            # not be the plan we priced — the incumbent stands
            self._counts["merge_failures"] += 1
            obs.counter_add("serve.plansvc.store_lost")
            logger.warning(
                "plansvc swap for %s abandoned: merged plan did not "
                "survive the cache round-trip", key[:12],
            )
            return False
        self.service.swap_bound(new_bound)
        self._counts["swaps"] += 1
        obs.counter_add("serve.plansvc.swap")
        logger.info(
            "plansvc swap for %s: predicted cost %.3e -> %.3e "
            "(%d trial results merged)",
            key[:12], incumbent_cost, candidate_cost,
            len(board.results()),
        )
        return True

    # -- surfaces -------------------------------------------------------

    def stats(self) -> dict:
        """The ``stats()["plansvc"]`` block: role, event counts, the
        aggregated board counters, and the last merge's best cost and
        relative improvement."""
        boards = {
            k: 0
            for k in (
                "posts", "dedup", "claims", "reclaims", "results",
                "failures", "corrupt",
            )
        }
        for board in self._boards.values():
            for k, v in board.stats.items():
                boards[k] = boards.get(k, 0) + v
        return {
            "role": self.role,
            "counts": dict(self._counts),
            "board": boards,
            "best_cost": self.best_cost,
            "best_delta": round(self.best_delta, 6),
        }

    def heartbeat_payload(self) -> dict:
        """What rides the fleet heartbeat (``serve_top --fleet``'s
        planner columns): role, trials completed here, and the last
        merge's relative cost improvement."""
        return {
            "role": self.role,
            "trials": self._counts["trials_run"],
            "best_delta": round(self.best_delta, 4),
        }


def _fast_finders() -> tuple:
    from tnc_tpu.serve.replan import _FAST_FINDERS

    return _FAST_FINDERS


# -- standalone worker CLI ----------------------------------------------


def main(argv: Sequence[str] | None = None) -> int:
    """``python -m tnc_tpu.serve.plansvc <board-dir>`` — a standalone
    worker: join the board, claim trials until none are takeable, exit
    with the number of trials run in the process exit status 0 path.
    ``--hold-after-claim`` parks after one claim (lease-reclaim test
    target); ``--stale-after`` tunes the reclaim threshold."""
    import argparse

    parser = argparse.ArgumentParser(description=main.__doc__)
    parser.add_argument("board", help="per-structure board directory")
    parser.add_argument("--owner", default=None)
    parser.add_argument("--stale-after", type=float, default=10.0)
    parser.add_argument("--max-trials", type=int, default=None)
    parser.add_argument("--hold-after-claim", action="store_true")
    args = parser.parse_args(argv)

    board = TrialBoard(
        args.board, stale_after_s=args.stale_after, owner=args.owner
    )
    if board.load_structure() is None:
        print("board has no structure.json", flush=True)
        return 2
    ran = work_board(
        board,
        max_trials=args.max_trials,
        hold_after_claim=args.hold_after_claim,
    )
    print(f"ran {ran} trials", flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
