"""Query-serving front end: one mixed queue + micro-batching dispatcher.

:class:`ContractionService` turns a :class:`~tnc_tpu.serve.rebind.
BoundProgram` into a request server. Callers submit bitstrings (from
any thread, or ``await`` the asyncio facade); a dispatcher thread
collects requests into micro-batches — up to ``max_batch`` riders or
``max_wait_ms`` after the first arrival, whichever comes first — and
issues ONE rebind dispatch per batch, the TPU-native shape for
amplitude traffic (one compiled program, B bitstrings per dispatch).

Beyond amplitudes, the queue is **mixed**: bitstring sampling, Pauli
expectation values and marginal sweeps are ``submit()``-able query
types (:meth:`~ContractionService.submit_sample` /
:meth:`~ContractionService.submit_expectation` /
:meth:`~ContractionService.submit_marginal`), each handled by a
registered :mod:`tnc_tpu.queries.handlers` handler. Every request
carries a per-type **batching key** (the marginal key includes the
wildcard mask); the dispatcher partitions each micro-batch window by
key, so a dispatched batch never mixes structures while all types
share one queue, one deadline/admission policy, and one plan cache.
Per-type counters and latency histograms ride ``stats()["by_type"]``
and the ``serve.query.*`` obs metrics.

Production posture:

- **admission control**: a bounded queue; submissions beyond
  ``max_queue`` fail fast with :class:`QueueFullError` instead of
  growing latency without bound;
- **deadlines**: each request may carry a timeout; requests that
  expire while queued are completed with
  :class:`DeadlineExceededError` at batch assembly (they never waste a
  dispatch);
- **resilience**: the batch dispatch runs under the shared
  :class:`~tnc_tpu.resilience.retry.RetryPolicy` (transient runtime
  failures retry with backoff); a batch that still fails **degrades to
  singleton requests** — each rider is re-dispatched alone, so one
  poisoned request cannot fail its co-riders;
- **observability**: ``serve.queue_depth`` gauge,
  ``serve.batch_size``/``serve.latency_s``/``serve.wait_s``
  histograms, ``serve.requests.*`` counters, plus the plan-cache
  hit/miss counters from :mod:`tnc_tpu.serve.plancache`;
- **anytime replanning**: a cache-missed structure serves from its
  fast greedy plan immediately; a
  :class:`~tnc_tpu.serve.replan.BackgroundReplanner` may later
  :meth:`~ContractionService.swap_bound` in a hyper-optimized
  :class:`BoundProgram` for the SAME structure — the dispatcher adopts
  it atomically between batches (every batch runs wholly under one
  bound, so in-flight requests are never split across plans).
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np

from tnc_tpu import obs
from tnc_tpu.resilience import retry as _retry
from tnc_tpu.serve.rebind import BoundProgram, bind_circuit

logger = logging.getLogger(__name__)


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class QueueFullError(ServeError):
    """Admission control rejected the request (queue at ``max_queue``)."""


class DeadlineExceededError(ServeError):
    """The request's deadline passed before it could be dispatched."""


class ServiceClosedError(ServeError):
    """The service is stopped and no longer accepts requests."""


@dataclass
class _Request:
    bits: object  # the validated payload (determined bits for amplitudes)
    future: concurrent.futures.Future
    deadline: float | None  # absolute monotonic, None = no deadline
    t_submit: float = field(default_factory=time.monotonic)
    kind: str = "amplitude"
    # batching key: requests dispatch together ONLY when keys match
    # (per-type, plus structure discriminators like the marginal mask)
    key: tuple = ("amplitude",)


_STATS_CAP = 4096  # bounded in-memory samples for stats()/bench


class ContractionService:
    """Micro-batching amplitude server over one bound program.

    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> c.append_gate(TensorData.gate("cx"), [reg.qubit(0), reg.qubit(1)])
    >>> with ContractionService.from_circuit(c) as svc:
    ...     amp = svc.amplitude("00")
    >>> round(abs(amp), 6)
    0.707107
    """

    def __init__(
        self,
        bound: BoundProgram,
        backend=None,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        max_queue: int = 1024,
        retry_policy: _retry.RetryPolicy | None = None,
        dispatcher=None,
    ):
        """``dispatcher``: optional batch-execution hook
        ``fn(bound, bits, backend) -> (B,)+result_shape array``
        replacing the local ``bound.amplitudes_det`` dispatch — the
        multi-host fan-out point (:class:`~tnc_tpu.serve.multihost.
        ClusterDispatcher` shards the micro-batch across host
        processes and gathers at the root). Everything else (queueing,
        deadlines, retry, degradation, plan swaps) is unchanged: the
        dispatcher is only ever called with a batch and the CURRENT
        bound, so plan swaps stay batch-atomic across the fleet."""
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.bound = bound
        self.backend = backend  # None → rebind's numpy default
        self.dispatcher = dispatcher
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        self.retry_policy = retry_policy or _retry.default_policy()
        self._queue: deque[_Request] = deque()
        self._cond = threading.Condition()
        self._running = False
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._counts = {
            "submitted": 0, "completed": 0, "failed": 0,
            "expired": 0, "rejected": 0, "cancelled": 0,
            "batches": 0, "degraded_batches": 0, "plan_swaps": 0,
        }
        self._batch_sizes: deque[int] = deque(maxlen=_STATS_CAP)
        self._latencies: deque[float] = deque(maxlen=_STATS_CAP)
        # per-query-type breakdowns (kind -> counts / latency samples);
        # "amplitude" is pre-seeded so dashboards always see the
        # primary type even before traffic arrives
        self._by_type: dict[str, dict] = {}
        self._latencies_by_type: dict[str, deque] = {}
        self._ensure_type("amplitude")
        # registered query handlers (sampling / expectation / marginal)
        self._handlers: dict[str, object] = {}
        # an improved BoundProgram staged by the background replanner;
        # the dispatcher adopts it at the next batch boundary
        self._pending_bound: BoundProgram | None = None
        self._replanner = None  # attached BackgroundReplanner, if any
        self._watchers: list = []  # attached SharedCacheWatchers

    @classmethod
    def from_circuit(
        cls,
        circuit,
        mask=None,
        pathfinder=None,
        plan_cache=None,
        backend=None,
        target_size=None,
        background_replan: bool = False,
        replan_options: dict | None = None,
        shared_cache_watch: bool = False,
        watch_options: dict | None = None,
        queries: bool = False,
        **kwargs,
    ) -> "ContractionService":
        """Build (plan/compile once, plan cache honored) and start.

        ``queries=True`` additionally registers the sampling /
        expectation / marginal query handlers for the same circuit
        (:func:`tnc_tpu.queries.handlers.attach_query_handlers`),
        sharing ``plan_cache``/``target_size``; the circuit is copied
        before the amplitude finalizer consumes it.

        ``background_replan=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.replan.BackgroundReplanner`: a cache miss
        is answered from the fast greedy plan immediately, and the
        worker hyper-optimizes the structure between requests, swapping
        in the improved plan when its predicted cost wins.
        ``replan_options`` are its constructor kwargs.

        ``shared_cache_watch=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.replan.SharedCacheWatcher`: a replica
        deployment sharing one cache directory adopts OTHER replicas'
        published plans (including their background replanner's swaps)
        at batch boundaries. ``watch_options`` are its kwargs."""
        if background_replan and plan_cache is None:
            raise ValueError("background_replan requires a plan_cache")
        if shared_cache_watch and plan_cache is None:
            raise ValueError("shared_cache_watch requires a plan_cache")
        query_circuit = circuit.copy() if queries else None
        bound = bind_circuit(circuit, mask, pathfinder, plan_cache, target_size)
        svc = cls(bound, backend=backend, **kwargs)
        svc.start()
        try:
            if queries:
                svc.enable_queries(
                    query_circuit,
                    pathfinder=pathfinder,
                    plan_cache=plan_cache,
                    target_size=target_size,
                )
            if background_replan:
                from tnc_tpu.serve.replan import BackgroundReplanner

                BackgroundReplanner(
                    svc, plan_cache, **(replan_options or {})
                ).start()
            if shared_cache_watch:
                from tnc_tpu.serve.replan import SharedCacheWatcher

                watcher = SharedCacheWatcher(
                    svc, plan_cache, **(watch_options or {})
                )
                svc._watchers.append(watcher)
                watcher.start()
        except Exception:
            # a bad option kwarg must not leak a running dispatcher
            # thread (or half the attachments) the caller can't reach
            svc.stop()
            raise
        return svc

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ContractionService":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="tnc-serve-dispatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop accepting requests; by default finish ('drain') what is
        already queued, otherwise fail queued requests with
        :class:`ServiceClosedError`. An attached background replanner
        is stopped first (it must not swap into a closing service)."""
        replanner, self._replanner = self._replanner, None
        if replanner is not None:
            replanner.stop()
        watchers, self._watchers = list(self._watchers), []
        for watcher in watchers:
            watcher.stop()
        with self._cond:
            if not self._running:
                return
            self._running = False
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._complete(req, exc=ServiceClosedError("stopped"))
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    # -- plan swap (anytime replanning) ------------------------------------

    def swap_bound(self, bound: BoundProgram) -> None:
        """Stage an improved :class:`BoundProgram` for the SAME circuit
        structure (the background replanner's entry point). The
        dispatcher adopts it at the next batch boundary — batches are
        dispatched wholly under one bound, so no in-flight request ever
        mixes plans. Amplitude *values* are plan-independent (both
        programs contract the same network), so co-existing old-plan
        and new-plan responses are equally correct."""
        from tnc_tpu.serve.plancache import network_structure_digest

        if bound.template is not self.bound.template:
            # same structure digest (legs/dims/budget) AND same leaf
            # values: the digest is value-blind by design (all
            # bitstrings share it), but a swap with different gate
            # VALUES would silently serve another circuit's amplitudes
            if network_structure_digest(
                bound.template.network, bound.target_size
            ) != network_structure_digest(
                self.bound.template.network, self.bound.target_size
            ) or not all(
                np.array_equal(a, b)
                for a, b in zip(bound.arrays, self.bound.arrays)
            ):
                raise ValueError(
                    "swap_bound: replacement program was bound for a "
                    "different structure or different leaf values — "
                    "not a plan for this service's circuit/budget"
                )
        with self._lock:
            self._pending_bound = bound

    def _current_bound(self) -> BoundProgram:
        """The bound to dispatch the NEXT batch under, adopting any
        staged replacement first."""
        with self._lock:
            pending, self._pending_bound = self._pending_bound, None
            if pending is not None:
                self.bound = pending
                self._counts["plan_swaps"] += 1
        if pending is not None:
            obs.counter_add("serve.replan.adopted")
            logger.info("adopted replanned program for serving")
        return self.bound

    def queue_depth(self) -> int:
        """Instantaneous queue length (the replanner's idleness probe)."""
        with self._cond:
            return len(self._queue)

    def __enter__(self) -> "ContractionService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- query handlers ----------------------------------------------------

    def register_query_handler(self, handler) -> None:
        """Register a query-type handler (``kind`` attribute +
        ``validate(payload) -> (payload, key)`` at admission +
        ``dispatch(payloads, backend) -> results`` per batch — the
        :mod:`tnc_tpu.queries.handlers` protocol). One handler per
        kind; re-registering replaces."""
        self._handlers[str(handler.kind)] = handler

    def enable_queries(
        self,
        circuit,
        pathfinder=None,
        plan_cache=None,
        target_size=None,
    ) -> "ContractionService":
        """Register the sampling / expectation / marginal handlers for
        ``circuit`` (copied, not consumed) — the query-engine
        attachment point (lazy import: :mod:`tnc_tpu.queries` depends
        on this module's package)."""
        from tnc_tpu.queries.handlers import attach_query_handlers

        attach_query_handlers(
            self, circuit,
            pathfinder=pathfinder, plan_cache=plan_cache,
            target_size=target_size,
        )
        return self

    # -- submission --------------------------------------------------------

    def _enqueue(
        self,
        kind: str,
        key: tuple,
        payload,
        timeout_s: float | None,
    ) -> concurrent.futures.Future:
        """Shared admission path for every query type: bounded queue,
        deadline arming, global + per-type accounting."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        deadline = (
            time.monotonic() + float(timeout_s) if timeout_s is not None else None
        )
        with self._cond:
            if not self._running:
                self._count("rejected")
                self._count_type(kind, "rejected")
                obs.counter_add("serve.requests.rejected", reason="closed")
                raise ServiceClosedError("service is not running")
            if len(self._queue) >= self.max_queue:
                self._count("rejected")
                self._count_type(kind, "rejected")
                obs.counter_add("serve.requests.rejected", reason="queue_full")
                raise QueueFullError(
                    f"queue at max_queue={self.max_queue}; retry later"
                )
            self._queue.append(
                _Request(payload, fut, deadline, kind=kind, key=key)
            )
            depth = len(self._queue)
            self._cond.notify()
        self._count("submitted")
        self._count_type(kind, "submitted")
        obs.counter_add("serve.requests.submitted")
        obs.counter_add("serve.query.submitted", type=kind)
        obs.gauge_set("serve.queue_depth", depth)
        return fut

    def submit(
        self, bitstring: str | Iterable, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """Enqueue one amplitude request; returns a ``Future`` resolving
        to the amplitude (complex scalar, or an ndarray over the
        template's open legs). ``timeout_s`` arms a deadline."""
        # validate at admission: a malformed request must fail alone,
        # immediately — not poison a whole batch at dispatch time. The
        # determined-position bits (not the raw object) are what gets
        # queued: a one-shot iterable is consumed by this validation,
        # and dispatch never re-validates
        bitstring = self.bound.template.request_bits(bitstring)
        return self._enqueue(
            "amplitude", ("amplitude",), bitstring, timeout_s
        )

    def submit_query(
        self, kind: str, payload, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """Enqueue one typed query request through its registered
        handler; the handler validates the payload at admission and
        assigns the batching key."""
        handler = self._handlers.get(kind)
        if handler is None:
            raise ValueError(
                f"no handler registered for query kind {kind!r} "
                "(enable_queries / register_query_handler first)"
            )
        payload, key = handler.validate(payload)
        return self._enqueue(kind, tuple(key), payload, timeout_s)

    def submit_sample(
        self,
        n_samples: int = 1,
        seed=None,
        timeout_s: float | None = None,
    ) -> concurrent.futures.Future:
        """Sample ``n_samples`` bitstrings from |⟨b|C|0⟩|² (chain-rule
        sampler); the future resolves to a list of bitstrings. A seeded
        request's stream is deterministic regardless of co-riders."""
        return self.submit_query(
            "sample", {"n_samples": n_samples, "seed": seed}, timeout_s
        )

    def submit_expectation(
        self, terms, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """⟨ψ|P|ψ⟩ (a Pauli string) or a Pauli sum (iterable of
        ``(coeff, pauli)``); the future resolves to the complex
        value. Terms batch through one sandwich structure."""
        return self.submit_query("expectation", terms, timeout_s)

    def submit_marginal(
        self, pattern, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """Marginal probability of ``pattern``'s determined bits
        (``'*'`` = marginalized); the future resolves to a float."""
        return self.submit_query("marginal", pattern, timeout_s)

    def sample(self, n_samples: int = 1, seed=None,
               timeout_s: float | None = None) -> list:
        """Blocking :meth:`submit_sample`."""
        return self.submit_sample(n_samples, seed, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def expectation(self, terms, timeout_s: float | None = None) -> complex:
        """Blocking :meth:`submit_expectation`."""
        return self.submit_expectation(terms, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def marginal(self, pattern, timeout_s: float | None = None) -> float:
        """Blocking :meth:`submit_marginal`."""
        return self.submit_marginal(pattern, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def amplitude(self, bitstring, timeout_s: float | None = None):
        """Blocking single-amplitude query (deadline doubles as the
        caller-side wait bound)."""
        fut = self.submit(bitstring, timeout_s)
        return fut.result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    async def amplitude_async(self, bitstring, timeout_s: float | None = None):
        """Asyncio facade: ``await service.amplitude_async("0101")``."""
        import asyncio

        return await asyncio.wrap_future(self.submit(bitstring, timeout_s))

    # -- dispatcher --------------------------------------------------------

    def _collect_batch(self) -> list[_Request] | None:
        """Block for the first request, then hold the window open up to
        ``max_wait_s`` (or until ``max_batch`` riders); None = stopped
        and drained."""
        with self._cond:
            while not self._queue:
                if not self._running:
                    return None
                self._cond.wait(timeout=0.1)
            t0 = time.monotonic()
            deadline = t0 + self.max_wait_s
            while (
                len(self._queue) < self.max_batch
                and time.monotonic() < deadline
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    break
            batch = [
                self._queue.popleft()
                for _ in range(min(self.max_batch, len(self._queue)))
            ]
            obs.gauge_set("serve.queue_depth", len(self._queue))
            return batch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            except Exception as exc:  # noqa: BLE001 — the dispatcher must survive
                # _run_batch handles dispatch failures itself; anything
                # reaching here is a bookkeeping bug — fail the batch,
                # keep serving
                logger.exception("dispatcher batch processing failed")
                for req in batch:
                    self._complete(req, exc=ServeError(f"dispatcher error: {exc}"))

    def _complete(self, req: _Request, result=None, exc=None) -> bool:
        """Resolve a request's future, tolerating caller-side
        cancellation (``fut.cancel()`` / an abandoned asyncio await):
        completing a cancelled future raises ``InvalidStateError``,
        which must never kill the dispatcher thread."""
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)
            return True
        except concurrent.futures.InvalidStateError:
            self._count("cancelled")
            obs.counter_add("serve.requests.cancelled")
            return False

    def _dispatch_amps(self, bound: BoundProgram, bits: list) -> np.ndarray:
        """One batch execution under ``bound`` — locally, or through the
        pluggable ``dispatcher`` (multi-host fan-out)."""
        if self.dispatcher is not None:
            return self.dispatcher(bound, bits, self.backend)
        return bound.amplitudes_det(bits, self.backend)

    def _per_request(self, amps: np.ndarray, i: int):
        out = amps[i]
        # copy, not view: co-riders must never alias one mutable batch
        # buffer (an in-place edit by one caller would corrupt another's
        # already-delivered result)
        return complex(out) if out.shape == () else np.array(out)

    def _dispatch_group(
        self, kind: str, payloads: list, bound: BoundProgram
    ) -> list:
        """One batched execution of a same-key group; returns one
        result object per payload."""
        if kind == "amplitude":
            amps = self._dispatch_amps(bound, payloads)
            return [
                self._per_request(amps, i) for i in range(len(payloads))
            ]
        return self._handlers[kind].dispatch(payloads, self.backend)

    def _run_batch(self, batch: list[_Request]) -> None:
        now = time.monotonic()
        live: list[_Request] = []
        for req in batch:
            if req.deadline is not None and now > req.deadline:
                self._count("expired")
                self._count_type(req.kind, "expired")
                obs.counter_add("serve.requests.expired")
                self._complete(
                    req,
                    exc=DeadlineExceededError(
                        f"deadline exceeded after "
                        f"{now - req.t_submit:.3f}s in queue"
                    ),
                )
            else:
                live.append(req)
        if not live:
            return
        for req in live:
            obs.observe("serve.wait_s", now - req.t_submit)
        # one bound per window: adopt a staged replan at this boundary,
        # then every group of the window (including singleton-degrade
        # re-dispatches) runs under the SAME program
        bound = self._current_bound()
        # partition the window by batching key (insertion order): one
        # dispatch per key — a batch never mixes query types or
        # structures, while all types share the queue and the window
        groups: dict[tuple, list[_Request]] = {}
        for req in live:
            groups.setdefault(req.key, []).append(req)
        for group in groups.values():
            self._run_group(group, bound)

    def _run_group(
        self, group: list[_Request], bound: BoundProgram
    ) -> None:
        kind = group[0].kind
        self._count("batches")
        self._count_type(kind, "batches")
        with self._lock:
            self._batch_sizes.append(len(group))
        obs.observe("serve.batch_size", len(group))
        obs.observe("serve.query.batch_size", len(group), type=kind)
        payloads = [req.bits for req in group]
        try:
            with obs.span("serve.dispatch", batch=len(group), kind=kind):
                results = self.retry_policy.run(
                    lambda: self._dispatch_group(kind, payloads, bound),
                    label="serve.dispatch",
                )
        except Exception as exc:  # noqa: BLE001 — degrade to singletons
            logger.warning(
                "%s batch of %d failed (%s: %s); degrading to singleton "
                "requests", kind, len(group), type(exc).__name__, exc,
            )
            self._count("degraded_batches")
            obs.counter_add("serve.batch_degraded")
            self._run_singletons(group, bound)
            return
        done = time.monotonic()
        for req, result in zip(group, results):
            if self._complete(req, result=result):
                self._finish(req, done)

    def _run_singletons(self, batch: list[_Request], bound=None) -> None:
        """Degraded mode: each rider re-dispatched alone — one bad
        request (or a transient that outlived its retries) fails only
        itself. ``bound`` pins the batch's program across the
        re-dispatches (a mid-degrade plan swap must not split a
        batch)."""
        if bound is None:
            bound = self.bound
        for req in batch:
            try:
                results = self._dispatch_group(req.kind, [req.bits], bound)
            except Exception as exc:  # noqa: BLE001 — per-request verdict
                self._count("failed")
                self._count_type(req.kind, "failed")
                obs.counter_add("serve.requests.failed")
                obs.counter_add("serve.query.failed", type=req.kind)
                self._complete(req, exc=exc)
                continue
            if self._complete(req, result=results[0]):
                self._finish(req, time.monotonic())

    def _finish(self, req: _Request, done: float) -> None:
        self._count("completed")
        self._count_type(req.kind, "completed")
        obs.counter_add("serve.requests.completed")
        obs.counter_add("serve.query.completed", type=req.kind)
        latency = done - req.t_submit
        with self._lock:
            self._latencies.append(latency)
            self._latencies_by_type[req.kind].append(latency)
        obs.observe("serve.latency_s", latency)
        obs.observe("serve.query.latency_s", latency, type=req.kind)

    # -- stats -------------------------------------------------------------

    _TYPE_KEYS = (
        "submitted", "completed", "failed", "expired", "rejected",
        "batches",
    )

    def _ensure_type(self, kind: str) -> dict:
        """Per-type accounting row (callers hold no lock; dict writes
        are guarded by ``_lock`` in the callers that mutate)."""
        row = self._by_type.get(kind)
        if row is None:
            row = {k: 0 for k in self._TYPE_KEYS}
            self._by_type[kind] = row
            self._latencies_by_type[kind] = deque(maxlen=_STATS_CAP)
        return row

    def _count(self, key: str) -> None:
        with self._lock:
            self._counts[key] += 1

    def _count_type(self, kind: str, key: str) -> None:
        with self._lock:
            self._ensure_type(kind)[key] += 1

    def reset_stats(self) -> None:
        """Zero the in-memory counts and samples — benchmarks call this
        after their warmup so compile-time requests never skew the
        published batch-size/latency distribution."""
        with self._lock:
            for key in self._counts:
                self._counts[key] = 0
            self._batch_sizes.clear()
            self._latencies.clear()
            for kind, row in self._by_type.items():
                for key in row:
                    row[key] = 0
                self._latencies_by_type[kind].clear()

    @staticmethod
    def _pct(sorted_vals: list, q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
        return float(sorted_vals[idx])

    def stats(self) -> dict:
        """Snapshot for dashboards and ``bench.py --serve``: request
        counts, batch-size distribution, latency percentiles, and the
        per-query-type breakdown (``by_type``: one row per kind with
        request/batch counts and latency percentiles)."""
        with self._lock:
            counts = dict(self._counts)
            sizes = list(self._batch_sizes)
            lats = sorted(self._latencies)
            by_type = {
                kind: (
                    dict(row),
                    sorted(self._latencies_by_type[kind]),
                )
                for kind, row in self._by_type.items()
            }

        def latency_block(sorted_lats: list) -> dict:
            return {
                "p50": round(self._pct(sorted_lats, 0.50), 6),
                "p99": round(self._pct(sorted_lats, 0.99), 6),
                "max": round(sorted_lats[-1], 6) if sorted_lats else 0.0,
            }

        return {
            "counts": counts,
            "batch_size": {
                "count": len(sizes),
                "min": int(min(sizes)) if sizes else 0,
                "max": int(max(sizes)) if sizes else 0,
                "mean": float(np.mean(sizes)) if sizes else 0.0,
            },
            "latency_s": latency_block(lats),
            "by_type": {
                kind: {"counts": row, "latency_s": latency_block(tl)}
                for kind, (row, tl) in by_type.items()
            },
        }
