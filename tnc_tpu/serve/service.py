"""Query-serving front end: one mixed queue + micro-batching dispatcher.

:class:`ContractionService` turns a :class:`~tnc_tpu.serve.rebind.
BoundProgram` into a request server. Callers submit bitstrings (from
any thread, or ``await`` the asyncio facade); a dispatcher thread
collects requests into micro-batches — up to ``max_batch`` riders or
``max_wait_ms`` after the first arrival, whichever comes first — and
issues ONE rebind dispatch per batch, the TPU-native shape for
amplitude traffic (one compiled program, B bitstrings per dispatch).

Beyond amplitudes, the queue is **mixed**: bitstring sampling, Pauli
expectation values and marginal sweeps are ``submit()``-able query
types (:meth:`~ContractionService.submit_sample` /
:meth:`~ContractionService.submit_expectation` /
:meth:`~ContractionService.submit_marginal`), each handled by a
registered :mod:`tnc_tpu.queries.handlers` handler. Every request
carries a per-type **batching key** (the marginal key includes the
wildcard mask); the dispatcher partitions each micro-batch window by
key, so a dispatched batch never mixes structures while all types
share one queue, one deadline/admission policy, and one plan cache.
Per-type counters and latency histograms ride ``stats()["by_type"]``
and the ``serve.query.*`` obs metrics.

Production posture:

- **admission control**: a bounded queue; submissions beyond
  ``max_queue`` fail fast with :class:`QueueFullError` instead of
  growing latency without bound;
- **deadlines**: each request may carry a timeout; requests that
  expire while queued are completed with
  :class:`DeadlineExceededError` at batch assembly (they never waste a
  dispatch);
- **resilience**: the batch dispatch runs under the shared
  :class:`~tnc_tpu.resilience.retry.RetryPolicy` (transient runtime
  failures retry with backoff); a batch that still fails **degrades to
  singleton requests** — each rider is re-dispatched alone, so one
  poisoned request cannot fail its co-riders;
- **observability**: ``serve.queue_depth`` gauge,
  ``serve.batch_size``/``serve.latency_s``/``serve.wait_s``
  histograms, ``serve.requests.*`` counters, plus the plan-cache
  hit/miss counters from :mod:`tnc_tpu.serve.plancache`;
- **anytime replanning**: a cache-missed structure serves from its
  fast greedy plan immediately; a
  :class:`~tnc_tpu.serve.replan.BackgroundReplanner` may later
  :meth:`~ContractionService.swap_bound` in a hyper-optimized
  :class:`BoundProgram` for the SAME structure — the dispatcher adopts
  it atomically between batches (every batch runs wholly under one
  bound, so in-flight requests are never split across plans);
- **fidelity tiers**: ``submit``/``submit_expectation``/
  ``submit_marginal`` accept ``rtol=`` (default exact). A tolerant
  request routes through the :class:`FidelityRouter` to the
  **approximate tier** — a boundary-MPS chi-ladder
  (:mod:`tnc_tpu.approx`) with its own batching key, so approx and
  exact traffic share the queue but never share a batch — and comes
  back as an :class:`ApproxAnswer` carrying ``(value, err,
  chi_used)``. A ladder that cannot meet the tolerance **escalates**
  to the exact pipeline (counted, spanned, capped); per-tier rows ride
  ``stats()["by_tier"]`` and the ``serve.tier.*`` metrics.
"""

from __future__ import annotations

import concurrent.futures
import itertools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np

from tnc_tpu import obs
from tnc_tpu.obs import fleet as _fleet
from tnc_tpu.obs.core import QuantileSummary
from tnc_tpu.ops.backends import JaxBackend
from tnc_tpu.resilience import retry as _retry
from tnc_tpu.resilience.faultinject import fault_point
from tnc_tpu.serve.rebind import (
    BoundProgram,
    bind_circuit,
    plan_signature,
    pow2_bucket,
)

logger = logging.getLogger(__name__)

#: drift-bucket granularity == executable granularity: one shared
#: power-of-two rule (rebind pads batched dispatches to it, so all
#: measurements inside a bucket ran the same compiled shape)
batch_bucket = pow2_bucket

#: the approximate tier's request kind (its batching keys are
#: ``(APPROX_KIND, base kind)`` — approx traffic never co-batches with
#: exact traffic OR across base kinds)
APPROX_KIND = "approx"


def tier_of(kind: str) -> str:
    """The fidelity tier a request kind serves from."""
    return "approx" if kind == APPROX_KIND else "exact"


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class QueueFullError(ServeError):
    """Admission control rejected the request (queue at ``max_queue``)."""


class TenantQuotaError(QueueFullError):
    """Admission control rejected the request: its tenant is at its
    per-tenant queued-request quota (elastic scheduling); subclasses
    :class:`QueueFullError` so existing backpressure handling applies."""


class DeadlineExceededError(ServeError):
    """The request's deadline passed before it could be dispatched."""


class ServiceClosedError(ServeError):
    """The service is stopped and no longer accepts requests."""


@dataclass
class _Request:
    bits: object  # the validated payload (determined bits for amplitudes)
    future: concurrent.futures.Future
    deadline: float | None  # absolute monotonic, None = no deadline
    t_submit: float = field(default_factory=time.monotonic)
    kind: str = "amplitude"
    # batching key: requests dispatch together ONLY when keys match
    # (per-type, plus structure discriminators like the marginal mask)
    key: tuple = ("amplitude",)
    # per-request trace id, assigned at admission; every serve.* span
    # that touches this request carries it, so the whole timeline
    # (queue age -> batch wait -> dispatch share) is queryable per
    # request (scripts/trace_summarize.py --serve)
    rid: int = 0
    t_collect: float = 0.0  # when batch assembly pulled it off the queue
    # elastic scheduling: weighted-fair tenant + priority class (higher
    # wins; a strictly-higher priority may preempt a running sliced
    # contraction at a checkpoint boundary)
    tenant: str = "default"
    priority: int = 0


_STATS_CAP = 4096  # bounded in-memory samples for stats()/bench


class ContractionService:
    """Micro-batching amplitude server over one bound program.

    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> c.append_gate(TensorData.gate("cx"), [reg.qubit(0), reg.qubit(1)])
    >>> with ContractionService.from_circuit(c) as svc:
    ...     amp = svc.amplitude("00")
    >>> round(abs(amp), 6)
    0.707107
    """

    def __init__(
        self,
        bound: BoundProgram,
        backend=None,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        max_queue: int = 1024,
        retry_policy: _retry.RetryPolicy | None = None,
        dispatcher=None,
        slo=None,
        cost_model=None,
    ):
        """``dispatcher``: optional batch-execution hook
        ``fn(bound, bits, backend) -> (B,)+result_shape array``
        replacing the local ``bound.amplitudes_det`` dispatch — the
        multi-host fan-out point (:class:`~tnc_tpu.serve.multihost.
        ClusterDispatcher` shards the micro-batch across host
        processes and gathers at the root). Everything else (queueing,
        deadlines, retry, degradation, plan swaps) is unchanged: the
        dispatcher is only ever called with a batch and the CURRENT
        bound, so plan swaps stay batch-atomic across the fleet.

        ``slo``: an :class:`~tnc_tpu.obs.slo.SLOEngine` (or an
        :class:`~tnc_tpu.obs.slo.SLOConfig` to build one) — every
        terminal request outcome and every dispatch measurement feeds
        it, burn/drift alerts surface in ``stats()["slo"]`` and the
        telemetry endpoint. ``cost_model``: a
        :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` giving the
        drift detector its predicted dispatch seconds (without one,
        drift tracks raw measured seconds per bucket — still a change
        signal when the engine self-baselines)."""
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.bound = bound
        self.backend = backend  # None → rebind's numpy default
        self.dispatcher = dispatcher
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        self.retry_policy = retry_policy or _retry.default_policy()
        self.cost_model = cost_model
        self._queue: deque[_Request] = deque()
        self._cond = threading.Condition()
        self._running = False
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._counts = {
            "submitted": 0, "completed": 0, "failed": 0,
            "expired": 0, "rejected": 0, "cancelled": 0,
            "batches": 0, "degraded_batches": 0, "plan_swaps": 0,
            "deduped": 0,
        }
        # observability-only references, set by from_circuit (or by the
        # owner directly): surfaced in stats() and /metrics
        self._plan_cache = None
        self.reuse_store = None
        self._batch_sizes: deque[int] = deque(maxlen=_STATS_CAP)
        # bounded streaming percentiles (p50/p90/p99 without retained
        # samples) — the SAME objects back stats() and /metrics, so the
        # two surfaces can never disagree. Cumulative since start /
        # reset_stats(): on a long-lived replica they answer "how has
        # this service served", not "how is it serving right now" — the
        # windowed view of the present is the SLO engine's burn rates
        self._latencies = QuantileSummary()
        # per-query-type breakdowns (kind -> counts / latency summary);
        # "amplitude" is pre-seeded so dashboards always see the
        # primary type even before traffic arrives
        self._by_type: dict[str, dict] = {}
        self._latencies_by_type: dict[str, QuantileSummary] = {}
        self._ensure_type("amplitude")
        # per-fidelity-tier breakdowns ("exact" pre-seeded; "approx"
        # appears when a FidelityRouter is attached): counts, latency
        # summaries, and measured dispatch seconds — the bench's
        # serving.by_tier surface
        self._by_tier: dict[str, dict] = {}
        self._latencies_by_tier: dict[str, QuantileSummary] = {}
        self._tier_dispatch: dict[str, list] = {}
        self._ensure_tier("exact")
        # registered query handlers (sampling / expectation / marginal)
        self._handlers: dict[str, object] = {}
        self._router = None  # attached FidelityRouter, if any
        # an improved BoundProgram staged by the background replanner;
        # the dispatcher adopts it at the next batch boundary
        self._pending_bound: BoundProgram | None = None
        self._replanner = None  # attached BackgroundReplanner, if any
        self._plansvc = None  # attached PlannerFleet pod, if any
        self._watchers: list = []  # attached SharedCacheWatchers
        self._rids = itertools.count(1)
        # plan-swap generation: bumps on every adopted replan/shared
        # swap; rides the dispatch spans and request timelines so a
        # latency change is attributable to the plan that served it
        self._generation = 0
        self._telemetry = None  # attached TelemetryServer, if any
        # fleet plane (attach_fleet): replica-registry membership +
        # heartbeat + the /fleet federation source
        self._fleet_registry = None
        self._fleet_heartbeat = None
        self._fleet_aggregator = None
        self._slo = None
        self._slo_last_check = 0.0
        # cost-truth plane (enable_cost_truth): production sampling,
        # drift-triggered refits, versioned model adoption, the plan
        # scoreboard and the post-swap rollback watch
        self._cost_truth = None
        # per-bound derived constants (program flops/bytes/steps, plan
        # key + signature), memoized by bound identity: computed once
        # per adopted plan, never per dispatch
        self._bound_profiles: dict[int, dict] = {}
        # elastic plane (enable_elastic): tenant/priority scheduling
        # config, advisory scale controller, preemption state (the
        # priority of the batch currently dispatching, and a recursion
        # guard so interlude work is itself never preempted)
        self._elastic = None
        self._elastic_controller = None
        self._active_priority = 0
        self._in_interlude = False
        self.attach_slo(slo)

    @classmethod
    def from_circuit(
        cls,
        circuit,
        mask=None,
        pathfinder=None,
        plan_cache=None,
        backend=None,
        target_size=None,
        reuse_store=None,
        background_replan: bool = False,
        replan_options: dict | None = None,
        shared_cache_watch: bool = False,
        watch_options: dict | None = None,
        queries: bool = False,
        approx: bool = False,
        approx_options: dict | None = None,
        telemetry_port: int | None = None,
        fleet_dir: str | None = None,
        fleet_endpoints=None,
        fleet_heartbeat_s: float = 2.0,
        cost_truth: bool = False,
        cost_truth_options: dict | None = None,
        plansvc: bool = False,
        plansvc_dir: str | None = None,
        plansvc_options: dict | None = None,
        **kwargs,
    ) -> "ContractionService":
        """Build (plan/compile once, plan cache honored) and start.

        ``telemetry_port`` (0 = ephemeral) additionally starts the live
        scrape endpoint (:meth:`serve_telemetry`): ``/metrics`` +
        ``/healthz`` + ``/slo`` (+ ``/fleet`` once the fleet plane is
        attached).

        ``fleet_dir`` / ``fleet_endpoints`` join the fleet
        observability plane (:meth:`attach_fleet`): this replica
        heartbeats into the shared registry directory every
        ``fleet_heartbeat_s`` seconds and the ``/fleet`` endpoint
        federates every replica's telemetry.

        ``queries=True`` additionally registers the sampling /
        expectation / marginal query handlers for the same circuit
        (:func:`tnc_tpu.queries.handlers.attach_query_handlers`),
        sharing ``plan_cache``/``target_size``; the circuit is copied
        before the amplitude finalizer consumes it.

        ``approx=True`` additionally attaches a :class:`FidelityRouter`
        for the same circuit (nearest-neighbour circuits only):
        ``submit*`` calls gain a working ``rtol=`` and tolerant
        requests serve from the boundary-MPS chi-ladder tier,
        escalating to the exact pipeline on a tolerance miss.
        ``approx_options`` are :meth:`enable_approx` kwargs.

        ``background_replan=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.replan.BackgroundReplanner`: a cache miss
        is answered from the fast greedy plan immediately, and the
        worker hyper-optimizes the structure between requests, swapping
        in the improved plan when its predicted cost wins.
        ``replan_options`` are its constructor kwargs.

        ``shared_cache_watch=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.replan.SharedCacheWatcher`: a replica
        deployment sharing one cache directory adopts OTHER replicas'
        published plans (including their background replanner's swaps)
        at batch boundaries. ``watch_options`` are its kwargs.

        ``cost_truth=True`` turns on the cost-truth loop
        (:meth:`enable_cost_truth`): production dispatch sampling,
        drift-triggered cost-model refits, versioned model adoption
        and the plan scoreboard + post-swap rollback watch.
        ``cost_truth_options`` are its kwargs (notably ``registry=`` —
        a shared model-registry directory for fleet-wide adoption).

        ``plansvc=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.plansvc.PlannerFleet` pod
        (:meth:`enable_plansvc`): idle windows run distributed planner
        trials against the shared trial board (``plansvc_dir``,
        defaulting to a ``plansvc/`` sibling inside the plan-cache
        directory) and the merged best publishes through the plan
        cache so every watching replica adopts it. ``plansvc_options``
        are its constructor kwargs."""
        if background_replan and plan_cache is None:
            raise ValueError("background_replan requires a plan_cache")
        if shared_cache_watch and plan_cache is None:
            raise ValueError("shared_cache_watch requires a plan_cache")
        if plansvc and plan_cache is None:
            raise ValueError("plansvc requires a plan_cache")
        query_circuit = circuit.copy() if queries else None
        approx_circuit = circuit.copy() if approx else None
        bound = bind_circuit(
            circuit, mask, pathfinder, plan_cache, target_size, reuse_store
        )
        svc = cls(bound, backend=backend, **kwargs)
        svc._plan_cache = plan_cache
        svc.reuse_store = reuse_store
        svc.start()
        try:
            if queries:
                svc.enable_queries(
                    query_circuit,
                    pathfinder=pathfinder,
                    plan_cache=plan_cache,
                    target_size=target_size,
                )
            if approx:
                svc.enable_approx(approx_circuit, **(approx_options or {}))
            if background_replan:
                from tnc_tpu.serve.replan import BackgroundReplanner

                BackgroundReplanner(
                    svc, plan_cache, **(replan_options or {})
                ).start()
            if shared_cache_watch:
                from tnc_tpu.serve.replan import SharedCacheWatcher

                watcher = SharedCacheWatcher(
                    svc, plan_cache, **(watch_options or {})
                )
                svc._watchers.append(watcher)
                watcher.start()
            if plansvc:
                svc.enable_plansvc(
                    directory=plansvc_dir, **(plansvc_options or {})
                )
            if cost_truth or cost_truth_options:
                svc.enable_cost_truth(**(cost_truth_options or {}))
            if telemetry_port is not None:
                svc.serve_telemetry(port=telemetry_port)
            if fleet_dir is not None or fleet_endpoints:
                svc.attach_fleet(
                    directory=fleet_dir,
                    endpoints=fleet_endpoints or (),
                    heartbeat_s=fleet_heartbeat_s,
                )
        except Exception:
            # a bad option kwarg must not leak a running dispatcher
            # thread (or half the attachments) the caller can't reach
            svc.stop()
            raise
        return svc

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ContractionService":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="tnc-serve-dispatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop accepting requests; by default finish ('drain') what is
        already queued, otherwise fail queued requests with
        :class:`ServiceClosedError`. An attached background replanner
        is stopped first (it must not swap into a closing service).
        The planner pod goes before even that: the replanner's
        delegate path blocks on the pod, and the pod's stop flag is
        what unblocks it."""
        pod, self._plansvc = self._plansvc, None
        if pod is not None:
            pod.stop()
        replanner, self._replanner = self._replanner, None
        if replanner is not None:
            replanner.stop()
        watchers, self._watchers = list(self._watchers), []
        for watcher in watchers:
            watcher.stop()
        heartbeat, self._fleet_heartbeat = self._fleet_heartbeat, None
        if heartbeat is not None:
            heartbeat.stop()  # retires the registry entry: clean leave
        telemetry, self._telemetry = self._telemetry, None
        if telemetry is not None:
            telemetry.stop()  # releases the port
        with self._cond:
            if not self._running:
                return
            self._running = False
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._complete(req, exc=ServiceClosedError("stopped"))
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    # -- plan swap (anytime replanning) ------------------------------------

    def swap_bound(self, bound: BoundProgram) -> None:
        """Stage an improved :class:`BoundProgram` for the SAME circuit
        structure (the background replanner's entry point). The
        dispatcher adopts it at the next batch boundary — batches are
        dispatched wholly under one bound, so no in-flight request ever
        mixes plans. Amplitude *values* are plan-independent (both
        programs contract the same network), so co-existing old-plan
        and new-plan responses are equally correct."""
        from tnc_tpu.serve.plancache import network_structure_digest

        if bound.template is not self.bound.template:
            # same structure digest (legs/dims/budget) AND same leaf
            # values: the digest is value-blind by design (all
            # bitstrings share it), but a swap with different gate
            # VALUES would silently serve another circuit's amplitudes
            if network_structure_digest(
                bound.template.network, bound.target_size
            ) != network_structure_digest(
                self.bound.template.network, self.bound.target_size
            ) or not all(
                np.array_equal(a, b)
                for a, b in zip(bound.arrays, self.bound.arrays)
            ):
                raise ValueError(
                    "swap_bound: replacement program was bound for a "
                    "different structure or different leaf values — "
                    "not a plan for this service's circuit/budget"
                )
        with self._lock:
            self._pending_bound = bound

    def _current_bound(self) -> BoundProgram:
        """The bound to dispatch the NEXT batch under, adopting any
        staged replacement (and any staged cost-model generation)
        first — the one boundary where swaps become visible, so no
        batch ever mixes plans or model versions."""
        ct = self._cost_truth
        refused = prior = None
        with self._lock:
            pending, self._pending_bound = self._pending_bound, None
            if pending is not None:
                if ct is not None and ct.is_pinned(plan_signature(pending)):
                    # a rolled-back plan staged again (shared-cache
                    # watcher, replanner re-run): the stager cannot
                    # know it regressed here — refuse, keep serving
                    refused, pending = pending, None
                else:
                    prior = self.bound
                    self.bound = pending
                    self._counts["plan_swaps"] += 1
                    self._generation += 1
        if refused is not None:
            ct.count("pin_refusals")
            obs.counter_add("serve.cost_truth.pin_refused")
            logger.warning(
                "refused adoption of a regression-pinned plan"
            )
        if pending is not None:
            obs.counter_add("serve.replan.adopted")
            logger.info("adopted replanned program for serving")
            if ct is not None:
                self._arm_swap_watch(pending, prior)
        if ct is not None:
            adopted = ct.adopt_pending()
            if adopted is not None:
                self._adopt_cost_model(*adopted)
        return self.bound

    def attach_slo(self, slo) -> "ContractionService":
        """Attach (or replace, or None-detach) the SLO engine — an
        :class:`~tnc_tpu.obs.slo.SLOEngine` or an
        :class:`~tnc_tpu.obs.slo.SLOConfig` to build one. Benchmarks
        attach AFTER their warmup, so compile-time requests never
        count against the objectives or seed the drift baselines."""
        if slo is not None and not hasattr(slo, "record_request"):
            from tnc_tpu.obs.slo import SLOEngine

            slo = SLOEngine(slo)
        self._slo = slo
        return self

    def queue_depth(self) -> int:
        """Instantaneous queue length (the replanner's idleness probe)."""
        with self._cond:
            return len(self._queue)

    # -- elastic scheduling (tenants / priority / scaling) -----------------

    def enable_elastic(
        self, config=None, controller=None
    ) -> "ContractionService":
        """Turn on elastic scheduling: ``submit(tenant=, priority=)``
        gains weighted-fair window selection and per-tenant quotas
        (``config``, an :class:`~tnc_tpu.serve.elastic.ElasticConfig`;
        default config = fair weights, no quotas), and local sliced
        dispatches become priority-preemptible at checkpoint
        boundaries. ``controller`` (an :class:`~tnc_tpu.serve.elastic.
        ElasticController`) additionally arms :meth:`elastic_check` —
        the advisory scale-decision step."""
        from tnc_tpu.serve import elastic as _elastic_mod

        self._elastic = (
            config if config is not None else _elastic_mod.ElasticConfig()
        )
        self._elastic_controller = controller
        return self

    def elastic_check(self) -> dict | None:
        """One advisory controller step: fold the current queue depth,
        the fleet roster's live count and the worst SLO burn rate into
        a scale decision (None without a controller). The decision also
        lands in ``stats()["elastic"]["controller"]`` and fans out to
        the controller's ``on_decision`` hooks — actuate it with a
        :class:`~tnc_tpu.serve.elastic.LocalAutoscaler` or external
        infrastructure."""
        ctrl = self._elastic_controller
        if ctrl is None:
            return None
        live = 1
        if self._fleet_registry is not None:
            try:
                live = max(int(self._fleet_registry.roster()["live"]), 1)
            except Exception:  # noqa: BLE001 — roster is advisory input
                pass
        burn = 0.0
        if self._slo is not None:
            burn = type(ctrl).burn_from_slo(self._slo.stats())
        return ctrl.decide(self.queue_depth(), live, burn)

    def _tenant_depths(self) -> dict[str, int]:
        """Queued requests per tenant (stats / heartbeat surface)."""
        with self._cond:
            depths: dict[str, int] = {}
            for req in self._queue:
                depths[req.tenant] = depths.get(req.tenant, 0) + 1
            return depths

    def __enter__(self) -> "ContractionService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- query handlers ----------------------------------------------------

    def register_query_handler(self, handler) -> None:
        """Register a query-type handler (``kind`` attribute +
        ``validate(payload) -> (payload, key)`` at admission +
        ``dispatch(payloads, backend) -> results`` per batch — the
        :mod:`tnc_tpu.queries.handlers` protocol). One handler per
        kind; re-registering replaces."""
        self._handlers[str(handler.kind)] = handler

    def enable_queries(
        self,
        circuit,
        pathfinder=None,
        plan_cache=None,
        target_size=None,
    ) -> "ContractionService":
        """Register the sampling / expectation / marginal handlers for
        ``circuit`` (copied, not consumed) — the query-engine
        attachment point (lazy import: :mod:`tnc_tpu.queries` depends
        on this module's package)."""
        from tnc_tpu.queries.handlers import attach_query_handlers

        attach_query_handlers(
            self, circuit,
            pathfinder=pathfinder, plan_cache=plan_cache,
            target_size=target_size,
        )
        return self

    def enable_approx(self, circuit, **options) -> "ContractionService":
        """Attach a :class:`FidelityRouter` for ``circuit`` (copied,
        not consumed; nearest-neighbour circuits only — the attach
        fails fast otherwise). ``options`` are router kwargs (``chis``,
        ``chi_cap``, ``safety``, ``max_escalations``, ``cost_model``).
        Afterwards ``submit*(..., rtol=...)`` routes to the
        approximate tier."""
        router = FidelityRouter(self, circuit, **options)
        self.register_query_handler(router)
        self._router = router
        self._ensure_tier("approx")
        return self

    def enable_plansvc(
        self, directory: str | None = None, **options
    ) -> "ContractionService":
        """Attach a :class:`~tnc_tpu.serve.plansvc.PlannerFleet` pod:
        a daemon that — only while the request queue is empty — runs
        distributed planner trials against the shared trial board
        under ``directory`` (default: a ``plansvc/`` sibling inside
        the plan-cache directory) and merges the fleet's best plan
        through the plan cache + ``swap_bound``. Requires the service
        to have been built with a plan cache. ``options`` are
        :class:`~tnc_tpu.serve.plansvc.PlannerFleet` kwargs
        (``ntrials``, ``margin``, ``sa_steps``, ``cost_model``...).
        Idempotent re-attach replaces the previous pod."""
        from tnc_tpu.serve.plansvc import PlannerFleet

        if self._plan_cache is None:
            raise ValueError("enable_plansvc requires a plan_cache")
        if self._plansvc is not None:
            self._plansvc.stop()
            self._plansvc = None
        PlannerFleet(
            self, self._plan_cache, directory=directory, **options
        ).start()
        return self

    @property
    def fidelity_router(self):
        """The attached :class:`FidelityRouter` (None = exact only)."""
        return self._router

    # -- submission --------------------------------------------------------

    def _enqueue(
        self,
        kind: str,
        key: tuple,
        payload,
        timeout_s: float | None,
        tenant: str = "default",
        priority: int = 0,
    ) -> concurrent.futures.Future:
        """Shared admission path for every query type: bounded queue,
        per-tenant quota (elastic), deadline arming, request-id
        assignment, global + per-type accounting."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        deadline = (
            time.monotonic() + float(timeout_s) if timeout_s is not None else None
        )
        tenant = str(tenant)
        with self._cond:
            if not self._running:
                self._count("rejected")
                self._count_type(kind, "rejected")
                obs.counter_add("serve.requests.rejected", reason="closed")
                self._slo_request(kind, 0.0, "rejected")
                raise ServiceClosedError("service is not running")
            if len(self._queue) >= self.max_queue:
                self._count("rejected")
                self._count_type(kind, "rejected")
                obs.counter_add("serve.requests.rejected", reason="queue_full")
                self._slo_request(kind, 0.0, "rejected")
                raise QueueFullError(
                    f"queue at max_queue={self.max_queue}; retry later"
                )
            cfg = self._elastic
            if cfg is not None and cfg.tenant_quotas:
                quota = cfg.tenant_quotas.get(tenant)
                if quota is not None and sum(
                    1 for r in self._queue if r.tenant == tenant
                ) >= int(quota):
                    self._count("rejected")
                    self._count_type(kind, "rejected")
                    obs.counter_add(
                        "serve.requests.rejected", reason="tenant_quota"
                    )
                    self._slo_request(kind, 0.0, "rejected")
                    raise TenantQuotaError(
                        f"tenant {tenant!r} at quota {quota}; retry later"
                    )
            self._queue.append(
                _Request(
                    payload, fut, deadline, kind=kind, key=key,
                    rid=next(self._rids),
                    tenant=tenant, priority=int(priority),
                )
            )
            depth = len(self._queue)
            self._cond.notify()
        self._count("submitted")
        self._count_type(kind, "submitted")
        obs.counter_add("serve.requests.submitted")
        obs.counter_add("serve.query.submitted", type=kind)
        obs.gauge_set("serve.queue_depth", depth)
        return fut

    def submit(
        self,
        bitstring: str | Iterable,
        timeout_s: float | None = None,
        rtol: float | None = None,
        tenant: str = "default",
        priority: int = 0,
    ) -> concurrent.futures.Future:
        """Enqueue one amplitude request; returns a ``Future`` resolving
        to the amplitude (complex scalar, or an ndarray over the
        template's open legs). ``timeout_s`` arms a deadline.

        ``rtol`` (default None = exact) routes the request to the
        approximate tier: the future resolves to an
        :class:`ApproxAnswer` whose error estimate meets
        ``rtol · max(|value|, 2^(-n/2))`` — or, when the chi-ladder
        cannot meet it, to the escalated exact answer.

        ``tenant`` / ``priority`` engage the elastic scheduler
        (:meth:`enable_elastic`): tenants share the window
        weighted-fair under per-tenant quotas, and a strictly-higher
        ``priority`` jumps the queue — preempting a running sliced
        contraction at its next checkpoint boundary."""
        if rtol is not None:
            return self._submit_approx("amplitude", bitstring, rtol, timeout_s)
        # validate at admission: a malformed request must fail alone,
        # immediately — not poison a whole batch at dispatch time. The
        # determined-position bits (not the raw object) are what gets
        # queued: a one-shot iterable is consumed by this validation,
        # and dispatch never re-validates
        bitstring = self.bound.template.request_bits(bitstring)
        return self._enqueue(
            "amplitude", ("amplitude",), bitstring, timeout_s,
            tenant=tenant, priority=priority,
        )

    def _submit_approx(
        self, base: str, payload, rtol, timeout_s: float | None
    ) -> concurrent.futures.Future:
        """Route a tolerant request to the approximate tier (its own
        batching key per base kind — approx work never co-batches with
        exact work)."""
        router = self._handlers.get(APPROX_KIND)
        if router is None:
            raise ValueError(
                "rtol= routes to the approximate tier; attach it first "
                "(from_circuit(approx=True) / enable_approx)"
            )
        payload, key = router.validate(
            {"kind": base, "payload": payload, "rtol": rtol}
        )
        return self._enqueue(APPROX_KIND, tuple(key), payload, timeout_s)

    def submit_query(
        self, kind: str, payload, timeout_s: float | None = None,
        tenant: str = "default", priority: int = 0,
    ) -> concurrent.futures.Future:
        """Enqueue one typed query request through its registered
        handler; the handler validates the payload at admission and
        assigns the batching key."""
        handler = self._handlers.get(kind)
        if handler is None:
            raise ValueError(
                f"no handler registered for query kind {kind!r} "
                "(enable_queries / register_query_handler first)"
            )
        payload, key = handler.validate(payload)
        return self._enqueue(
            kind, tuple(key), payload, timeout_s,
            tenant=tenant, priority=priority,
        )

    def submit_sample(
        self,
        n_samples: int = 1,
        seed=None,
        timeout_s: float | None = None,
    ) -> concurrent.futures.Future:
        """Sample ``n_samples`` bitstrings from |⟨b|C|0⟩|² (chain-rule
        sampler); the future resolves to a list of bitstrings. A seeded
        request's stream is deterministic regardless of co-riders."""
        return self.submit_query(
            "sample", {"n_samples": n_samples, "seed": seed}, timeout_s
        )

    def submit_expectation(
        self, terms, timeout_s: float | None = None,
        rtol: float | None = None,
    ) -> concurrent.futures.Future:
        """⟨ψ|P|ψ⟩ (a Pauli string) or a Pauli sum (iterable of
        ``(coeff, pauli)``); the future resolves to the complex
        value. Terms batch through one sandwich structure. ``rtol``
        routes to the approximate tier (an :class:`ApproxAnswer`)."""
        if rtol is not None:
            return self._submit_approx("expectation", terms, rtol, timeout_s)
        return self.submit_query("expectation", terms, timeout_s)

    def submit_marginal(
        self, pattern, timeout_s: float | None = None,
        rtol: float | None = None,
    ) -> concurrent.futures.Future:
        """Marginal probability of ``pattern``'s determined bits
        (``'*'`` = marginalized); the future resolves to a float.
        ``rtol`` routes to the approximate tier (an
        :class:`ApproxAnswer`)."""
        if rtol is not None:
            return self._submit_approx("marginal", pattern, rtol, timeout_s)
        return self.submit_query("marginal", pattern, timeout_s)

    def sample(self, n_samples: int = 1, seed=None,
               timeout_s: float | None = None) -> list:
        """Blocking :meth:`submit_sample`."""
        return self.submit_sample(n_samples, seed, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def expectation(
        self, terms, timeout_s: float | None = None,
        rtol: float | None = None,
    ) -> complex:
        """Blocking :meth:`submit_expectation`."""
        return self.submit_expectation(terms, timeout_s, rtol=rtol).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def marginal(
        self, pattern, timeout_s: float | None = None,
        rtol: float | None = None,
    ) -> float:
        """Blocking :meth:`submit_marginal`."""
        return self.submit_marginal(pattern, timeout_s, rtol=rtol).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def amplitude(
        self, bitstring, timeout_s: float | None = None,
        rtol: float | None = None,
    ):
        """Blocking single-amplitude query (deadline doubles as the
        caller-side wait bound)."""
        fut = self.submit(bitstring, timeout_s, rtol=rtol)
        return fut.result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    async def amplitude_async(self, bitstring, timeout_s: float | None = None):
        """Asyncio facade: ``await service.amplitude_async("0101")``."""
        import asyncio

        return await asyncio.wrap_future(self.submit(bitstring, timeout_s))

    # -- dispatcher --------------------------------------------------------

    def _collect_batch(self) -> list[_Request] | None:
        """Block for the first request, then hold the window open up to
        ``max_wait_s`` (or until ``max_batch`` riders); None = stopped
        and drained."""
        with self._cond:
            while not self._queue:
                if not self._running:
                    return None
                self._cond.wait(timeout=0.1)
            t0 = time.monotonic()
            deadline = t0 + self.max_wait_s
            while (
                len(self._queue) < self.max_batch
                and time.monotonic() < deadline
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    break
            cfg = self._elastic
            if cfg is not None and len(self._queue) > 1:
                # elastic window selection: priority classes first,
                # weighted-fair across tenants within a class, FIFO
                # within a tenant (stride scheduling — see elastic.py)
                from tnc_tpu.serve import elastic as _elastic_mod

                items = list(self._queue)
                order = _elastic_mod.weighted_fair_order(
                    items,
                    lambda r: r.tenant,
                    lambda r: r.priority,
                    weights=cfg.tenant_weights,
                )
                picked = order[: self.max_batch]
                taken = set(picked)
                batch = [items[i] for i in picked]
                self._queue = deque(
                    items[i] for i in range(len(items)) if i not in taken
                )
            else:
                batch = [
                    self._queue.popleft()
                    for _ in range(min(self.max_batch, len(self._queue)))
                ]
            obs.gauge_set("serve.queue_depth", len(self._queue))
            return batch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            except Exception as exc:  # noqa: BLE001 — the dispatcher must survive
                # _run_batch handles dispatch failures itself; anything
                # reaching here is a bookkeeping bug — fail the batch,
                # keep serving
                logger.exception("dispatcher batch processing failed")
                for req in batch:
                    if not self._complete(
                        req, exc=ServeError(f"dispatcher error: {exc}")
                    ):
                        continue  # cancelled: _complete counted it
                    self._count("failed")
                    self._count_type(req.kind, "failed")
                    obs.counter_add("serve.requests.failed")
                    obs.counter_add("serve.query.failed", type=req.kind)
                    self._slo_request(
                        req.kind, time.monotonic() - req.t_submit, "failed"
                    )
                    self._trace_request(req, "failed")

    def _complete(self, req: _Request, result=None, exc=None) -> bool:
        """Resolve a request's future, tolerating caller-side
        cancellation (``fut.cancel()`` / an abandoned asyncio await):
        completing a cancelled future raises ``InvalidStateError``,
        which must never kill the dispatcher thread."""
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)
            return True
        except concurrent.futures.InvalidStateError:
            self._count("cancelled")
            self._count_type(req.kind, "cancelled")
            obs.counter_add("serve.requests.cancelled")
            obs.counter_add("serve.query.cancelled", type=req.kind)
            self._slo_request(
                req.kind, time.monotonic() - req.t_submit, "cancelled"
            )
            self._trace_request(req, "cancelled")
            return False

    def _dispatch_amps(self, bound: BoundProgram, bits: list) -> np.ndarray:
        """One batch execution under ``bound`` — locally, or through the
        pluggable ``dispatcher`` (multi-host fan-out). With elastic
        scheduling enabled, local sliced dispatches run preemptibly: a
        strictly-higher-priority arrival forces a checkpoint save at
        the next slice boundary, the priority work runs in the
        interlude, and the contraction resumes bit-identically."""
        if self.dispatcher is not None:
            return self.dispatcher(bound, bits, self.backend)
        cfg = self._elastic
        if cfg is not None and cfg.preempt_enabled and not self._in_interlude:
            from tnc_tpu.serve import elastic as _elastic_mod

            return _elastic_mod.preemptible_amplitudes(
                bound, bits, self.backend,
                ckpt=cfg.ckpt_dir,
                should_yield=self._should_preempt,
                interlude=self._priority_interlude,
                max_yields=cfg.max_yields,
            )
        return bound.amplitudes_det(bits, self.backend)

    def _should_preempt(self, cursor: int) -> bool:
        """The ``on_slice`` gate: yield when any queued request outranks
        the batch currently dispatching (never from inside an
        interlude — priority work itself runs to completion)."""
        if self._in_interlude:
            return False
        prio = self._active_priority
        with self._cond:
            return any(req.priority > prio for req in self._queue)

    def _priority_interlude(self) -> None:
        """Runs between a preempted contraction's yield and its resume:
        pull every request outranking the preempted batch off the queue
        and serve them as a nested batch (same plumbing — grouping,
        retry, degrade, accounting — under a recursion guard so the
        interlude is itself never preempted)."""
        prio = self._active_priority
        with self._cond:
            higher = [req for req in self._queue if req.priority > prio]
            for req in higher:
                self._queue.remove(req)
            if higher:
                obs.gauge_set("serve.queue_depth", len(self._queue))
        if not higher:
            return
        self._in_interlude = True
        try:
            self._run_batch(higher)
        finally:
            self._in_interlude = False
            self._active_priority = prio

    def _per_request(self, amps: np.ndarray, i: int):
        out = amps[i]
        # copy, not view: co-riders must never alias one mutable batch
        # buffer (an in-place edit by one caller would corrupt another's
        # already-delivered result)
        return complex(out) if out.shape == () else np.array(out)

    def _dispatch_group(
        self, kind: str, payloads: list, bound: BoundProgram
    ) -> list:
        """One batched execution of a same-key group; returns one
        result object per payload."""
        # injectable boundary (TNC_TPU_FAULTS): the SLO smoke scripts a
        # `slow` rule here to trip burn/drift alerts deterministically,
        # and raising kinds exercise the retry->degrade ladder exactly
        # where production dispatch failures surface
        fault_point("serve.dispatch", kind=kind, batch=len(payloads))
        if kind == "amplitude":
            amps = self._dispatch_amps(bound, payloads)
            return [
                self._per_request(amps, i) for i in range(len(payloads))
            ]
        return self._handlers[kind].dispatch(payloads, self.backend)

    def _run_batch(self, batch: list[_Request]) -> None:
        now = time.monotonic()
        live: list[_Request] = []
        for req in batch:
            # every request was just pulled off the queue at `now` —
            # set it on the expired branch too, or an expired request's
            # timeline would report its whole queue wait as batch_wait
            req.t_collect = now
            if req.deadline is not None and now > req.deadline:
                # complete FIRST: a caller-cancelled future takes the
                # cancelled outcome inside _complete, and exactly one
                # terminal outcome may count per request
                if self._complete(
                    req,
                    exc=DeadlineExceededError(
                        f"deadline exceeded after "
                        f"{now - req.t_submit:.3f}s in queue"
                    ),
                ):
                    self._count("expired")
                    self._count_type(req.kind, "expired")
                    obs.counter_add("serve.requests.expired")
                    self._slo_request(req.kind, now - req.t_submit, "expired")
                    self._trace_request(req, "expired")
            else:
                live.append(req)
        if not live:
            self._slo_check()
            return
        for req in live:
            obs.observe("serve.wait_s", now - req.t_submit)
        # one bound per window: adopt a staged replan at this boundary,
        # then every group of the window (including singleton-degrade
        # re-dispatches) runs under the SAME program
        bound = self._current_bound()
        # partition the window by batching key (insertion order): one
        # dispatch per key — a batch never mixes query types or
        # structures, while all types share the queue and the window
        groups: dict[tuple, list[_Request]] = {}
        for req in live:
            groups.setdefault(req.key, []).append(req)
        for group in groups.values():
            self._run_group(group, bound)
        self._slo_check()

    def _run_group(
        self, group: list[_Request], bound: BoundProgram
    ) -> None:
        kind = group[0].kind
        # the running batch's priority class — what the preemption gate
        # compares queued arrivals against (single dispatcher thread;
        # interludes save/restore around their nested batch)
        self._active_priority = max(req.priority for req in group)
        self._count("batches")
        self._count_type(kind, "batches")
        with self._lock:
            self._batch_sizes.append(len(group))
            generation = self._generation
        obs.observe("serve.batch_size", len(group))
        obs.observe("serve.query.batch_size", len(group), type=kind)
        payloads = [req.bits for req in group]
        # queue-level dedup: identical riders inside one batch window
        # collapse to a single dispatch entry, the result fanned out
        # (copied) to every future. Deterministic kinds only —
        # amplitudes always, query handlers that opt in via
        # `dedup_payloads` (sampling is stochastic and never collapses)
        fan = None
        handler = self._handlers.get(kind)
        if len(group) > 1 and (
            kind == "amplitude" or getattr(handler, "dedup_payloads", False)
        ):
            try:
                index_of: dict = {}
                fan = [index_of.setdefault(p, len(index_of)) for p in payloads]
            except TypeError:  # unhashable payload shape: no dedup
                fan = None
            else:
                if len(index_of) == len(payloads):
                    fan = None
                else:
                    unique: list = [None] * len(index_of)
                    for p, j in index_of.items():
                        unique[j] = p
                    collapsed = len(payloads) - len(unique)
                    payloads = unique
                    with self._lock:
                        self._counts["deduped"] += collapsed
                    obs.counter_add(
                        "serve.reuse.dedup", float(collapsed), kind=kind
                    )
        riders = ",".join(f"r{req.rid}" for req in group)
        t0 = time.monotonic()
        try:
            # the batch-level span carries the rider id list so the
            # trace rollup can attribute shared batch time back to
            # request ids and query types; the thread-local dispatch
            # context carries the same identity to the pluggable
            # dispatcher (whose signature has no rids) so a
            # ClusterDispatcher can ship it to every worker's spans
            with _fleet.dispatch_context(
                riders=riders, kind=kind, generation=generation
            ), obs.span(
                "serve.dispatch",
                batch=len(group), kind=kind, riders=riders,
                generation=generation,
                collapsed=len(group) - len(payloads),
                **self._span_model(),
            ):
                results = self.retry_policy.run(
                    lambda: self._dispatch_group(kind, payloads, bound),
                    label="serve.dispatch",
                )
            if fan is not None:
                # copies per rider: co-riders of one collapsed payload
                # must never alias one mutable result object
                results = [
                    np.array(r) if isinstance(r, np.ndarray) else r
                    for r in (results[j] for j in fan)
                ]
        except Exception as exc:  # noqa: BLE001 — degrade to singletons
            logger.warning(
                "%s batch of %d failed (%s: %s); degrading to singleton "
                "requests", kind, len(group), type(exc).__name__, exc,
            )
            self._count("degraded_batches")
            obs.counter_add("serve.batch_degraded")
            self._run_singletons(group, bound)
            return
        done = time.monotonic()
        dispatch_s = done - t0
        self._note_dispatch(kind, dispatch_s)
        self._slo_dispatch(kind, len(group), dispatch_s, bound)
        self._cost_truth_dispatch(kind, len(group), dispatch_s, bound)
        for req, result in zip(group, results):
            if self._complete(req, result=result):
                self._finish(
                    req, done, dispatch_s=dispatch_s,
                    riders=len(group), generation=generation,
                )

    def _run_singletons(self, batch: list[_Request], bound=None) -> None:
        """Degraded mode: each rider re-dispatched alone — one bad
        request (or a transient that outlived its retries) fails only
        itself. ``bound`` pins the batch's program across the
        re-dispatches (a mid-degrade plan swap must not split a
        batch)."""
        if bound is None:
            bound = self.bound
        with self._lock:
            generation = self._generation
        for req in batch:
            self._active_priority = req.priority
            t0 = time.monotonic()
            try:
                with _fleet.dispatch_context(
                    riders=f"r{req.rid}", kind=req.kind,
                    generation=generation,
                ), obs.span(
                    "serve.dispatch",
                    batch=1, kind=req.kind, riders=f"r{req.rid}",
                    generation=generation, degraded=1,
                    **self._span_model(),
                ):
                    results = self._dispatch_group(req.kind, [req.bits], bound)
            except Exception as exc:  # noqa: BLE001 — per-request verdict
                if self._complete(req, exc=exc):
                    self._count("failed")
                    self._count_type(req.kind, "failed")
                    obs.counter_add("serve.requests.failed")
                    obs.counter_add("serve.query.failed", type=req.kind)
                    self._slo_request(
                        req.kind, time.monotonic() - req.t_submit, "failed"
                    )
                    self._trace_request(req, "failed", degraded=True)
                continue
            done = time.monotonic()
            self._note_dispatch(req.kind, done - t0)
            self._slo_dispatch(req.kind, 1, done - t0, bound)
            self._cost_truth_dispatch(req.kind, 1, done - t0, bound)
            if self._complete(req, result=results[0]):
                self._finish(
                    req, done, dispatch_s=done - t0, riders=1,
                    generation=generation, degraded=True,
                )

    def _finish(
        self,
        req: _Request,
        done: float,
        dispatch_s: float = 0.0,
        riders: int = 1,
        generation: int = 0,
        degraded: bool = False,
    ) -> None:
        self._count("completed")
        self._count_type(req.kind, "completed")
        obs.counter_add("serve.requests.completed")
        obs.counter_add("serve.query.completed", type=req.kind)
        latency = done - req.t_submit
        tier = tier_of(req.kind)
        with self._lock:
            self._latencies.observe(latency)
            self._latencies_by_type[req.kind].observe(latency)
            self._ensure_tier(tier)
            self._latencies_by_tier[tier].observe(latency)
        obs.observe("serve.latency_s", latency)
        obs.observe("serve.query.latency_s", latency, type=req.kind)
        obs.observe("serve.tier.latency_s", latency, tier=tier)
        timeline = None
        if self._slo is not None or obs.enabled():
            timeline = self._timeline(
                req, "completed", latency, dispatch_s, riders, generation,
                degraded,
            )
        if self._slo is not None:
            self._slo_request(
                req.kind, latency, "completed", timeline=timeline
            )
        self._trace_request(req, "completed", timeline=timeline)

    # -- per-request timeline + SLO plumbing -------------------------------

    def _timeline(
        self, req: _Request, outcome: str, latency: float,
        dispatch_s: float = 0.0, riders: int = 1, generation: int = 0,
        degraded: bool = False,
    ) -> dict:
        """Plain-data per-request trace record: where this request's
        latency went (queue age -> batch wait -> its share of a
        ``riders``-wide dispatch) plus the serving context (plan-cache
        provenance, replan-swap generation)."""
        t_collect = req.t_collect or req.t_submit
        return {
            "rid": f"r{req.rid}",
            "type": req.kind,
            "outcome": outcome,
            "latency_s": round(latency, 6),
            "queue_age_s": round(max(t_collect - req.t_submit, 0.0), 6),
            "batch_wait_s": round(
                max(latency - (t_collect - req.t_submit) - dispatch_s, 0.0), 6
            ),
            "dispatch_s": round(dispatch_s, 6),
            "riders": riders,
            "generation": generation,
            "degraded": degraded,
            "plan_cached": bool(self.bound.plan),
        }

    def _trace_request(
        self, req: _Request, outcome: str, timeline: dict | None = None,
        **extra,
    ) -> None:
        """Emit the request's terminal ``serve.request`` span (duration
        ~0; the timeline lives in the args) so an exported trace can be
        rolled up per request id and query type
        (``scripts/trace_summarize.py --serve``). A caller that already
        built the timeline (``_finish``) passes it in."""
        if not obs.enabled():
            return
        if timeline is None:
            latency = extra.pop("latency", time.monotonic() - req.t_submit)
            timeline = self._timeline(
                req, outcome, latency,
                extra.pop("dispatch_s", 0.0), extra.pop("riders", 1),
                extra.pop("generation", 0), extra.pop("degraded", False),
            )
        with obs.span("serve.request", **timeline):
            pass

    def _slo_request(
        self, kind: str, latency: float, outcome: str, timeline=None
    ) -> None:
        if self._slo is not None:
            self._slo.record_request(
                kind, latency, outcome, timeline=timeline
            )

    def _slo_dispatch(
        self, kind: str, batch: int, measured_s: float, bound: BoundProgram
    ) -> None:
        """Feed the drift detector one dispatch observation, bucketed by
        query type x power-of-two batch size (the executor-bucket
        granularity at which measured seconds are comparable). Kinds
        whose handler declares ``drift_stable = False`` (work varies
        with payload, not batch size — sampling's n_samples,
        expectation's unique-term count) are excluded: their measured
        seconds per bucket are not comparable, and feeding them would
        manufacture drift out of workload mix."""
        if self._slo is None:
            return
        bucket = f"{kind}/b{batch_bucket(batch)}"
        handler = self._handlers.get(kind)
        if handler is not None and not getattr(handler, "drift_stable", True):
            # excluded, but COUNTED: the /slo surface must show how
            # much traffic the detector deliberately never sees, or
            # "no drift buckets" is indistinguishable from "no traffic"
            exclude = getattr(self._slo, "record_dispatch_excluded", None)
            if exclude is not None:
                exclude(bucket)
            return
        self._slo.record_dispatch(
            bucket, self._predict_dispatch_s(kind, bound), measured_s
        )

    def _predict_dispatch_s(self, kind: str, bound: BoundProgram):
        """Calibrated prediction for one dispatch of ``kind`` under
        ``bound`` (None without a cost model, or for handler query
        types whose flops the service cannot see)."""
        if self.cost_model is None or kind != "amplitude":
            return None
        try:
            prof = self._bound_profile(bound)
            return self.cost_model.op_seconds(
                prof["flops"], dispatches=prof["steps"]
            )
        except Exception:  # noqa: BLE001 — prediction is best-effort
            return None

    #: minimum seconds between dispatcher-thread SLO evaluations — the
    #: burn windows are seconds-to-hours, so sub-batch freshness buys
    #: nothing and the evaluation must stay off the per-batch hot path
    _SLO_CHECK_INTERVAL_S = 0.2

    def _slo_check(self) -> None:
        if self._slo is None:
            return
        now = time.monotonic()
        if now - self._slo_last_check < self._SLO_CHECK_INTERVAL_S:
            return
        self._slo_last_check = now
        alerts = self._slo.check()
        if self._cost_truth is not None and any(
            a.get("kind") == "drift" for a in alerts
        ):
            # the drift alert IS the refit trigger: reality diverged
            # from the model, so re-learn the constants from sampled
            # production traffic instead of waiting for a human (the
            # refit's own cooldown/hysteresis bounds the reaction)
            self._cost_truth.maybe_refit(trigger="drift")

    # -- cost-truth loop (production calibration) --------------------------

    def enable_cost_truth(
        self,
        registry=None,
        config=None,
        watch: bool = True,
        poll_interval_s: float = 0.25,
    ) -> "ContractionService":
        """Turn on the cost-truth loop: amplitude dispatches are
        reservoir-sampled by (kind × batch bucket), a drift alert
        triggers a hysteresis-bounded refit of the
        ``time ≈ flops/F + bytes/B + c`` model, accepted fits are
        published as versioned generations, and every pricing surface
        (drift predictions, replanner objective, router quotes) adopts
        a generation only at batch boundaries. A plan scoreboard keyed
        by plan-cache key records measured vs predicted dispatch
        seconds; a freshly swapped plan that measures worse than the
        incumbent's baseline beyond tolerance auto-rolls back
        (:mod:`tnc_tpu.obs.cost_truth`).

        ``registry`` — a :class:`~tnc_tpu.obs.cost_truth.ModelRegistry`
        or a directory path for one; replicas sharing the directory
        converge on one model generation (``watch=True`` polls it every
        ``poll_interval_s`` seconds, the ``SharedCacheWatcher`` path).
        Without a registry, versions are in-process only. ``config`` —
        a :class:`~tnc_tpu.obs.cost_truth.CostTruthConfig`. The whole
        plane is suppressible with ``TNC_TPU_COST_TRUTH=0``."""
        from tnc_tpu.obs import cost_truth as _ct

        cfg = _ct.config_from_env(config)
        if registry is not None and not isinstance(
            registry, _ct.ModelRegistry
        ):
            registry = _ct.ModelRegistry(registry)
        ct = _ct.CostTruth(cfg, model=self.cost_model, registry=registry)
        self._cost_truth = ct
        if ct.model is not None and ct.model is not self.cost_model:
            # the registry's current generation outranks the
            # constructor's offline constants: the fleet's source of
            # truth prices this replica from the first dispatch
            self._adopt_cost_model(ct.model_version, ct.model)
        elif ct.model_version:
            _fleet.set_flight_annotation(model_version=ct.model_version)
        if watch and registry is not None and cfg.enabled:
            watcher = _ct.ModelRegistryWatcher(
                self, registry, poll_interval_s=poll_interval_s
            )
            self._watchers.append(watcher)
            watcher.start()
        return self

    def _bound_profile(self, bound: BoundProgram) -> dict:
        """Derived per-bound constants (program flops/bytes/step count,
        plan-cache key, plan signature, scoreboard key), memoized by
        bound identity so the hot path never recomputes them per
        dispatch. Safe from any thread (atomic dict ops; a lost race
        costs one recompute)."""
        prof = self._bound_profiles.get(id(bound))
        if prof is not None and prof["bound"] is bound:
            return prof
        from tnc_tpu.ops.program import steps_bytes, steps_flops
        from tnc_tpu.serve.plancache import network_structure_digest

        steps = bound.program.steps
        cache_key = network_structure_digest(
            bound.template.network, bound.target_size
        )
        sig = plan_signature(bound)
        prof = {
            "bound": bound,
            "flops": float(steps_flops(steps)),
            "bytes": float(steps_bytes(steps)),
            "steps": max(len(steps), 1),
            "cache_key": cache_key,
            "sig": sig,
            # scoreboard rows are per PLAN: the cache key names the
            # structure, the signature the specific plan serving it —
            # so an adopted swap scores separately from its incumbent
            "score_key": f"{cache_key}:{sig[:12]}",
        }
        if len(self._bound_profiles) >= 8:
            self._bound_profiles.clear()
        self._bound_profiles[id(bound)] = prof
        return prof

    def _cost_truth_dispatch(
        self, kind: str, batch: int, dispatch_s: float, bound: BoundProgram
    ) -> None:
        """Feed the cost-truth plane one measured dispatch (sampler +
        scoreboard + the post-swap regression watch), and restage the
        prior plan when the watch's verdict is a regression. Amplitude
        dispatches only — the one kind whose program flops the service
        can see, the same reason ``_predict_dispatch_s`` is
        amplitude-only."""
        ct = self._cost_truth
        if ct is None or kind != "amplitude":
            return
        try:
            prof = self._bound_profile(bound)
        except Exception:  # noqa: BLE001 — observability must not fail serving
            return
        verdict = ct.observe_dispatch(
            kind, batch, dispatch_s,
            flops=prof["flops"], nbytes=prof["bytes"], steps=prof["steps"],
            plan_key=prof["score_key"],
            predicted_s=self._predict_dispatch_s(kind, bound),
        )
        if verdict == "rollback":
            self._rollback_plan(prof)

    def _rollback_plan(self, prof: dict) -> None:
        """Auto-rollback: the adopted plan's measured cost regressed
        beyond tolerance inside its watch window — restage the prior
        bound (adopted at the next batch boundary, like any swap) and
        pin the regressed plan's signature against re-adoption."""
        ct = self._cost_truth
        prior = ct.take_rollback()
        if prior is None:
            return
        with self._lock:
            self._pending_bound = prior
        obs.counter_add("serve.cost_truth.rollback")
        # rollbacks are incidents, not bookkeeping: ride the same alert
        # counter family the SLO engine fires so dashboards see them
        obs.counter_add("slo.alerts", kind="plan_rollback")
        logger.warning(
            "plan %s rolled back: measured dispatch seconds regressed "
            "past %.2fx its pre-swap baseline (%s)",
            prof["score_key"][:20], ct.config.rollback_tolerance,
            ct.last_rollback,
        )

    def _arm_swap_watch(
        self, new_bound: BoundProgram, prior_bound: BoundProgram | None
    ) -> None:
        """Start the regression watch for a just-adopted plan swap. The
        baseline is the incumbent's MEASURED seconds when the
        scoreboard is warm, its calibrated prediction otherwise; with
        neither the swap is unwatchable and simply trusted."""
        ct = self._cost_truth
        if ct is None or prior_bound is None:
            return
        try:
            prior_prof = self._bound_profile(prior_bound)
            new_prof = self._bound_profile(new_bound)
        except Exception:  # noqa: BLE001 — watch arming is best-effort
            return
        baseline = ct.scoreboard.measured_seconds(
            prior_prof["score_key"],
            min_samples=ct.config.scoreboard_min_samples,
        )
        if baseline is None and self.cost_model is not None:
            baseline = self.cost_model.op_seconds(
                prior_prof["flops"], dispatches=prior_prof["steps"]
            )
        if ct.arm_swap_watch(
            new_prof["score_key"], prior_bound, new_prof["sig"], baseline
        ):
            obs.counter_add("serve.cost_truth.swap_watch")

    def _adopt_cost_model(self, version: int, model) -> None:
        """A staged model generation becomes the one every pricing
        surface reads: the service's own drift predictions and quotes,
        the FidelityRouter's rung pricing, the background replanner's
        seconds objective — one auditable generation, adopted at a
        batch boundary, stamped on spans and flight recordings."""
        self.cost_model = model
        if self._router is not None:
            self._router.cost_model = model
        replanner = self._replanner
        if replanner is not None:
            adopt = getattr(replanner, "adopt_cost_model", None)
            if adopt is not None:
                adopt(model)
        _fleet.set_flight_annotation(model_version=version)
        obs.counter_add("serve.cost_truth.model_adopted")
        logger.info(
            "adopted cost-model generation v%d (%.3e flops/s, "
            "%.1e s/dispatch)", version, model.flops_per_s,
            model.dispatch_s,
        )

    def measured_plan_seconds(self) -> float | None:
        """Measured mean dispatch seconds for the CURRENT serving plan,
        from the scoreboard (None while cold or without cost-truth) —
        the replanner's measured-incumbent margin input."""
        ct = self._cost_truth
        if ct is None:
            return None
        try:
            prof = self._bound_profile(self.bound)
        except Exception:  # noqa: BLE001 — pricing input is best-effort
            return None
        return ct.scoreboard.measured_seconds(
            prof["score_key"], min_samples=ct.config.scoreboard_min_samples
        )

    def _span_model(self) -> dict:
        """Span kwargs stamping the active model generation (empty
        without cost-truth, so existing span shapes are unchanged)."""
        ct = self._cost_truth
        return {} if ct is None else {"model_version": ct.model_version}

    # -- stats -------------------------------------------------------------

    # every terminal outcome increments its per-type row — deadline
    # expiry, queue rejection and caller-side cancellation included
    # (audited per outcome by tests/test_serve.py)
    _TYPE_KEYS = (
        "submitted", "completed", "failed", "expired", "rejected",
        "cancelled", "batches",
    )

    # per-tier rows additionally audit the escalation ladder
    _TIER_KEYS = _TYPE_KEYS + ("escalated", "escalation_capped")

    def _ensure_type(self, kind: str) -> dict:
        """Per-type accounting row (callers hold no lock; dict writes
        are guarded by ``_lock`` in the callers that mutate)."""
        row = self._by_type.get(kind)
        if row is None:
            row = {k: 0 for k in self._TYPE_KEYS}
            self._by_type[kind] = row
            self._latencies_by_type[kind] = QuantileSummary()
        return row

    def _ensure_tier(self, tier: str) -> dict:
        row = self._by_tier.get(tier)
        if row is None:
            row = {k: 0 for k in self._TIER_KEYS}
            self._by_tier[tier] = row
            self._latencies_by_tier[tier] = QuantileSummary()
            self._tier_dispatch[tier] = [0, 0.0]  # dispatches, seconds
        return row

    def _count(self, key: str) -> None:
        with self._lock:
            self._counts[key] += 1

    def _count_type(self, kind: str, key: str) -> None:
        tier = tier_of(kind)
        with self._lock:
            self._ensure_type(kind)[key] += 1
            self._ensure_tier(tier)[key] += 1
        obs.counter_add(f"serve.tier.{key}", tier=tier)

    def _note_dispatch(self, kind: str, dispatch_s: float) -> None:
        """Measured dispatch seconds, accumulated per tier — next to
        the router's predicted rung seconds this is the bench's
        predicted-vs-measured per-tier surface."""
        tier = tier_of(kind)
        with self._lock:
            row = self._tier_dispatch[tier]
            row[0] += 1
            row[1] += dispatch_s
        obs.observe("serve.tier.dispatch_s", dispatch_s, tier=tier)

    def note_escalation(self, base: str, capped: bool = False) -> None:
        """The router's escalation audit hook: counted per tier and as
        ``serve.tier.escalated`` / ``serve.tier.escalation_capped``
        (``capped`` = the escalation budget was exhausted and the
        approx answer was served with ``tolerance_met=False``)."""
        key = "escalation_capped" if capped else "escalated"
        with self._lock:
            self._ensure_tier("approx")[key] += 1
        # tier= keeps the serve.tier.* namespace queryable by its
        # established label; kind= adds the per-base-kind breakdown
        obs.counter_add(f"serve.tier.{key}", tier="approx", kind=base)

    def reset_stats(self) -> None:
        """Zero the in-memory counts and samples — benchmarks call this
        after their warmup so compile-time requests never skew the
        published batch-size/latency distribution."""
        with self._lock:
            for key in self._counts:
                self._counts[key] = 0
            self._batch_sizes.clear()
            self._latencies = QuantileSummary()
            for kind, row in self._by_type.items():
                for key in row:
                    row[key] = 0
                self._latencies_by_type[kind] = QuantileSummary()
            for tier, row in self._by_tier.items():
                for key in row:
                    row[key] = 0
                self._latencies_by_tier[tier] = QuantileSummary()
                self._tier_dispatch[tier] = [0, 0.0]
        # the router's escalation audit (and its max_escalations
        # budget) covers the same measurement window as the tier rows
        # — resetting one without the other would leave the two
        # escalation numbers in stats() contradicting each other
        if self._router is not None:
            self._router.reset()

    @staticmethod
    def _latency_block(summary: QuantileSummary) -> dict:
        """Percentile block from a streaming summary — the ONE source
        both ``stats()`` and the ``/metrics`` rendering read, so the
        two surfaces report identical numbers."""
        return {
            "count": summary.count,
            "p50": round(summary.quantile(0.5), 6),
            "p90": round(summary.quantile(0.9), 6),
            "p99": round(summary.quantile(0.99), 6),
            "max": round(summary.max, 6),
        }

    def stats(self) -> dict:
        """Snapshot for dashboards and ``bench.py --serve``: request
        counts, batch-size distribution, latency percentiles, the
        per-query-type breakdown (``by_type``: one row per kind with
        request/batch counts and latency percentiles), the
        per-fidelity-tier breakdown (``by_tier``: exact vs approx
        counts — escalations included — latency percentiles, and
        measured dispatch seconds), and — with an SLO engine attached
        — the ``slo`` block (burn rates, drift, firing alerts)."""
        # percentile blocks are computed UNDER the lock: the summaries
        # are live objects the dispatcher observes into, and a block
        # must be internally consistent (count vs quantiles)
        with self._lock:
            counts = dict(self._counts)
            sizes = list(self._batch_sizes)
            latency = self._latency_block(self._latencies)
            by_type = {
                kind: {
                    "counts": dict(row),
                    "latency_s": self._latency_block(
                        self._latencies_by_type[kind]
                    ),
                }
                for kind, row in self._by_type.items()
            }
            by_tier = {
                tier: {
                    "counts": dict(row),
                    "latency_s": self._latency_block(
                        self._latencies_by_tier[tier]
                    ),
                    "dispatch": {
                        "count": self._tier_dispatch[tier][0],
                        "total_s": round(self._tier_dispatch[tier][1], 6),
                        "mean_s": round(
                            self._tier_dispatch[tier][1]
                            / max(self._tier_dispatch[tier][0], 1),
                            6,
                        ),
                    },
                }
                for tier, row in self._by_tier.items()
            }
        if self._router is not None:
            by_tier["approx"]["router"] = self._router.describe()
        out = {
            "counts": counts,
            "batch_size": {
                "count": len(sizes),
                "min": int(min(sizes)) if sizes else 0,
                "max": int(max(sizes)) if sizes else 0,
                "mean": float(np.mean(sizes)) if sizes else 0.0,
            },
            "latency_s": latency,
            "by_type": by_type,
            "by_tier": by_tier,
        }
        store = self._effective_reuse_store()
        if store is not None:
            out["reuse"] = store.stats()
        if self._plan_cache is not None:
            out["plan_cache"] = self._plan_cache.stats()
        if self._slo is not None:
            out["slo"] = self._slo.stats()
        if self._plansvc is not None:
            out["plansvc"] = self._plansvc.stats()
        if self._cost_truth is not None:
            out["calibration"] = self._cost_truth.stats()
        if self._elastic is not None:
            from tnc_tpu.serve import elastic as _elastic_mod

            out["elastic"] = {
                "counters": _elastic_mod.counters(),
                "tenants": self._tenant_depths(),
                "weights": dict(self._elastic.tenant_weights),
                "quotas": dict(self._elastic.tenant_quotas),
                "controller": (
                    dict(self._elastic_controller.last_decision)
                    if self._elastic_controller is not None else None
                ),
            }
        return out

    def _effective_reuse_store(self):
        """The intermediate-tensor store serving this service's bound
        program (attached via from_circuit, or carried by a bound built
        directly with ``bind_template(..., reuse_store=)``)."""
        if self.reuse_store is not None:
            return self.reuse_store
        reuse = getattr(self.bound, "reuse", None)
        return reuse.store if reuse is not None else None

    # -- live telemetry endpoint -------------------------------------------

    def serve_telemetry(
        self, host: str = "127.0.0.1", port: int = 0
    ):
        """Start (and own) the live scrape endpoint for this service:
        ``/metrics`` (Prometheus text: the obs registry + the service's
        own families, percentile-identical to ``stats()``), ``/healthz``
        and ``/slo``. Returns the started
        :class:`~tnc_tpu.obs.http.TelemetryServer` (``.port`` carries
        the bound port when ``port=0``); :meth:`stop` shuts it down and
        releases the port."""
        from tnc_tpu.obs.http import TelemetryServer

        if self._telemetry is not None:
            return self._telemetry

        def health() -> dict:
            running = self._running
            body = {
                "status": "ok" if running else "stopped",
                "running": running,
                "queue_depth": self.queue_depth() if running else 0,
                "replica": _fleet.replica_identity(),
            }
            if self._fleet_registry is not None:
                body["heartbeat_age_s"] = (
                    self._fleet_registry.last_heartbeat_age_s()
                )
            return body

        def slo() -> dict:
            if self._slo is None:
                return {"enabled": False}
            body = self._slo.stats()
            body["enabled"] = True
            body["recent_requests"] = self._slo.timelines()[-32:]
            return body

        def fleet() -> dict:
            # late-bound: attach_fleet may run after serve_telemetry
            if self._fleet_aggregator is None:
                return {"enabled": False}
            body = self._fleet_aggregator.snapshot()
            body["enabled"] = True
            return body

        def calibration() -> dict:
            # late-bound: enable_cost_truth may run after serve_telemetry
            if self._cost_truth is None:
                return {"enabled": False}
            return self._cost_truth.stats()

        self._telemetry = TelemetryServer(
            registry=obs.get_registry(),
            host=host,
            port=port,
            health_fn=health,
            slo_fn=slo,
            extra_metrics_fn=self._prometheus_families,
            fleet_fn=fleet,
            calibration_fn=calibration,
        ).start()
        return self._telemetry

    # -- fleet observability plane ----------------------------------------

    def attach_fleet(
        self,
        directory: str | None = None,
        endpoints=(),
        heartbeat_s: float = 2.0,
        name: str | None = None,
        stale_after_s: float = 10.0,
    ) -> None:
        """Join the fleet observability plane (idempotent re-attach
        replaces the previous membership).

        ``directory`` — the shared :class:`~tnc_tpu.obs.fleet.
        FleetRegistry` directory: this replica heartbeats its identity,
        queue depth, SLO-alert/drift state and scrape URL every
        ``heartbeat_s`` seconds, and the roster (with join/stale/leave
        transitions) rides the ``/fleet`` body. ``endpoints`` — extra
        ``{name: url}`` scrape targets (replicas outside the registry).
        The root's own metrics are read in-process (no HTTP round-trip
        to itself). See :class:`~tnc_tpu.obs.fleet.FleetAggregator`."""
        if self._fleet_heartbeat is not None:
            self._fleet_heartbeat.stop()
            self._fleet_heartbeat = None
        registry = None
        if directory is not None:
            registry = _fleet.FleetRegistry(
                directory, name=name, stale_after_s=stale_after_s
            )

            def provider() -> dict:
                payload = {
                    "role": "root",
                    "queue_depth": self.queue_depth(),
                    "url": (
                        self._telemetry.url
                        if self._telemetry is not None else None
                    ),
                }
                if self._slo is not None:
                    slo_stats = self._slo.stats()
                    payload["slo_alerts"] = len(slo_stats.get("alerts", ()))
                    payload["slo_alerts_total"] = slo_stats.get(
                        "alerts_total", 0
                    )
                    drift = slo_stats.get("drift", {})
                    payload["drift_alerting"] = sum(
                        1 for row in drift.values()
                        if isinstance(row, dict) and row.get("alerting")
                    )
                    # worst live measured/predicted ratio across drift
                    # buckets: serve_top --fleet's at-a-glance column
                    ratios = [
                        row["ratio"] for row in drift.values()
                        if isinstance(row, dict)
                        and row.get("ratio") is not None
                    ]
                    if ratios:
                        payload["drift_ratio"] = round(
                            max(ratios, key=lambda r: abs(r - 1.0)), 4
                        )
                if self._cost_truth is not None:
                    payload["model_version"] = (
                        self._cost_truth.model_version
                    )
                if self._plansvc is not None:
                    # planner columns for serve_top --fleet: role,
                    # trials completed here, last merge's cost delta
                    payload["plansvc"] = self._plansvc.heartbeat_payload()
                if self._elastic is not None:
                    from tnc_tpu.serve import elastic as _elastic_mod

                    payload["tenants"] = self._tenant_depths()
                    payload["elastic"] = _elastic_mod.counters()
                # the cluster dispatcher's last per-process slice-range
                # assignment (serve_top --fleet's assignment column)
                assignment = getattr(self.dispatcher, "last_ranges", None)
                if assignment is not None:
                    payload["assignment"] = [list(r) for r in assignment]
                return payload

            self._fleet_registry = registry
            self._fleet_heartbeat = _fleet.Heartbeat(
                registry, provider=provider, interval_s=heartbeat_s
            ).start()

        def local_render() -> str:
            if self._telemetry is not None:
                return self._telemetry.render_metrics()
            from tnc_tpu.obs.http import render_prometheus

            return render_prometheus(
                obs.get_registry(), self._prometheus_families()
            )

        local_name = name if name is not None else _fleet.replica_name()
        self._fleet_aggregator = _fleet.FleetAggregator(
            endpoints=endpoints,
            registry=registry,
            local=(local_name, local_render),
        )

    def fleet_snapshot(self) -> dict | None:
        """The federated fleet view (same body as ``/fleet``), or None
        before :meth:`attach_fleet`."""
        if self._fleet_aggregator is None:
            return None
        return self._fleet_aggregator.snapshot()

    def _prometheus_families(self) -> list:
        """The service's own metric families for ``/metrics`` —
        computed from the same counters and quantile summaries
        ``stats()`` reads, independent of whether obs tracing is on.
        Summaries are snapshotted under the lock (consistent with the
        dispatcher's concurrent observes)."""
        with self._lock:
            counts = dict(self._counts)
            overall = (
                self._latency_block(self._latencies),
                self._latencies.sum,
            )
            by_type = {
                kind: (
                    dict(row),
                    self._latency_block(self._latencies_by_type[kind]),
                    self._latencies_by_type[kind].sum,
                )
                for kind, row in self._by_type.items()
            }
        fams: list = [("gauge", "serve.queue_depth", {}, self.queue_depth())]
        # request-outcome counters get their own family so
        # sum(serve_requests_total) is a true request count; batch and
        # plan-swap counters are separate families, not fake "outcomes"
        outcome_keys = (
            "submitted", "completed", "failed", "expired", "rejected",
            "cancelled",
        )
        for key in outcome_keys:
            fams.append(
                ("counter", "serve.requests", {"outcome": key}, counts[key])
            )
        fams.append(("counter", "serve.batches", {}, counts["batches"]))
        fams.append(
            ("counter", "serve.batches_degraded", {},
             counts["degraded_batches"])
        )
        fams.append(("counter", "serve.plan_swaps", {}, counts["plan_swaps"]))
        fams.append(
            ("counter", "serve.dedup_collapsed", {}, counts["deduped"])
        )
        # cross-request reuse + plan-cache efficacy: the same counters
        # stats() reports, as labeled families (hit/miss/evict/... as
        # {event=} so rates are one PromQL expression away)
        store = self._effective_reuse_store()
        if store is not None:
            reuse_stats = store.stats()
            for key in store.COUNT_KEYS:
                fams.append(
                    ("counter", "serve.reuse", {"event": key},
                     reuse_stats[key])
                )
            fams.append(
                ("gauge", "serve.reuse.bytes_held", {},
                 reuse_stats["bytes_held"])
            )
            fams.append(
                ("gauge", "serve.reuse.entries", {}, reuse_stats["entries"])
            )
            fams.append(
                ("counter", "serve.reuse.prefix_flops_saved", {},
                 reuse_stats["prefix_flops_saved"])
            )
        if self._plan_cache is not None:
            for key, value in self._plan_cache.stats()["counts"].items():
                fams.append(
                    ("counter", "serve.plan_cache", {"event": key}, value)
                )
        if self._plansvc is not None:
            svc_stats = self._plansvc.stats()
            for key, value in sorted(svc_stats["counts"].items()):
                fams.append(
                    ("counter", "serve.plansvc.events", {"event": key},
                     value)
                )
            for key, value in sorted(svc_stats["board"].items()):
                fams.append(
                    ("counter", "serve.plansvc.board", {"event": key},
                     value)
                )
            fams.append(
                ("gauge", "serve.plansvc.best_delta", {},
                 svc_stats["best_delta"])
            )

        def summary(name: str, labels: dict, block: dict, total: float):
            for q, qlabel in (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99")):
                fams.append(
                    ("summary", name, {**labels, "quantile": qlabel}, block[q])
                )
            fams.append(("summary", f"{name}_count", labels, block["count"]))
            fams.append(("summary", f"{name}_sum", labels, total))
            fams.append(("gauge", f"{name}_max", labels, block["max"]))

        summary("serve.latency_seconds", {}, *overall)
        for kind, (row, block, total) in by_type.items():
            for key, value in row.items():
                if key == "batches":
                    fams.append(
                        ("counter", "serve.type_batches", {"type": kind},
                         value)
                    )
                else:
                    fams.append(
                        (
                            "counter", "serve.type_requests",
                            {"type": kind, "outcome": key}, value,
                        )
                    )
            summary("serve.type_latency_seconds", {"type": kind}, block, total)
        with self._lock:
            tier_rows = {t: dict(r) for t, r in self._by_tier.items()}
        for tier, row in tier_rows.items():
            for key, value in row.items():
                if key == "batches":
                    fams.append(
                        ("counter", "serve.tier_batches", {"tier": tier},
                         value)
                    )
                else:
                    fams.append(
                        (
                            "counter", "serve.tier_requests",
                            {"tier": tier, "outcome": key}, value,
                        )
                    )
        if self._elastic is not None:
            from tnc_tpu.serve import elastic as _elastic_mod

            # serve_elastic_*: the elastic event ledger (reassigned /
            # preempted / scale decisions), per-tenant queue depths,
            # and the controller's current target — same numbers as
            # stats()["elastic"], so /metrics and /fleet federate them
            for event, value in sorted(_elastic_mod.counters().items()):
                fams.append(
                    ("counter", "serve.elastic.events",
                     {"event": event}, float(value))
                )
            for tenant, depth in sorted(self._tenant_depths().items()):
                fams.append(
                    ("gauge", "serve.elastic.tenant_queue",
                     {"tenant": tenant}, float(depth))
                )
            ctrl = self._elastic_controller
            if ctrl is not None:
                fams.append(
                    ("gauge", "serve.elastic.scale_target", {},
                     float(ctrl.last_decision.get("target", 0)))
                )
        ct = self._cost_truth
        if ct is not None:
            # cost-truth plane: the live model generation, the loop's
            # event ledger (samples/refits/publishes/adoptions/
            # rollbacks), and the sampler's reservoir fill — the same
            # numbers as stats()["calibration"], so /metrics and /fleet
            # federate them
            cal = ct.stats()
            fams.append(
                ("gauge", "serve.cost_truth.model_version", {},
                 float(cal["model_version"]))
            )
            for event, value in sorted(cal["counts"].items()):
                fams.append(
                    ("counter", "serve.cost_truth.events",
                     {"event": event}, float(value))
                )
            fams.append(
                ("gauge", "serve.cost_truth.sampler_kept", {},
                 float(cal["sampler"]["kept"]))
            )
        return fams


@dataclass(frozen=True)
class ApproxAnswer:
    """What an ``rtol=`` request resolves to: the value with an honest
    per-answer error estimate.

    ``err`` bounds ``|value − exact|`` (the chi-ladder's estimate, or
    a pure roundoff margin for escalated/untruncated answers);
    ``chi_used`` is the converged rung's bond dimension (None for an
    escalated exact answer); ``tolerance_met`` is False only when the
    escalation budget was exhausted and the best approximate answer was
    served anyway; ``sweeps`` counts the ladder rungs executed."""

    value: complex
    err: float
    chi_used: int | None
    escalated: bool = False
    tolerance_met: bool = True
    sweeps: int = 0


class FidelityRouter:
    """Routes tolerant requests onto the boundary-MPS chi-ladder tier
    and escalates tolerance misses to the exact pipeline.

    Registered as the ``"approx"`` query handler: ``validate`` checks
    the payload per base kind (amplitude / expectation / marginal) and
    assigns the ``(approx, base)`` batching key — approx work shares
    the queue but never a batch with exact work; ``dispatch`` runs the
    :class:`~tnc_tpu.approx.ladder.ChiLadder` per request against the
    structure-shared :class:`~tnc_tpu.approx.program.ApproxProgram`
    grids (amplitude grid for amplitudes, ONE sandwich grid for
    expectation and marginal — per-request payloads are leaf-data
    rebinds).

    A ladder that cannot meet the requested tolerance **escalates**:
    the request is re-answered by the exact pipeline (the service's
    bound program / registered query handlers), counted per tier and
    as ``serve.tier.escalated``, under a ``serve.escalate`` span, and
    capped at ``max_escalations`` — past the cap the approximate
    answer is served with ``tolerance_met=False`` (the error estimate
    stays honest either way).

    ``cost_model`` (a :class:`~tnc_tpu.obs.calibrate.
    CalibratedCostModel`) prices every ladder rung in predicted
    seconds (:mod:`tnc_tpu.approx.cost`), so :meth:`describe` quotes
    approximate-tier latency exactly like exact plans.
    """

    kind = APPROX_KIND
    #: ladder work varies with rtol and payload, not batch size — the
    #: SLO drift detector must not bucket it by batch shape
    drift_stable = False

    BASES = ("amplitude", "expectation", "marginal")

    #: tolerance scale per base kind: the tolerance is relative to
    #: ``max(|value|, scale)`` — an amplitude's natural magnitude is
    #: ``2^(-n/2)``, expectation values and probabilities are O(1)
    _UNIT_SCALE = 1.0

    def __init__(
        self,
        service: "ContractionService",
        circuit,
        chis=None,
        chi_start: int = 2,
        chi_cap: int = 64,
        safety: float = 4.0,
        max_escalations: int = 256,
        cost_model=None,
    ) -> None:
        from tnc_tpu.approx import ApproxProgram, ChiLadder

        self._service = service
        self._circuit = circuit.copy()
        self.num_qubits = self._circuit.num_qubits()
        self.ladder = ChiLadder(
            chis=chis, chi_start=chi_start, chi_cap=chi_cap, safety=safety
        )
        self.cost_model = (
            cost_model if cost_model is not None else service.cost_model
        )
        self.max_escalations = int(max_escalations)
        self.escalations = 0
        self.escalations_capped = 0
        self._programs: dict[str, object] = {}
        self._exact_programs: dict[str, object] = {}
        # build the amplitude grid eagerly: a circuit the tier cannot
        # flatten (non-nearest-neighbour) must fail at attach time, not
        # on the first tolerant request
        self._programs["amplitude"] = ApproxProgram.from_circuit(
            self._circuit
        )

    # -- programs ----------------------------------------------------------

    def program(self, base: str):
        """The grid program serving ``base`` (expectation and marginal
        share the sandwich grid)."""
        from tnc_tpu.approx import ApproxProgram

        key = "amplitude" if base == "amplitude" else "sandwich"
        prog = self._programs.get(key)
        if prog is None:
            prog = ApproxProgram.sandwich_from_circuit(self._circuit)
            self._programs[key] = prog
        return prog

    def _scale(self, base: str) -> float:
        if base == "amplitude":
            return 2.0 ** (-self.num_qubits / 2.0)
        return self._UNIT_SCALE

    # -- handler protocol --------------------------------------------------

    def validate(self, payload) -> tuple[dict, tuple]:
        from tnc_tpu.builders.circuit_builder import normalize_bitstring

        payload = dict(payload)
        base = payload.get("kind")
        if base not in self.BASES:
            raise ValueError(
                f"approx tier serves {self.BASES}, not {base!r}"
            )
        rtol = float(payload.get("rtol", 0.0))
        if not rtol > 0.0:
            raise ValueError(f"rtol must be > 0, got {rtol}")
        raw = payload.get("payload")
        if base == "amplitude":
            bits = normalize_bitstring(raw, self.num_qubits)
            if "*" in bits:
                raise ValueError(
                    "approx amplitude requests must be fully determined "
                    "(no '*' positions)"
                )
            validated = bits
        elif base == "expectation":
            from tnc_tpu.queries.expectation import normalize_terms

            validated = normalize_terms(raw, self.num_qubits)
        else:
            validated = normalize_bitstring(raw, self.num_qubits)
        return (
            {"kind": base, "payload": validated, "rtol": rtol},
            (self.kind, base),
        )

    def dispatch(self, payloads, backend) -> list:
        name = "jax" if isinstance(backend, JaxBackend) else "numpy"
        with obs.span(
            "serve.handler", type=self.kind, batch=len(payloads)
        ):
            return [self._one(p, backend, name) for p in payloads]

    # -- the ladder + escalation path --------------------------------------

    def _one(self, payload: dict, backend, backend_name: str) -> ApproxAnswer:
        base = payload["kind"]
        raw = payload["payload"]
        rtol = payload["rtol"]
        if base == "expectation":
            return self._one_expectation(raw, rtol, backend, backend_name)
        prog = self.program(base)
        if base == "amplitude":
            prog.rebind_bits(raw)
        else:
            prog.rebind_projectors(raw)
        res = self.ladder.run(
            prog, rtol, scale=self._scale(base), backend=backend_name,
            cost_model=self.cost_model,
        )
        value = res.value if base != "marginal" else res.value.real
        if res.converged:
            return ApproxAnswer(
                value, res.err, res.chi_used, sweeps=res.sweeps
            )
        return self._escalate(
            base, raw, value, res.err, res.chi_used, res.sweeps, backend,
            rtol,
        )

    def _one_expectation(
        self, terms, rtol: float, backend, backend_name: str
    ) -> ApproxAnswer:
        """A Pauli sum rides one sandwich grid: one ladder climb per
        UNIQUE Pauli string, coefficient-weighted combination, summed
        error bars."""
        prog = self.program("expectation")
        unique: dict[str, object] = {}
        for _c, pauli in terms:
            if pauli not in unique:
                prog.rebind_pauli(pauli)
                unique[pauli] = self.ladder.run(
                    prog, rtol, scale=self._scale("expectation"),
                    backend=backend_name, cost_model=self.cost_model,
                )
        value = complex(
            sum(c * unique[p].value for c, p in terms)
        )
        err = float(sum(abs(c) * unique[p].err for c, p in terms))
        chi_used = max(r.chi_used for r in unique.values())
        sweeps = sum(r.sweeps for r in unique.values())
        converged = all(r.converged for r in unique.values()) and (
            err <= rtol * max(abs(value), self._UNIT_SCALE)
        )
        if converged:
            return ApproxAnswer(value, err, chi_used, sweeps=sweeps)
        return self._escalate(
            "expectation", terms, value, err, chi_used, sweeps, backend,
            rtol,
        )

    def _escalate(
        self, base, raw, value, err, chi_used, sweeps, backend, rtol
    ) -> ApproxAnswer:
        if self.escalations >= self.max_escalations:
            self.escalations_capped += 1
            self._service.note_escalation(base, capped=True)
            logger.warning(
                "approx %s miss (err=%.3g > rtol=%.3g) but the "
                "escalation budget (%d) is exhausted; serving the "
                "approximate answer", base, err, rtol,
                self.max_escalations,
            )
            return ApproxAnswer(
                value, err, chi_used, tolerance_met=False, sweeps=sweeps
            )
        self.escalations += 1
        self._service.note_escalation(base)
        with obs.span("serve.escalate", kind=base, rtol=rtol):
            exact = self._exact_value(base, raw, backend)
        from tnc_tpu.approx.ladder import COMPLEX64_ERR_REL, EXACT_ERR_REL

        # the escalated answer's bar is the EXACT pipeline's roundoff
        # floor — which is single-precision-sized when the service
        # backend dispatches in complex64
        floor = EXACT_ERR_REL
        if isinstance(backend, JaxBackend) and np.dtype(
            getattr(backend, "dtype", np.complex128)
        ) == np.complex64:
            floor = COMPLEX64_ERR_REL
        return ApproxAnswer(
            exact,
            floor * max(abs(exact), self._scale(base)),
            None,
            escalated=True,
            sweeps=sweeps,
        )

    def _exact_value(self, base: str, raw, backend):
        """The exact pipeline's answer for an escalated request —
        through the service's registered query handler when present
        (shared plan cache), else through a lazily-bound exact program
        of the router's own circuit copy."""
        if base == "amplitude":
            return complex(
                self._service.bound.amplitudes([raw], backend)[0]
            )
        handler = self._service._handlers.get(base)
        if handler is not None:
            return handler.dispatch([raw], backend)[0]
        if base == "expectation":
            prog = self._exact_programs.get("expectation")
            if prog is None:
                from tnc_tpu.queries.expectation import bind_expectation

                prog = bind_expectation(self._circuit.copy())
                self._exact_programs["expectation"] = prog
            unique = sorted({p for _c, p in raw})
            vals = dict(zip(unique, prog.values(unique, backend)))
            return complex(sum(c * vals[p] for c, p in raw))
        from tnc_tpu.queries.marginal import (
            bind_marginal,
            marginal_probabilities,
            wildcard_mask,
        )

        mask = wildcard_mask(raw)
        bound = self._exact_programs.get(("marginal", mask))
        if bound is None:
            bound = bind_marginal(self._circuit.copy(), mask)
            self._exact_programs[("marginal", mask)] = bound
        return float(
            np.asarray(marginal_probabilities(bound, [raw], backend))[0]
        )

    def reset(self) -> None:
        """Zero the escalation audit (and re-arm the budget) — called
        by :meth:`ContractionService.reset_stats` so the router's
        numbers always describe the same window as the tier rows."""
        self.escalations = 0
        self.escalations_capped = 0

    # -- quoting -----------------------------------------------------------

    def quote_seconds(self, base: str = "amplitude") -> float | None:
        """Predicted seconds of a full ladder climb for ``base`` under
        the calibrated cost model (None without one) — the admission-
        control quote for the approximate tier, the exact analogue of
        pricing a plan's steps through the same model."""
        if self.cost_model is None:
            return None
        from tnc_tpu.approx.cost import ladder_seconds

        prog = self.program(base)
        return ladder_seconds(
            prog, self.ladder.rungs_for(prog), self.cost_model
        )

    def describe(self) -> dict:
        """Router posture for ``stats()["by_tier"]["approx"]``:
        escalation budget audit + per-base-kind rung schedule and
        latency quotes."""
        out = {
            "escalations": self.escalations,
            "escalations_capped": self.escalations_capped,
            "max_escalations": self.max_escalations,
            "rungs": {},
            "quote_s": {},
        }
        for base in ("amplitude", "sandwich"):
            prog = self._programs.get(base)
            if prog is None:
                continue
            out["rungs"][base] = list(self.ladder.rungs_for(prog))
            quote = (
                self.quote_seconds(
                    "amplitude" if base == "amplitude" else "marginal"
                )
                if self.cost_model is not None
                else None
            )
            out["quote_s"][base] = (
                round(quote, 6) if quote is not None else None
            )
        return out
