"""Query-serving front end: one mixed queue + micro-batching dispatcher.

:class:`ContractionService` turns a :class:`~tnc_tpu.serve.rebind.
BoundProgram` into a request server. Callers submit bitstrings (from
any thread, or ``await`` the asyncio facade); a dispatcher thread
collects requests into micro-batches — up to ``max_batch`` riders or
``max_wait_ms`` after the first arrival, whichever comes first — and
issues ONE rebind dispatch per batch, the TPU-native shape for
amplitude traffic (one compiled program, B bitstrings per dispatch).

Beyond amplitudes, the queue is **mixed**: bitstring sampling, Pauli
expectation values and marginal sweeps are ``submit()``-able query
types (:meth:`~ContractionService.submit_sample` /
:meth:`~ContractionService.submit_expectation` /
:meth:`~ContractionService.submit_marginal`), each handled by a
registered :mod:`tnc_tpu.queries.handlers` handler. Every request
carries a per-type **batching key** (the marginal key includes the
wildcard mask); the dispatcher partitions each micro-batch window by
key, so a dispatched batch never mixes structures while all types
share one queue, one deadline/admission policy, and one plan cache.
Per-type counters and latency histograms ride ``stats()["by_type"]``
and the ``serve.query.*`` obs metrics.

Production posture:

- **admission control**: a bounded queue; submissions beyond
  ``max_queue`` fail fast with :class:`QueueFullError` instead of
  growing latency without bound;
- **deadlines**: each request may carry a timeout; requests that
  expire while queued are completed with
  :class:`DeadlineExceededError` at batch assembly (they never waste a
  dispatch);
- **resilience**: the batch dispatch runs under the shared
  :class:`~tnc_tpu.resilience.retry.RetryPolicy` (transient runtime
  failures retry with backoff); a batch that still fails **degrades to
  singleton requests** — each rider is re-dispatched alone, so one
  poisoned request cannot fail its co-riders;
- **observability**: ``serve.queue_depth`` gauge,
  ``serve.batch_size``/``serve.latency_s``/``serve.wait_s``
  histograms, ``serve.requests.*`` counters, plus the plan-cache
  hit/miss counters from :mod:`tnc_tpu.serve.plancache`;
- **anytime replanning**: a cache-missed structure serves from its
  fast greedy plan immediately; a
  :class:`~tnc_tpu.serve.replan.BackgroundReplanner` may later
  :meth:`~ContractionService.swap_bound` in a hyper-optimized
  :class:`BoundProgram` for the SAME structure — the dispatcher adopts
  it atomically between batches (every batch runs wholly under one
  bound, so in-flight requests are never split across plans).
"""

from __future__ import annotations

import concurrent.futures
import itertools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np

from tnc_tpu import obs
from tnc_tpu.obs.core import QuantileSummary
from tnc_tpu.resilience import retry as _retry
from tnc_tpu.resilience.faultinject import fault_point
from tnc_tpu.serve.rebind import BoundProgram, bind_circuit, pow2_bucket

logger = logging.getLogger(__name__)

#: drift-bucket granularity == executable granularity: one shared
#: power-of-two rule (rebind pads batched dispatches to it, so all
#: measurements inside a bucket ran the same compiled shape)
batch_bucket = pow2_bucket


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class QueueFullError(ServeError):
    """Admission control rejected the request (queue at ``max_queue``)."""


class DeadlineExceededError(ServeError):
    """The request's deadline passed before it could be dispatched."""


class ServiceClosedError(ServeError):
    """The service is stopped and no longer accepts requests."""


@dataclass
class _Request:
    bits: object  # the validated payload (determined bits for amplitudes)
    future: concurrent.futures.Future
    deadline: float | None  # absolute monotonic, None = no deadline
    t_submit: float = field(default_factory=time.monotonic)
    kind: str = "amplitude"
    # batching key: requests dispatch together ONLY when keys match
    # (per-type, plus structure discriminators like the marginal mask)
    key: tuple = ("amplitude",)
    # per-request trace id, assigned at admission; every serve.* span
    # that touches this request carries it, so the whole timeline
    # (queue age -> batch wait -> dispatch share) is queryable per
    # request (scripts/trace_summarize.py --serve)
    rid: int = 0
    t_collect: float = 0.0  # when batch assembly pulled it off the queue


_STATS_CAP = 4096  # bounded in-memory samples for stats()/bench


class ContractionService:
    """Micro-batching amplitude server over one bound program.

    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> c = Circuit(); reg = c.allocate_register(2)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> c.append_gate(TensorData.gate("cx"), [reg.qubit(0), reg.qubit(1)])
    >>> with ContractionService.from_circuit(c) as svc:
    ...     amp = svc.amplitude("00")
    >>> round(abs(amp), 6)
    0.707107
    """

    def __init__(
        self,
        bound: BoundProgram,
        backend=None,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        max_queue: int = 1024,
        retry_policy: _retry.RetryPolicy | None = None,
        dispatcher=None,
        slo=None,
        cost_model=None,
    ):
        """``dispatcher``: optional batch-execution hook
        ``fn(bound, bits, backend) -> (B,)+result_shape array``
        replacing the local ``bound.amplitudes_det`` dispatch — the
        multi-host fan-out point (:class:`~tnc_tpu.serve.multihost.
        ClusterDispatcher` shards the micro-batch across host
        processes and gathers at the root). Everything else (queueing,
        deadlines, retry, degradation, plan swaps) is unchanged: the
        dispatcher is only ever called with a batch and the CURRENT
        bound, so plan swaps stay batch-atomic across the fleet.

        ``slo``: an :class:`~tnc_tpu.obs.slo.SLOEngine` (or an
        :class:`~tnc_tpu.obs.slo.SLOConfig` to build one) — every
        terminal request outcome and every dispatch measurement feeds
        it, burn/drift alerts surface in ``stats()["slo"]`` and the
        telemetry endpoint. ``cost_model``: a
        :class:`~tnc_tpu.obs.calibrate.CalibratedCostModel` giving the
        drift detector its predicted dispatch seconds (without one,
        drift tracks raw measured seconds per bucket — still a change
        signal when the engine self-baselines)."""
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.bound = bound
        self.backend = backend  # None → rebind's numpy default
        self.dispatcher = dispatcher
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        self.retry_policy = retry_policy or _retry.default_policy()
        self.cost_model = cost_model
        self._queue: deque[_Request] = deque()
        self._cond = threading.Condition()
        self._running = False
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._counts = {
            "submitted": 0, "completed": 0, "failed": 0,
            "expired": 0, "rejected": 0, "cancelled": 0,
            "batches": 0, "degraded_batches": 0, "plan_swaps": 0,
        }
        self._batch_sizes: deque[int] = deque(maxlen=_STATS_CAP)
        # bounded streaming percentiles (p50/p90/p99 without retained
        # samples) — the SAME objects back stats() and /metrics, so the
        # two surfaces can never disagree. Cumulative since start /
        # reset_stats(): on a long-lived replica they answer "how has
        # this service served", not "how is it serving right now" — the
        # windowed view of the present is the SLO engine's burn rates
        self._latencies = QuantileSummary()
        # per-query-type breakdowns (kind -> counts / latency summary);
        # "amplitude" is pre-seeded so dashboards always see the
        # primary type even before traffic arrives
        self._by_type: dict[str, dict] = {}
        self._latencies_by_type: dict[str, QuantileSummary] = {}
        self._ensure_type("amplitude")
        # registered query handlers (sampling / expectation / marginal)
        self._handlers: dict[str, object] = {}
        # an improved BoundProgram staged by the background replanner;
        # the dispatcher adopts it at the next batch boundary
        self._pending_bound: BoundProgram | None = None
        self._replanner = None  # attached BackgroundReplanner, if any
        self._watchers: list = []  # attached SharedCacheWatchers
        self._rids = itertools.count(1)
        # plan-swap generation: bumps on every adopted replan/shared
        # swap; rides the dispatch spans and request timelines so a
        # latency change is attributable to the plan that served it
        self._generation = 0
        self._telemetry = None  # attached TelemetryServer, if any
        self._slo = None
        self._slo_last_check = 0.0
        self.attach_slo(slo)

    @classmethod
    def from_circuit(
        cls,
        circuit,
        mask=None,
        pathfinder=None,
        plan_cache=None,
        backend=None,
        target_size=None,
        background_replan: bool = False,
        replan_options: dict | None = None,
        shared_cache_watch: bool = False,
        watch_options: dict | None = None,
        queries: bool = False,
        telemetry_port: int | None = None,
        **kwargs,
    ) -> "ContractionService":
        """Build (plan/compile once, plan cache honored) and start.

        ``telemetry_port`` (0 = ephemeral) additionally starts the live
        scrape endpoint (:meth:`serve_telemetry`): ``/metrics`` +
        ``/healthz`` + ``/slo``.

        ``queries=True`` additionally registers the sampling /
        expectation / marginal query handlers for the same circuit
        (:func:`tnc_tpu.queries.handlers.attach_query_handlers`),
        sharing ``plan_cache``/``target_size``; the circuit is copied
        before the amplitude finalizer consumes it.

        ``background_replan=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.replan.BackgroundReplanner`: a cache miss
        is answered from the fast greedy plan immediately, and the
        worker hyper-optimizes the structure between requests, swapping
        in the improved plan when its predicted cost wins.
        ``replan_options`` are its constructor kwargs.

        ``shared_cache_watch=True`` (requires ``plan_cache``) attaches a
        :class:`~tnc_tpu.serve.replan.SharedCacheWatcher`: a replica
        deployment sharing one cache directory adopts OTHER replicas'
        published plans (including their background replanner's swaps)
        at batch boundaries. ``watch_options`` are its kwargs."""
        if background_replan and plan_cache is None:
            raise ValueError("background_replan requires a plan_cache")
        if shared_cache_watch and plan_cache is None:
            raise ValueError("shared_cache_watch requires a plan_cache")
        query_circuit = circuit.copy() if queries else None
        bound = bind_circuit(circuit, mask, pathfinder, plan_cache, target_size)
        svc = cls(bound, backend=backend, **kwargs)
        svc.start()
        try:
            if queries:
                svc.enable_queries(
                    query_circuit,
                    pathfinder=pathfinder,
                    plan_cache=plan_cache,
                    target_size=target_size,
                )
            if background_replan:
                from tnc_tpu.serve.replan import BackgroundReplanner

                BackgroundReplanner(
                    svc, plan_cache, **(replan_options or {})
                ).start()
            if shared_cache_watch:
                from tnc_tpu.serve.replan import SharedCacheWatcher

                watcher = SharedCacheWatcher(
                    svc, plan_cache, **(watch_options or {})
                )
                svc._watchers.append(watcher)
                watcher.start()
            if telemetry_port is not None:
                svc.serve_telemetry(port=telemetry_port)
        except Exception:
            # a bad option kwarg must not leak a running dispatcher
            # thread (or half the attachments) the caller can't reach
            svc.stop()
            raise
        return svc

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ContractionService":
        with self._cond:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="tnc-serve-dispatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop accepting requests; by default finish ('drain') what is
        already queued, otherwise fail queued requests with
        :class:`ServiceClosedError`. An attached background replanner
        is stopped first (it must not swap into a closing service)."""
        replanner, self._replanner = self._replanner, None
        if replanner is not None:
            replanner.stop()
        watchers, self._watchers = list(self._watchers), []
        for watcher in watchers:
            watcher.stop()
        telemetry, self._telemetry = self._telemetry, None
        if telemetry is not None:
            telemetry.stop()  # releases the port
        with self._cond:
            if not self._running:
                return
            self._running = False
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    self._complete(req, exc=ServiceClosedError("stopped"))
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    # -- plan swap (anytime replanning) ------------------------------------

    def swap_bound(self, bound: BoundProgram) -> None:
        """Stage an improved :class:`BoundProgram` for the SAME circuit
        structure (the background replanner's entry point). The
        dispatcher adopts it at the next batch boundary — batches are
        dispatched wholly under one bound, so no in-flight request ever
        mixes plans. Amplitude *values* are plan-independent (both
        programs contract the same network), so co-existing old-plan
        and new-plan responses are equally correct."""
        from tnc_tpu.serve.plancache import network_structure_digest

        if bound.template is not self.bound.template:
            # same structure digest (legs/dims/budget) AND same leaf
            # values: the digest is value-blind by design (all
            # bitstrings share it), but a swap with different gate
            # VALUES would silently serve another circuit's amplitudes
            if network_structure_digest(
                bound.template.network, bound.target_size
            ) != network_structure_digest(
                self.bound.template.network, self.bound.target_size
            ) or not all(
                np.array_equal(a, b)
                for a, b in zip(bound.arrays, self.bound.arrays)
            ):
                raise ValueError(
                    "swap_bound: replacement program was bound for a "
                    "different structure or different leaf values — "
                    "not a plan for this service's circuit/budget"
                )
        with self._lock:
            self._pending_bound = bound

    def _current_bound(self) -> BoundProgram:
        """The bound to dispatch the NEXT batch under, adopting any
        staged replacement first."""
        with self._lock:
            pending, self._pending_bound = self._pending_bound, None
            if pending is not None:
                self.bound = pending
                self._counts["plan_swaps"] += 1
                self._generation += 1
        if pending is not None:
            obs.counter_add("serve.replan.adopted")
            logger.info("adopted replanned program for serving")
        return self.bound

    def attach_slo(self, slo) -> "ContractionService":
        """Attach (or replace, or None-detach) the SLO engine — an
        :class:`~tnc_tpu.obs.slo.SLOEngine` or an
        :class:`~tnc_tpu.obs.slo.SLOConfig` to build one. Benchmarks
        attach AFTER their warmup, so compile-time requests never
        count against the objectives or seed the drift baselines."""
        if slo is not None and not hasattr(slo, "record_request"):
            from tnc_tpu.obs.slo import SLOEngine

            slo = SLOEngine(slo)
        self._slo = slo
        return self

    def queue_depth(self) -> int:
        """Instantaneous queue length (the replanner's idleness probe)."""
        with self._cond:
            return len(self._queue)

    def __enter__(self) -> "ContractionService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- query handlers ----------------------------------------------------

    def register_query_handler(self, handler) -> None:
        """Register a query-type handler (``kind`` attribute +
        ``validate(payload) -> (payload, key)`` at admission +
        ``dispatch(payloads, backend) -> results`` per batch — the
        :mod:`tnc_tpu.queries.handlers` protocol). One handler per
        kind; re-registering replaces."""
        self._handlers[str(handler.kind)] = handler

    def enable_queries(
        self,
        circuit,
        pathfinder=None,
        plan_cache=None,
        target_size=None,
    ) -> "ContractionService":
        """Register the sampling / expectation / marginal handlers for
        ``circuit`` (copied, not consumed) — the query-engine
        attachment point (lazy import: :mod:`tnc_tpu.queries` depends
        on this module's package)."""
        from tnc_tpu.queries.handlers import attach_query_handlers

        attach_query_handlers(
            self, circuit,
            pathfinder=pathfinder, plan_cache=plan_cache,
            target_size=target_size,
        )
        return self

    # -- submission --------------------------------------------------------

    def _enqueue(
        self,
        kind: str,
        key: tuple,
        payload,
        timeout_s: float | None,
    ) -> concurrent.futures.Future:
        """Shared admission path for every query type: bounded queue,
        deadline arming, request-id assignment, global + per-type
        accounting."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        deadline = (
            time.monotonic() + float(timeout_s) if timeout_s is not None else None
        )
        with self._cond:
            if not self._running:
                self._count("rejected")
                self._count_type(kind, "rejected")
                obs.counter_add("serve.requests.rejected", reason="closed")
                self._slo_request(kind, 0.0, "rejected")
                raise ServiceClosedError("service is not running")
            if len(self._queue) >= self.max_queue:
                self._count("rejected")
                self._count_type(kind, "rejected")
                obs.counter_add("serve.requests.rejected", reason="queue_full")
                self._slo_request(kind, 0.0, "rejected")
                raise QueueFullError(
                    f"queue at max_queue={self.max_queue}; retry later"
                )
            self._queue.append(
                _Request(
                    payload, fut, deadline, kind=kind, key=key,
                    rid=next(self._rids),
                )
            )
            depth = len(self._queue)
            self._cond.notify()
        self._count("submitted")
        self._count_type(kind, "submitted")
        obs.counter_add("serve.requests.submitted")
        obs.counter_add("serve.query.submitted", type=kind)
        obs.gauge_set("serve.queue_depth", depth)
        return fut

    def submit(
        self, bitstring: str | Iterable, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """Enqueue one amplitude request; returns a ``Future`` resolving
        to the amplitude (complex scalar, or an ndarray over the
        template's open legs). ``timeout_s`` arms a deadline."""
        # validate at admission: a malformed request must fail alone,
        # immediately — not poison a whole batch at dispatch time. The
        # determined-position bits (not the raw object) are what gets
        # queued: a one-shot iterable is consumed by this validation,
        # and dispatch never re-validates
        bitstring = self.bound.template.request_bits(bitstring)
        return self._enqueue(
            "amplitude", ("amplitude",), bitstring, timeout_s
        )

    def submit_query(
        self, kind: str, payload, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """Enqueue one typed query request through its registered
        handler; the handler validates the payload at admission and
        assigns the batching key."""
        handler = self._handlers.get(kind)
        if handler is None:
            raise ValueError(
                f"no handler registered for query kind {kind!r} "
                "(enable_queries / register_query_handler first)"
            )
        payload, key = handler.validate(payload)
        return self._enqueue(kind, tuple(key), payload, timeout_s)

    def submit_sample(
        self,
        n_samples: int = 1,
        seed=None,
        timeout_s: float | None = None,
    ) -> concurrent.futures.Future:
        """Sample ``n_samples`` bitstrings from |⟨b|C|0⟩|² (chain-rule
        sampler); the future resolves to a list of bitstrings. A seeded
        request's stream is deterministic regardless of co-riders."""
        return self.submit_query(
            "sample", {"n_samples": n_samples, "seed": seed}, timeout_s
        )

    def submit_expectation(
        self, terms, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """⟨ψ|P|ψ⟩ (a Pauli string) or a Pauli sum (iterable of
        ``(coeff, pauli)``); the future resolves to the complex
        value. Terms batch through one sandwich structure."""
        return self.submit_query("expectation", terms, timeout_s)

    def submit_marginal(
        self, pattern, timeout_s: float | None = None
    ) -> concurrent.futures.Future:
        """Marginal probability of ``pattern``'s determined bits
        (``'*'`` = marginalized); the future resolves to a float."""
        return self.submit_query("marginal", pattern, timeout_s)

    def sample(self, n_samples: int = 1, seed=None,
               timeout_s: float | None = None) -> list:
        """Blocking :meth:`submit_sample`."""
        return self.submit_sample(n_samples, seed, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def expectation(self, terms, timeout_s: float | None = None) -> complex:
        """Blocking :meth:`submit_expectation`."""
        return self.submit_expectation(terms, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def marginal(self, pattern, timeout_s: float | None = None) -> float:
        """Blocking :meth:`submit_marginal`."""
        return self.submit_marginal(pattern, timeout_s).result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    def amplitude(self, bitstring, timeout_s: float | None = None):
        """Blocking single-amplitude query (deadline doubles as the
        caller-side wait bound)."""
        fut = self.submit(bitstring, timeout_s)
        return fut.result(
            timeout=None if timeout_s is None else float(timeout_s) + 60.0
        )

    async def amplitude_async(self, bitstring, timeout_s: float | None = None):
        """Asyncio facade: ``await service.amplitude_async("0101")``."""
        import asyncio

        return await asyncio.wrap_future(self.submit(bitstring, timeout_s))

    # -- dispatcher --------------------------------------------------------

    def _collect_batch(self) -> list[_Request] | None:
        """Block for the first request, then hold the window open up to
        ``max_wait_s`` (or until ``max_batch`` riders); None = stopped
        and drained."""
        with self._cond:
            while not self._queue:
                if not self._running:
                    return None
                self._cond.wait(timeout=0.1)
            t0 = time.monotonic()
            deadline = t0 + self.max_wait_s
            while (
                len(self._queue) < self.max_batch
                and time.monotonic() < deadline
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    break
            batch = [
                self._queue.popleft()
                for _ in range(min(self.max_batch, len(self._queue)))
            ]
            obs.gauge_set("serve.queue_depth", len(self._queue))
            return batch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._collect_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            except Exception as exc:  # noqa: BLE001 — the dispatcher must survive
                # _run_batch handles dispatch failures itself; anything
                # reaching here is a bookkeeping bug — fail the batch,
                # keep serving
                logger.exception("dispatcher batch processing failed")
                for req in batch:
                    if not self._complete(
                        req, exc=ServeError(f"dispatcher error: {exc}")
                    ):
                        continue  # cancelled: _complete counted it
                    self._count("failed")
                    self._count_type(req.kind, "failed")
                    obs.counter_add("serve.requests.failed")
                    obs.counter_add("serve.query.failed", type=req.kind)
                    self._slo_request(
                        req.kind, time.monotonic() - req.t_submit, "failed"
                    )
                    self._trace_request(req, "failed")

    def _complete(self, req: _Request, result=None, exc=None) -> bool:
        """Resolve a request's future, tolerating caller-side
        cancellation (``fut.cancel()`` / an abandoned asyncio await):
        completing a cancelled future raises ``InvalidStateError``,
        which must never kill the dispatcher thread."""
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)
            return True
        except concurrent.futures.InvalidStateError:
            self._count("cancelled")
            self._count_type(req.kind, "cancelled")
            obs.counter_add("serve.requests.cancelled")
            obs.counter_add("serve.query.cancelled", type=req.kind)
            self._slo_request(
                req.kind, time.monotonic() - req.t_submit, "cancelled"
            )
            self._trace_request(req, "cancelled")
            return False

    def _dispatch_amps(self, bound: BoundProgram, bits: list) -> np.ndarray:
        """One batch execution under ``bound`` — locally, or through the
        pluggable ``dispatcher`` (multi-host fan-out)."""
        if self.dispatcher is not None:
            return self.dispatcher(bound, bits, self.backend)
        return bound.amplitudes_det(bits, self.backend)

    def _per_request(self, amps: np.ndarray, i: int):
        out = amps[i]
        # copy, not view: co-riders must never alias one mutable batch
        # buffer (an in-place edit by one caller would corrupt another's
        # already-delivered result)
        return complex(out) if out.shape == () else np.array(out)

    def _dispatch_group(
        self, kind: str, payloads: list, bound: BoundProgram
    ) -> list:
        """One batched execution of a same-key group; returns one
        result object per payload."""
        # injectable boundary (TNC_TPU_FAULTS): the SLO smoke scripts a
        # `slow` rule here to trip burn/drift alerts deterministically,
        # and raising kinds exercise the retry->degrade ladder exactly
        # where production dispatch failures surface
        fault_point("serve.dispatch", kind=kind, batch=len(payloads))
        if kind == "amplitude":
            amps = self._dispatch_amps(bound, payloads)
            return [
                self._per_request(amps, i) for i in range(len(payloads))
            ]
        return self._handlers[kind].dispatch(payloads, self.backend)

    def _run_batch(self, batch: list[_Request]) -> None:
        now = time.monotonic()
        live: list[_Request] = []
        for req in batch:
            # every request was just pulled off the queue at `now` —
            # set it on the expired branch too, or an expired request's
            # timeline would report its whole queue wait as batch_wait
            req.t_collect = now
            if req.deadline is not None and now > req.deadline:
                # complete FIRST: a caller-cancelled future takes the
                # cancelled outcome inside _complete, and exactly one
                # terminal outcome may count per request
                if self._complete(
                    req,
                    exc=DeadlineExceededError(
                        f"deadline exceeded after "
                        f"{now - req.t_submit:.3f}s in queue"
                    ),
                ):
                    self._count("expired")
                    self._count_type(req.kind, "expired")
                    obs.counter_add("serve.requests.expired")
                    self._slo_request(req.kind, now - req.t_submit, "expired")
                    self._trace_request(req, "expired")
            else:
                live.append(req)
        if not live:
            self._slo_check()
            return
        for req in live:
            obs.observe("serve.wait_s", now - req.t_submit)
        # one bound per window: adopt a staged replan at this boundary,
        # then every group of the window (including singleton-degrade
        # re-dispatches) runs under the SAME program
        bound = self._current_bound()
        # partition the window by batching key (insertion order): one
        # dispatch per key — a batch never mixes query types or
        # structures, while all types share the queue and the window
        groups: dict[tuple, list[_Request]] = {}
        for req in live:
            groups.setdefault(req.key, []).append(req)
        for group in groups.values():
            self._run_group(group, bound)
        self._slo_check()

    def _run_group(
        self, group: list[_Request], bound: BoundProgram
    ) -> None:
        kind = group[0].kind
        self._count("batches")
        self._count_type(kind, "batches")
        with self._lock:
            self._batch_sizes.append(len(group))
            generation = self._generation
        obs.observe("serve.batch_size", len(group))
        obs.observe("serve.query.batch_size", len(group), type=kind)
        payloads = [req.bits for req in group]
        riders = ",".join(f"r{req.rid}" for req in group)
        t0 = time.monotonic()
        try:
            # the batch-level span carries the rider id list so the
            # trace rollup can attribute shared batch time back to
            # request ids and query types
            with obs.span(
                "serve.dispatch",
                batch=len(group), kind=kind, riders=riders,
                generation=generation,
            ):
                results = self.retry_policy.run(
                    lambda: self._dispatch_group(kind, payloads, bound),
                    label="serve.dispatch",
                )
        except Exception as exc:  # noqa: BLE001 — degrade to singletons
            logger.warning(
                "%s batch of %d failed (%s: %s); degrading to singleton "
                "requests", kind, len(group), type(exc).__name__, exc,
            )
            self._count("degraded_batches")
            obs.counter_add("serve.batch_degraded")
            self._run_singletons(group, bound)
            return
        done = time.monotonic()
        dispatch_s = done - t0
        self._slo_dispatch(kind, len(group), dispatch_s, bound)
        for req, result in zip(group, results):
            if self._complete(req, result=result):
                self._finish(
                    req, done, dispatch_s=dispatch_s,
                    riders=len(group), generation=generation,
                )

    def _run_singletons(self, batch: list[_Request], bound=None) -> None:
        """Degraded mode: each rider re-dispatched alone — one bad
        request (or a transient that outlived its retries) fails only
        itself. ``bound`` pins the batch's program across the
        re-dispatches (a mid-degrade plan swap must not split a
        batch)."""
        if bound is None:
            bound = self.bound
        with self._lock:
            generation = self._generation
        for req in batch:
            t0 = time.monotonic()
            try:
                with obs.span(
                    "serve.dispatch",
                    batch=1, kind=req.kind, riders=f"r{req.rid}",
                    generation=generation, degraded=1,
                ):
                    results = self._dispatch_group(req.kind, [req.bits], bound)
            except Exception as exc:  # noqa: BLE001 — per-request verdict
                if self._complete(req, exc=exc):
                    self._count("failed")
                    self._count_type(req.kind, "failed")
                    obs.counter_add("serve.requests.failed")
                    obs.counter_add("serve.query.failed", type=req.kind)
                    self._slo_request(
                        req.kind, time.monotonic() - req.t_submit, "failed"
                    )
                    self._trace_request(req, "failed", degraded=True)
                continue
            done = time.monotonic()
            self._slo_dispatch(req.kind, 1, done - t0, bound)
            if self._complete(req, result=results[0]):
                self._finish(
                    req, done, dispatch_s=done - t0, riders=1,
                    generation=generation, degraded=True,
                )

    def _finish(
        self,
        req: _Request,
        done: float,
        dispatch_s: float = 0.0,
        riders: int = 1,
        generation: int = 0,
        degraded: bool = False,
    ) -> None:
        self._count("completed")
        self._count_type(req.kind, "completed")
        obs.counter_add("serve.requests.completed")
        obs.counter_add("serve.query.completed", type=req.kind)
        latency = done - req.t_submit
        with self._lock:
            self._latencies.observe(latency)
            self._latencies_by_type[req.kind].observe(latency)
        obs.observe("serve.latency_s", latency)
        obs.observe("serve.query.latency_s", latency, type=req.kind)
        timeline = None
        if self._slo is not None or obs.enabled():
            timeline = self._timeline(
                req, "completed", latency, dispatch_s, riders, generation,
                degraded,
            )
        if self._slo is not None:
            self._slo_request(
                req.kind, latency, "completed", timeline=timeline
            )
        self._trace_request(req, "completed", timeline=timeline)

    # -- per-request timeline + SLO plumbing -------------------------------

    def _timeline(
        self, req: _Request, outcome: str, latency: float,
        dispatch_s: float = 0.0, riders: int = 1, generation: int = 0,
        degraded: bool = False,
    ) -> dict:
        """Plain-data per-request trace record: where this request's
        latency went (queue age -> batch wait -> its share of a
        ``riders``-wide dispatch) plus the serving context (plan-cache
        provenance, replan-swap generation)."""
        t_collect = req.t_collect or req.t_submit
        return {
            "rid": f"r{req.rid}",
            "type": req.kind,
            "outcome": outcome,
            "latency_s": round(latency, 6),
            "queue_age_s": round(max(t_collect - req.t_submit, 0.0), 6),
            "batch_wait_s": round(
                max(latency - (t_collect - req.t_submit) - dispatch_s, 0.0), 6
            ),
            "dispatch_s": round(dispatch_s, 6),
            "riders": riders,
            "generation": generation,
            "degraded": degraded,
            "plan_cached": bool(self.bound.plan),
        }

    def _trace_request(
        self, req: _Request, outcome: str, timeline: dict | None = None,
        **extra,
    ) -> None:
        """Emit the request's terminal ``serve.request`` span (duration
        ~0; the timeline lives in the args) so an exported trace can be
        rolled up per request id and query type
        (``scripts/trace_summarize.py --serve``). A caller that already
        built the timeline (``_finish``) passes it in."""
        if not obs.enabled():
            return
        if timeline is None:
            latency = extra.pop("latency", time.monotonic() - req.t_submit)
            timeline = self._timeline(
                req, outcome, latency,
                extra.pop("dispatch_s", 0.0), extra.pop("riders", 1),
                extra.pop("generation", 0), extra.pop("degraded", False),
            )
        with obs.span("serve.request", **timeline):
            pass

    def _slo_request(
        self, kind: str, latency: float, outcome: str, timeline=None
    ) -> None:
        if self._slo is not None:
            self._slo.record_request(
                kind, latency, outcome, timeline=timeline
            )

    def _slo_dispatch(
        self, kind: str, batch: int, measured_s: float, bound: BoundProgram
    ) -> None:
        """Feed the drift detector one dispatch observation, bucketed by
        query type x power-of-two batch size (the executor-bucket
        granularity at which measured seconds are comparable). Kinds
        whose handler declares ``drift_stable = False`` (work varies
        with payload, not batch size — sampling's n_samples,
        expectation's unique-term count) are excluded: their measured
        seconds per bucket are not comparable, and feeding them would
        manufacture drift out of workload mix."""
        if self._slo is None:
            return
        handler = self._handlers.get(kind)
        if handler is not None and not getattr(handler, "drift_stable", True):
            return
        bucket = f"{kind}/b{batch_bucket(batch)}"
        self._slo.record_dispatch(
            bucket, self._predict_dispatch_s(kind, bound), measured_s
        )

    def _predict_dispatch_s(self, kind: str, bound: BoundProgram):
        """Calibrated prediction for one dispatch of ``kind`` under
        ``bound`` (None without a cost model, or for handler query
        types whose flops the service cannot see)."""
        if self.cost_model is None or kind != "amplitude":
            return None
        try:
            from tnc_tpu.ops.program import steps_flops

            steps = bound.program.steps
            return self.cost_model.op_seconds(
                steps_flops(steps), dispatches=max(len(steps), 1)
            )
        except Exception:  # noqa: BLE001 — prediction is best-effort
            return None

    #: minimum seconds between dispatcher-thread SLO evaluations — the
    #: burn windows are seconds-to-hours, so sub-batch freshness buys
    #: nothing and the evaluation must stay off the per-batch hot path
    _SLO_CHECK_INTERVAL_S = 0.2

    def _slo_check(self) -> None:
        if self._slo is None:
            return
        now = time.monotonic()
        if now - self._slo_last_check < self._SLO_CHECK_INTERVAL_S:
            return
        self._slo_last_check = now
        self._slo.check()

    # -- stats -------------------------------------------------------------

    # every terminal outcome increments its per-type row — deadline
    # expiry, queue rejection and caller-side cancellation included
    # (audited per outcome by tests/test_serve.py)
    _TYPE_KEYS = (
        "submitted", "completed", "failed", "expired", "rejected",
        "cancelled", "batches",
    )

    def _ensure_type(self, kind: str) -> dict:
        """Per-type accounting row (callers hold no lock; dict writes
        are guarded by ``_lock`` in the callers that mutate)."""
        row = self._by_type.get(kind)
        if row is None:
            row = {k: 0 for k in self._TYPE_KEYS}
            self._by_type[kind] = row
            self._latencies_by_type[kind] = QuantileSummary()
        return row

    def _count(self, key: str) -> None:
        with self._lock:
            self._counts[key] += 1

    def _count_type(self, kind: str, key: str) -> None:
        with self._lock:
            self._ensure_type(kind)[key] += 1

    def reset_stats(self) -> None:
        """Zero the in-memory counts and samples — benchmarks call this
        after their warmup so compile-time requests never skew the
        published batch-size/latency distribution."""
        with self._lock:
            for key in self._counts:
                self._counts[key] = 0
            self._batch_sizes.clear()
            self._latencies = QuantileSummary()
            for kind, row in self._by_type.items():
                for key in row:
                    row[key] = 0
                self._latencies_by_type[kind] = QuantileSummary()

    @staticmethod
    def _latency_block(summary: QuantileSummary) -> dict:
        """Percentile block from a streaming summary — the ONE source
        both ``stats()`` and the ``/metrics`` rendering read, so the
        two surfaces report identical numbers."""
        return {
            "count": summary.count,
            "p50": round(summary.quantile(0.5), 6),
            "p90": round(summary.quantile(0.9), 6),
            "p99": round(summary.quantile(0.99), 6),
            "max": round(summary.max, 6),
        }

    def stats(self) -> dict:
        """Snapshot for dashboards and ``bench.py --serve``: request
        counts, batch-size distribution, latency percentiles, the
        per-query-type breakdown (``by_type``: one row per kind with
        request/batch counts and latency percentiles), and — with an
        SLO engine attached — the ``slo`` block (burn rates, drift,
        firing alerts)."""
        # percentile blocks are computed UNDER the lock: the summaries
        # are live objects the dispatcher observes into, and a block
        # must be internally consistent (count vs quantiles)
        with self._lock:
            counts = dict(self._counts)
            sizes = list(self._batch_sizes)
            latency = self._latency_block(self._latencies)
            by_type = {
                kind: {
                    "counts": dict(row),
                    "latency_s": self._latency_block(
                        self._latencies_by_type[kind]
                    ),
                }
                for kind, row in self._by_type.items()
            }
        out = {
            "counts": counts,
            "batch_size": {
                "count": len(sizes),
                "min": int(min(sizes)) if sizes else 0,
                "max": int(max(sizes)) if sizes else 0,
                "mean": float(np.mean(sizes)) if sizes else 0.0,
            },
            "latency_s": latency,
            "by_type": by_type,
        }
        if self._slo is not None:
            out["slo"] = self._slo.stats()
        return out

    # -- live telemetry endpoint -------------------------------------------

    def serve_telemetry(
        self, host: str = "127.0.0.1", port: int = 0
    ):
        """Start (and own) the live scrape endpoint for this service:
        ``/metrics`` (Prometheus text: the obs registry + the service's
        own families, percentile-identical to ``stats()``), ``/healthz``
        and ``/slo``. Returns the started
        :class:`~tnc_tpu.obs.http.TelemetryServer` (``.port`` carries
        the bound port when ``port=0``); :meth:`stop` shuts it down and
        releases the port."""
        from tnc_tpu.obs.http import TelemetryServer

        if self._telemetry is not None:
            return self._telemetry

        def health() -> dict:
            running = self._running
            return {
                "status": "ok" if running else "stopped",
                "running": running,
                "queue_depth": self.queue_depth() if running else 0,
            }

        def slo() -> dict:
            if self._slo is None:
                return {"enabled": False}
            body = self._slo.stats()
            body["enabled"] = True
            body["recent_requests"] = self._slo.timelines()[-32:]
            return body

        self._telemetry = TelemetryServer(
            registry=obs.get_registry(),
            host=host,
            port=port,
            health_fn=health,
            slo_fn=slo,
            extra_metrics_fn=self._prometheus_families,
        ).start()
        return self._telemetry

    def _prometheus_families(self) -> list:
        """The service's own metric families for ``/metrics`` —
        computed from the same counters and quantile summaries
        ``stats()`` reads, independent of whether obs tracing is on.
        Summaries are snapshotted under the lock (consistent with the
        dispatcher's concurrent observes)."""
        with self._lock:
            counts = dict(self._counts)
            overall = (
                self._latency_block(self._latencies),
                self._latencies.sum,
            )
            by_type = {
                kind: (
                    dict(row),
                    self._latency_block(self._latencies_by_type[kind]),
                    self._latencies_by_type[kind].sum,
                )
                for kind, row in self._by_type.items()
            }
        fams: list = [("gauge", "serve.queue_depth", {}, self.queue_depth())]
        # request-outcome counters get their own family so
        # sum(serve_requests_total) is a true request count; batch and
        # plan-swap counters are separate families, not fake "outcomes"
        outcome_keys = (
            "submitted", "completed", "failed", "expired", "rejected",
            "cancelled",
        )
        for key in outcome_keys:
            fams.append(
                ("counter", "serve.requests", {"outcome": key}, counts[key])
            )
        fams.append(("counter", "serve.batches", {}, counts["batches"]))
        fams.append(
            ("counter", "serve.batches_degraded", {},
             counts["degraded_batches"])
        )
        fams.append(("counter", "serve.plan_swaps", {}, counts["plan_swaps"]))

        def summary(name: str, labels: dict, block: dict, total: float):
            for q, qlabel in (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99")):
                fams.append(
                    ("summary", name, {**labels, "quantile": qlabel}, block[q])
                )
            fams.append(("summary", f"{name}_count", labels, block["count"]))
            fams.append(("summary", f"{name}_sum", labels, total))
            fams.append(("gauge", f"{name}_max", labels, block["max"]))

        summary("serve.latency_seconds", {}, *overall)
        for kind, (row, block, total) in by_type.items():
            for key, value in row.items():
                if key == "batches":
                    fams.append(
                        ("counter", "serve.type_batches", {"type": kind},
                         value)
                    )
                else:
                    fams.append(
                        (
                            "counter", "serve.type_requests",
                            {"type": kind, "outcome": key}, value,
                        )
                    )
            summary("serve.type_latency_seconds", {"type": kind}, block, total)
        return fams
