"""Chunked + slice-batched execution of sliced contraction programs.

The whole-path-in-one-``fori_loop`` executor (:mod:`tnc_tpu.ops.sliced`)
compiles one XLA program containing every step; on very large networks
(Sycamore-53 class) the TPU compiler struggles with a 250-step body. This
module trades one big compile for K small ones:

- the program is **split into chunks** of at most ``chunk_steps`` steps,
  each compiled separately (compile cost scales with the chunk, not the
  whole program);
- slices are processed in **batches of B** via ``jax.vmap`` over each
  chunk: every matmul gains a leading batch axis, so narrow per-slice
  matmuls become batched matmuls that keep the MXU busy, and host
  dispatch overhead is divided by B;
- batch results are summed on device and accumulated across batches.

Memory: a batch keeps B copies of each live intermediate, so B must be
chosen such that B x (peak live bytes of a chunk boundary) fits in HBM —
slicing deeper (smaller per-slice peak) and batching wider is the
TPU-friendly operating point.

Per-step contraction kernels are shared with the other executors
(``backends.apply_step`` / ``split_complex.apply_step_split``); compiled
chunk functions are cached by program signature so repeated executions
(benchmark reps, amplitude sweeps) compile nothing.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Sequence

import logging

import numpy as np

from tnc_tpu import obs
from tnc_tpu.ops.backends import apply_step, place_buffers
from tnc_tpu.ops.program import (
    ContractionProgram,
    PairStep,
    steps_bytes,
    steps_flops,
)
from tnc_tpu.ops.sliced import SlicedProgram, index_buffer, kahan_add
from tnc_tpu.resilience import checkpoint as _ckpt
from tnc_tpu.resilience import faultinject as _faults
from tnc_tpu.resilience import retry as _retry

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ProgramChunk:
    steps: tuple[PairStep, ...]
    in_slots: tuple[int, ...]  # slots read by this chunk (alive at entry)
    out_slots: tuple[int, ...]  # slots written here and still alive at exit


def split_program(
    program: ContractionProgram, chunk_steps: int
) -> list[ProgramChunk]:
    """Split ``program.steps`` into chunks with entry/exit slot lists.

    A slot is alive at step ``i`` if it will still be *read* at some step
    >= ``i`` (or it is the result slot). Pass-through slots that a chunk
    neither reads nor writes stay host-side and never enter the jit.

    >>> from tnc_tpu.builders.circuit_builder import Circuit
    >>> from tnc_tpu.tensornetwork.tensordata import TensorData
    >>> from tnc_tpu.contractionpath.paths import Greedy, OptMethod
    >>> c = Circuit(); reg = c.allocate_register(3)
    >>> c.append_gate(TensorData.gate("h"), [reg.qubit(0)])
    >>> for i in range(2):
    ...     c.append_gate(TensorData.gate("cx"), [reg.qubit(i), reg.qubit(i + 1)])
    >>> tn, _ = c.into_amplitude_network("111")
    >>> path = Greedy(OptMethod.GREEDY).find_path(tn).replace_path()
    >>> from tnc_tpu.ops.program import build_program
    >>> program = build_program(tn, path)
    >>> chunks = split_program(program, 3)
    >>> len(chunks), sum(len(ch.steps) for ch in chunks) == len(program.steps)
    (3, True)
    """
    steps = program.steps
    n = len(steps)
    last_read: dict[int, int] = {program.result_slot: n}
    for i, st in enumerate(steps):
        last_read[st.lhs] = max(last_read.get(st.lhs, -1), i)
        last_read[st.rhs] = max(last_read.get(st.rhs, -1), i)
    last_read[program.result_slot] = n

    chunks: list[ProgramChunk] = []
    for a in range(0, n, chunk_steps):
        b = min(a + chunk_steps, n)
        read_here: list[int] = []
        written: set[int] = set()
        seen: set[int] = set()
        for i in range(a, b):
            st = steps[i]
            # a read is "from outside" if the slot wasn't written earlier
            # in this same chunk
            for slot in (st.lhs, st.rhs):
                if slot not in written and slot not in seen:
                    read_here.append(slot)
                    seen.add(slot)
            written.add(st.lhs)
        outs = tuple(
            sorted(s for s in written if last_read.get(s, -1) >= b)
        )
        chunks.append(ProgramChunk(steps[a:b], tuple(read_here), outs))
    return chunks


def _run_chunk(xp, chunk: ProgramChunk, state: dict[int, Any]) -> None:
    for step in chunk.steps:
        state[step.lhs] = apply_step(xp, state[step.lhs], state[step.rhs], step)
        del state[step.rhs]


def _run_chunk_split(
    xp, chunk: ProgramChunk, state: dict[int, Any], precision, policy=None
) -> None:
    """``policy``: a per-chunk :class:`~tnc_tpu.ops.split_complex.
    KernelPolicy` (spans indexed relative to the chunk) — small
    consecutive residual steps fuse into single Pallas chain dispatches
    and eligible steps promote; ``None`` runs every step under the env
    mode."""
    from tnc_tpu.ops.split_complex import apply_step_split, run_chain_split

    steps = chunk.steps
    chain_end = {s: e for s, e in policy.chains} if policy is not None else {}
    i = 0
    while i < len(steps):
        end = chain_end.get(i)
        if end is not None:
            group = steps[i:end]
            run_chain_split(
                xp, group, state, precision,
                precision_mode=policy.precision_mode(i),
            )
            for st in group:
                if state.get(st.rhs) is None:  # consumed by the chain
                    state.pop(st.rhs, None)
            i = end
            continue
        step = steps[i]
        state[step.lhs] = apply_step_split(
            xp, state[step.lhs], state[step.rhs], step, precision,
            mode=policy.modes[i] if policy is not None else None,
            precision_mode=(
                policy.precision_mode(i) if policy is not None else None
            ),
        )
        del state[step.rhs]
        i += 1


# compiled plan cache: key -> (chunks, chunk_fns).
# Locked: the distributed local phase runs one chunked runner per
# partition from a thread pool, so lookups/evictions race otherwise.
_PLAN_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_PLAN_CACHE_MAX = 64
_PLAN_CACHE_LOCK = threading.Lock()

# jitted prelude executables (slice-invariant stem, run once per
# execution before the chunked slice loop), cached like the plans
_PRELUDE_CACHE: "OrderedDict[tuple, Any]" = OrderedDict()
_PRELUDE_CACHE_MAX = 64


def _prelude_fn(hp, split_complex: bool, precision):
    """jitted ``fn(prelude_input_buffers) -> cached outputs`` for a
    :class:`~tnc_tpu.ops.hoist.HoistedProgram` — one dispatch computes
    every invariant intermediate the residual program reads."""
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import lanemix_env
    from tnc_tpu.ops.split_complex import complex_mult_key, dot_precision_key

    key = (
        hp.signature(),
        split_complex,
        precision,
        lanemix_env(),
        complex_mult_key() if split_complex else None,
        dot_precision_key() if split_complex else None,
    )
    with _PLAN_CACHE_LOCK:
        fn = _PRELUDE_CACHE.get(key)
        if fn is not None:
            _PRELUDE_CACHE.move_to_end(key)
            return fn

    from tnc_tpu.ops.hoist import run_prelude_steps

    def run(pins):
        return tuple(
            run_prelude_steps(jnp, hp, pins, split_complex, precision)
        )

    fn = jax.jit(run)
    with _PLAN_CACHE_LOCK:
        _PRELUDE_CACHE[key] = fn
        while len(_PRELUDE_CACHE) > _PRELUDE_CACHE_MAX:
            _PRELUDE_CACHE.popitem(last=False)
    return fn


def _hoisted_inputs(hp, device_full, split_complex: bool, precision):
    """Run the prelude on device (one jitted dispatch) and assemble the
    residual program's input buffer list from pass-through leaves and
    the freshly cached intermediates."""
    pins = tuple(device_full[orig] for _, orig in hp.prelude_inputs)
    cached = _prelude_fn(hp, split_complex, precision)(pins)
    out = []
    it = iter(cached)
    for kind, ref in hp.residual_sources:
        out.append(device_full[ref] if kind == "leaf" else next(it))
    return out


def _compiled_plan(
    sp: SlicedProgram,
    batch: int,
    chunk_steps: int,
    split_complex: bool,
    precision: str | None,
):
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import lanemix_env
    from tnc_tpu.ops.split_complex import complex_mult_key, dot_precision_key

    key = (
        sp.signature(),
        batch,
        chunk_steps,
        split_complex,
        precision,
        lanemix_env(),
        complex_mult_key() if split_complex else None,
        dot_precision_key() if split_complex else None,
    )
    with _PLAN_CACHE_LOCK:
        hit = _PLAN_CACHE.get(key)
        if hit is not None:
            _PLAN_CACHE.move_to_end(key)
            obs.counter_add("chunk_plan_cache.hit")
            return hit
    obs.counter_add("chunk_plan_cache.miss")

    _faults.fault_point("chunked.plan")
    chunks = split_program(sp.program, chunk_steps)
    num_inputs = sp.program.num_inputs

    # kernel promotion ladder per chunk (split mode): chain spans and
    # per-step modes planned over each chunk's step subsequence — a
    # chain cannot cross a chunk boundary (the boundary is a dispatch
    # anyway). Cached with the plan; the cache key carries
    # complex_mult_key so forced/auto plans never collide.
    if split_complex:
        from tnc_tpu.ops.split_complex import plan_kernel_steps

        chunk_policies = [plan_kernel_steps(c.steps) for c in chunks]
    else:
        chunk_policies = [None] * len(chunks)

    # which slots carry a batch axis (sliced leaves + anything computed
    # from a batched slot)
    batched: set[int] = {
        slot for slot, info in enumerate(sp.slot_slices) if info
    }
    batched_after_chunk: list[set[int]] = []
    current = set(batched)
    for chunk in chunks:
        for step in chunk.steps:
            if step.lhs in current or step.rhs in current:
                current.add(step.lhs)
        batched_after_chunk.append(set(current))

    # Dispatch-count discipline (host calls dominate the steady state on
    # fast backends, TPU_EVIDENCE_r03.md): each sliced leaf is gathered
    # INSIDE its consuming chunk's jit (full buffer unbatched + the idx
    # rows vmapped), and the last chunk folds the batch-sum/accumulate.
    # One dispatch per chunk per batch — no separate gather or reduce.
    result_shape = sp.program.stored_result_shape
    result_slot = sp.program.result_slot
    last_ci = len(chunks) - 1
    chunk_fns = []
    written_before: set[int] = set()
    for ci, chunk in enumerate(chunks):
        pre_batched = batched if ci == 0 else batched_after_chunk[ci - 1]
        # a sliced-leaf slot read here for the first time enters as the
        # FULL buffer and is sliced per-batch-row inside the vmap; a slot
        # id below num_inputs that an earlier chunk already wrote holds
        # an intermediate (slots are reused as result holders)
        leaf_in = {
            slot
            for slot in chunk.in_slots
            if slot < num_inputs
            and sp.slot_slices[slot]
            and slot not in written_before
        }
        written_before.update(step.lhs for step in chunk.steps)
        in_axes_spec = []
        for slot in chunk.in_slots:
            if slot in leaf_in:
                ax = None
            else:
                ax = 0 if slot in pre_batched else None
            in_axes_spec.append((ax, ax) if split_complex else ax)
        post_batched = batched_after_chunk[ci]
        out_axes_spec = []
        for slot in chunk.out_slots:
            ax = 0 if slot in post_batched else None
            out_axes_spec.append((ax, ax) if split_complex else ax)

        def single(
            ins, idx1, _chunk=chunk, _leaf_in=leaf_in,
            _policy=chunk_policies[ci],
        ):
            state = {}
            for slot, val in zip(_chunk.in_slots, ins):
                if slot in _leaf_in:
                    info = sp.slot_slices[slot]
                    if split_complex:
                        state[slot] = (
                            index_buffer(jnp, val[0], info, idx1),
                            index_buffer(jnp, val[1], info, idx1),
                        )
                    else:
                        state[slot] = index_buffer(jnp, val, info, idx1)
                else:
                    state[slot] = val
            if split_complex:
                _run_chunk_split(jnp, _chunk, state, precision, _policy)
            else:
                _run_chunk(jnp, _chunk, state)
            return tuple(state[s] for s in _chunk.out_slots)

        def _has_axis(spec):
            return any(
                (s is not None)
                if not isinstance(s, tuple)
                else any(x is not None for x in s)
                for s in spec
            )

        is_batched_chunk = bool(leaf_in) or _has_axis(in_axes_spec)
        if is_batched_chunk:
            vmapped = jax.vmap(
                single,
                in_axes=(tuple(in_axes_spec), 0),
                out_axes=tuple(out_axes_spec),
            )
        else:
            # chunk touches no sliced data: identical for every slice,
            # run it unbatched (its outputs are unbatched too)
            def vmapped(ins, idx, _single=single):
                return _single(ins, None)

        if ci == last_ci:
            # the only slot alive after the final chunk is the result:
            # fold the batch-sum + compensated accumulate into the same
            # dispatch. The accumulator is a Kahan (sum, comp) pair per
            # part: thousands of batch contributions cancel to far below
            # the individual terms, where plain f32 accumulation loses
            # the 1e-5 parity target (VERDICT r3 #2).
            out_pos = chunk.out_slots.index(result_slot)
            res_batched = (
                result_slot in batched_after_chunk[ci] and is_batched_chunk
            )

            def last_fn(
                ins, idx, acc, _vmapped=vmapped, _pos=out_pos, _rb=res_batched
            ):
                out = _vmapped(ins, idx)[_pos]
                b = idx.shape[0]
                if split_complex:
                    if _rb:
                        re = jnp.sum(out[0], axis=0)
                        im = jnp.sum(out[1], axis=0)
                    else:  # slice-independent result: b identical terms
                        re, im = out[0] * b, out[1] * b
                    (sr, cr), (si, ci_) = acc
                    sr, cr = kahan_add(sr, cr, re.reshape(result_shape))
                    si, ci_ = kahan_add(si, ci_, im.reshape(result_shape))
                    return ((sr, cr), (si, ci_))
                s = jnp.sum(out, axis=0) if _rb else out * b
                return kahan_add(acc[0], acc[1], s.reshape(result_shape))

            fn = jax.jit(last_fn)
        else:
            fn = jax.jit(lambda ins, idx, _v=vmapped: _v(ins, idx))
        chunk_fns.append(fn)

    plan = (chunks, chunk_fns)
    with _PLAN_CACHE_LOCK:
        _PLAN_CACHE[key] = plan
        while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
            _PLAN_CACHE.popitem(last=False)
    return plan


def execute_sliced_batched_jax(
    sp: SlicedProgram,
    arrays: Sequence[Any],
    batch: int = 8,
    chunk_steps: int = 64,
    split_complex: bool = True,
    precision: str | None = "float32",
    dtype: str = "complex64",
    device=None,
    enforce_budget: bool = True,
    max_slices: int | None = None,
    host: bool = True,
    hoist: bool = False,
    ckpt: str | None = None,
    slice_range: tuple[int, int] | None = None,
):
    """Run a sliced program as chunked, slice-batched jitted calls.

    Returns the accumulated result: a complex ndarray (or a
    (real, imag) pair is combined before returning). ``batch`` is
    clamped to the HBM budget (see :mod:`tnc_tpu.ops.budget`; disable
    with ``enforce_budget=False``) and then to the largest divisor of
    the slice count <= the request. ``max_slices`` caps the loop (a
    partial sum over the first slices — benchmark subset mode).

    ``host=False`` returns the device-resident accumulator (a
    (real, imag) pair in split mode) in **stored** shape without any
    device→host transfer — benchmark timing must stay transfer-free:
    on tunneled backends the first D2H permanently degrades dispatch
    (measured 430× on the v5e axon tunnel, TPU_EVIDENCE_r03.md).

    ``ckpt`` (or ``TNC_TPU_CKPT``) arms slice-range checkpointing:
    the accumulator + cursor persist periodically and a restarted run
    resumes bit-identically (:mod:`tnc_tpu.resilience.checkpoint`).

    ``slice_range=(lo, hi)``: partial sum over slice ids ``[lo, hi)``
    only — the multi-host serving shard shape. Mutually exclusive with
    ``max_slices`` and explicit ``ckpt`` (a range partial is already
    someone else's resume unit; an env-armed ``TNC_TPU_CKPT`` is
    ignored for range runs for the same reason).
    """
    if sp.slicing.num_slices <= 1:
        raise ValueError(
            "execute_sliced_batched_jax expects a sliced program; "
            "use JaxBackend.execute for unsliced networks"
        )
    # input-data digest for the checkpoint signature, from the HOST
    # arrays (hashing device buffers would force a D2H): a structurally
    # identical program over different leaf data must not cross-resume
    data_digest = (
        _ckpt.arrays_digest(arrays)
        if _ckpt.resolve_ckpt(ckpt) is not None
        else None
    )
    device_full = place_buffers(arrays, dtype, split_complex, device)
    acc = run_sliced_chunked_placed(
        sp,
        device_full,
        batch=batch,
        chunk_steps=chunk_steps,
        split_complex=split_complex,
        precision=precision,
        dtype=dtype,
        device=device,
        enforce_budget=enforce_budget,
        max_slices=max_slices,
        hoist=hoist,
        ckpt=ckpt,
        ckpt_data_digest=data_digest,
        slice_range=slice_range,
    )
    if not host:
        return acc
    if split_complex:
        from tnc_tpu.ops.split_complex import combine_array

        return combine_array(acc[0], acc[1]).reshape(sp.program.result_shape)
    return np.asarray(acc).reshape(sp.program.result_shape)


def run_sliced_chunked_placed(
    sp: SlicedProgram,
    device_full: Sequence[Any],
    batch: int = 8,
    chunk_steps: int = 64,
    split_complex: bool = True,
    precision: str | None = "float32",
    dtype: str = "complex64",
    device=None,
    enforce_budget: bool = True,
    max_slices: int | None = None,
    hoist: bool = False,
    ckpt: str | None = None,
    ckpt_data_digest: str | None = None,
    slice_range: tuple[int, int] | None = None,
):
    """Chunked slice-batched execution over already-placed device
    buffers; returns the device-resident accumulator in stored shape
    (a (real, imag) pair in split mode). The distributed local phase
    uses this directly — each partition's buffers are committed to its
    own device, so every dispatch follows the data (one chunked runner
    per device, running concurrently under async dispatch).

    ``hoist=True`` computes the slice-invariant stem once (one extra
    jitted dispatch, outputs stay device-resident) and runs the chunked
    slice loop over the residual program only."""
    import jax.numpy as jnp

    if hoist:
        from tnc_tpu.ops.hoist import hoist_sliced_program

        hp = hoist_sliced_program(sp)
        if not hp.is_noop:
            with obs.span(
                "sliced.prelude",
                steps=len(hp.prelude_steps),
                executor="chunked",
            ) as osp:
                res_inputs = _hoisted_inputs(
                    hp, list(device_full), split_complex, precision
                )
                if obs.enabled():
                    from tnc_tpu.ops.backends import dtype_width

                    pre = [ps.step for ps in hp.prelude_steps]
                    osp.add(
                        flops=steps_flops(pre),
                        bytes=steps_bytes(pre, dtype_width(dtype)),
                    )
            return run_sliced_chunked_placed(
                hp.residual,
                res_inputs,
                batch=batch,
                chunk_steps=chunk_steps,
                split_complex=split_complex,
                precision=precision,
                dtype=dtype,
                device=device,
                enforce_budget=enforce_budget,
                max_slices=max_slices,
                hoist=False,
                ckpt=ckpt,
                ckpt_data_digest=ckpt_data_digest,
                slice_range=slice_range,
            )

    num = sp.slicing.num_slices
    if num <= 1:
        # a partition untouched by global slicing arrives as a 1-slice
        # program: run it straight (no batch axis exists to reduce over).
        # donate=False — the caller owns and may reuse these buffers.
        from tnc_tpu.ops.backends import jit_program

        fn = jit_program(sp.program, split_complex, precision, donate=False)
        return fn(list(device_full))
    if enforce_budget:
        from tnc_tpu.ops.budget import clamp_slice_batch

        batch = clamp_slice_batch(
            sp.program,
            batch,
            device=device,
            split_complex=split_complex,
            dtype_bytes=8 if "128" in str(dtype) else 4,
        )
    lo = 0
    if slice_range is not None:
        if max_slices is not None or ckpt is not None:
            raise ValueError(
                "slice_range is mutually exclusive with max_slices/ckpt"
            )
        lo = max(0, int(slice_range[0]))
        num = min(int(slice_range[1]), num)
        lo = min(lo, num)
    elif max_slices is not None:
        num = max(1, min(num, max_slices))
    span = max(num - lo, 1)
    batch = max(1, min(batch, span))
    while span % batch:  # largest divisor <= requested (dims are tiny)
        batch -= 1

    # slice-range checkpointing (TNC_TPU_CKPT / ckpt=): load cursor +
    # accumulator before compiling; the signature covers everything that
    # changes the accumulation sequence except the batch (the cursor is a
    # slice index, valid at any batch alignment)
    ckpt_path = _ckpt.resolve_ckpt(ckpt) if slice_range is None else None
    mgr = None
    resumed = None
    start0 = lo
    if ckpt_path is not None:
        # str(device) disambiguates the distributed local phase: two
        # structurally identical partitions share a program signature but
        # run on different devices, and must not cross-resume each
        # other's accumulator out of a shared TNC_TPU_CKPT directory.
        # ckpt_data_digest covers the leaf DATA (the program signature is
        # structural — same circuit, different bitstring, same hash); it
        # is None only on the placed-buffers entry point, whose callers
        # isolate runs by directory (per-cell TNC_TPU_CKPT)
        sig = _ckpt.signature_hash(
            "chunked-v1", sp.signature(), chunk_steps, split_complex,
            precision, str(dtype), num, str(device), ckpt_data_digest,
        )
        mgr = _ckpt.SliceCheckpoint(ckpt_path, sig)
        loaded = mgr.load()
        if loaded is not None:
            # the cursor may be unaligned to the batch (the crashed run
            # could have degraded its batch mid-range); the dispatch
            # loop below tolerates that — each range is b = min(batch,
            # num - start) slices, and the jitted chunk fns retrace
            # once for an odd tail shape
            start0, resumed = loaded
            start0 = max(0, min(start0, num))

    chunks, chunk_fns = _compiled_plan(
        sp, batch, chunk_steps, split_complex, precision
    )

    # per-slot slice indices, shape [num, n_sliced_legs]
    dims = sp.slicing.dims
    all_indices = np.zeros((num, len(dims)), dtype=np.int32)
    s = np.arange(num)
    for pos in range(len(dims) - 1, -1, -1):
        all_indices[:, pos] = s % dims[pos]
        s //= dims[pos]

    import jax

    def place(x):
        # born on the target device: in the multi-device local phase an
        # uncommitted array would materialize on device 0 and hop over
        # per batch (transfer overhead is the dominant cost on tunneled
        # backends, TPU_EVIDENCE_r03.md)
        return jax.device_put(x, device) if device is not None else jnp.asarray(x)

    part_dtype = "float64" if "128" in str(dtype) else "float32"
    stored_shape = sp.program.stored_result_shape

    def zeros(dt):  # allocated directly on the target, no device-0 hop
        if device is not None:
            return jnp.zeros(stored_shape, dtype=dt, device=device)
        return jnp.zeros(stored_shape, dtype=dt)

    if not chunks:
        # zero-step program: the result is the (sliced) leaf itself —
        # sum its first `num` slices in one dispatch
        info = sp.slot_slices[sp.program.result_slot]
        idx_all = place(all_indices[lo:num])

        def leaf_sum(buf, idx):
            rows = jax.vmap(lambda i: index_buffer(jnp, buf, info, i))(idx)
            return jnp.sum(rows, axis=0).reshape(stored_shape)

        fn = jax.jit(leaf_sum)
        leaf = device_full[sp.program.result_slot]
        if split_complex:
            return (fn(leaf[0], idx_all), fn(leaf[1], idx_all))
        return fn(leaf, idx_all)

    # Kahan (sum, comp) accumulator per part; finalized to sum+comp below
    if resumed is not None:
        acc = _unflatten_acc(resumed, split_complex, place)
    elif split_complex:
        acc = (
            (zeros(part_dtype), zeros(part_dtype)),
            (zeros(part_dtype), zeros(part_dtype)),
        )
    else:
        acc = (zeros(dtype), zeros(dtype))

    # TNC_TPU_SYNC_DISPATCH: force device errors to surface inside the
    # retry/degradation scope below (async dispatch otherwise raises
    # them at the NEXT use of the poisoned accumulator)
    sync = _retry.sync_dispatch()
    with obs.span(
        "sliced.residual", executor="chunked", batch=batch,
        chunks=len(chunks),
    ) as osp:
        start = start0
        dispatches = 0
        while start < num:
            b = min(batch, num - start)
            idx = place(all_indices[start : start + b])

            # leaf in_slots receive the FULL buffers; each chunk's jit does
            # its own per-row gather and the last one folds the reduction —
            # exactly one dispatch per chunk per batch
            def _one_batch(_idx=idx, _acc=acc, _start=start, _b=b):
                _faults.fault_point("chunked.batch", start=_start, batch=_b)
                last_ci = len(chunks) - 1
                state = dict(enumerate(device_full))
                a = _acc
                for ci, (chunk, fn) in enumerate(zip(chunks, chunk_fns)):
                    ins = tuple(state[s] for s in chunk.in_slots)
                    if ci == last_ci:
                        a = fn(ins, _idx, a)
                    else:
                        outs = fn(ins, _idx)
                        for slot, buf in zip(chunk.out_slots, outs):
                            state[slot] = buf
                        for step in chunk.steps:
                            state.pop(step.rhs, None)
                if sync:
                    jax.block_until_ready(a)
                return a

            try:
                # transient failures (preemption, disconnect) retry the
                # same batch — nothing was accumulated until the last
                # chunk's dispatch returns
                acc = _retry.retry_call(_one_batch, label="chunked.batch")
            except Exception as exc:  # noqa: BLE001 — classified below
                cls = _retry.classify_exception(exc)
                if cls is _retry.FailureClass.RESOURCE and batch > 1:
                    # OOM ladder rung 1: halve the slice batch (still a
                    # divisor of num and of the current cursor) and retry
                    # this range with a recompiled chunk plan
                    batch = max(1, batch // 2)
                    logger.warning(
                        "chunked dispatch hit a resource error (%s); "
                        "degrading slice batch to %d", exc, batch,
                    )
                    obs.counter_add("resilience.degrade.batch_shrink")
                    obs.gauge_set("resilience.degrade.batch", batch)
                    chunks, chunk_fns = _compiled_plan(
                        sp, batch, chunk_steps, split_complex, precision
                    )
                    continue
                raise
            dispatches += len(chunks)
            start += b
            if mgr is not None:
                mgr.maybe_save(
                    start,
                    lambda _a=acc: _flatten_acc(_a, split_complex),
                )
        if obs.enabled():
            from tnc_tpu.ops.backends import dtype_width

            osp.add(
                slices=num - start0,
                dispatches=dispatches,
                flops=(num - start0) * steps_flops(sp.program.steps),
                bytes=(num - start0)
                * steps_bytes(sp.program.steps, dtype_width(dtype)),
            )
        if mgr is not None:
            mgr.finalize()
        # fold the compensation in (two tiny dispatches, untimed-scale cost)
        if split_complex:
            (sr, cr), (si, ci) = acc
            return (sr + cr, si + ci)
        return acc[0] + acc[1]


def _flatten_acc(acc, split_complex: bool) -> list:
    """Kahan accumulator tree → flat array list (checkpoint payload)."""
    if split_complex:
        (sr, cr), (si, ci) = acc
        return [sr, cr, si, ci]
    return [acc[0], acc[1]]


def _unflatten_acc(arrs, split_complex: bool, place):
    """Checkpoint payload → device-resident Kahan accumulator tree."""
    if split_complex:
        sr, cr, si, ci = (place(a) for a in arrs)
        return ((sr, cr), (si, ci))
    s, c = (place(a) for a in arrs)
    return (s, c)
