"""Chunked + slice-batched execution of sliced contraction programs.

The whole-path-in-one-``fori_loop`` executor (:mod:`tnc_tpu.ops.sliced`)
compiles one XLA program containing every step; on very large networks
(Sycamore-53 class) the TPU compiler struggles with a 250-step body. This
module trades one big compile for K small ones:

- the program is **split into chunks** of at most ``chunk_steps`` steps,
  each compiled separately (compile cost scales with the chunk, not the
  whole program);
- slices are processed in **batches of B** via ``jax.vmap`` over each
  chunk: every matmul gains a leading batch axis, so narrow per-slice
  matmuls become batched matmuls that keep the MXU busy, and host
  dispatch overhead is divided by B;
- batch results are summed on device and accumulated across batches.

Memory: a batch keeps B copies of each live intermediate, so B must be
chosen such that B x (peak live bytes of a chunk boundary) fits in HBM —
slicing deeper (smaller per-slice peak) and batching wider is the
TPU-friendly operating point.

Per-step contraction kernels are shared with the other executors
(``backends.apply_step`` / ``split_complex.apply_step_split``); compiled
chunk functions are cached by program signature so repeated executions
(benchmark reps, amplitude sweeps) compile nothing.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from tnc_tpu.ops.backends import apply_step, place_buffers
from tnc_tpu.ops.program import ContractionProgram, PairStep
from tnc_tpu.ops.sliced import SlicedProgram, index_buffer


@dataclass(frozen=True)
class ProgramChunk:
    steps: tuple[PairStep, ...]
    in_slots: tuple[int, ...]  # slots read by this chunk (alive at entry)
    out_slots: tuple[int, ...]  # slots written here and still alive at exit


def split_program(
    program: ContractionProgram, chunk_steps: int
) -> list[ProgramChunk]:
    """Split ``program.steps`` into chunks with entry/exit slot lists.

    A slot is alive at step ``i`` if it will still be *read* at some step
    >= ``i`` (or it is the result slot). Pass-through slots that a chunk
    neither reads nor writes stay host-side and never enter the jit.
    """
    steps = program.steps
    n = len(steps)
    last_read: dict[int, int] = {program.result_slot: n}
    for i, st in enumerate(steps):
        last_read[st.lhs] = max(last_read.get(st.lhs, -1), i)
        last_read[st.rhs] = max(last_read.get(st.rhs, -1), i)
    last_read[program.result_slot] = n

    chunks: list[ProgramChunk] = []
    for a in range(0, n, chunk_steps):
        b = min(a + chunk_steps, n)
        read_here: list[int] = []
        written: set[int] = set()
        seen: set[int] = set()
        for i in range(a, b):
            st = steps[i]
            # a read is "from outside" if the slot wasn't written earlier
            # in this same chunk
            for slot in (st.lhs, st.rhs):
                if slot not in written and slot not in seen:
                    read_here.append(slot)
                    seen.add(slot)
            written.add(st.lhs)
        outs = tuple(
            sorted(s for s in written if last_read.get(s, -1) >= b)
        )
        chunks.append(ProgramChunk(steps[a:b], tuple(read_here), outs))
    return chunks


def _run_chunk(xp, chunk: ProgramChunk, state: dict[int, Any]) -> None:
    for step in chunk.steps:
        state[step.lhs] = apply_step(xp, state[step.lhs], state[step.rhs], step)
        del state[step.rhs]


def _run_chunk_split(
    xp, chunk: ProgramChunk, state: dict[int, Any], precision
) -> None:
    from tnc_tpu.ops.split_complex import apply_step_split

    for step in chunk.steps:
        state[step.lhs] = apply_step_split(
            xp, state[step.lhs], state[step.rhs], step, precision
        )
        del state[step.rhs]


# compiled plan cache: key -> (chunks, chunk_fns, gather, reduce_batch).
# Locked: the distributed local phase runs one chunked runner per
# partition from a thread pool, so lookups/evictions race otherwise.
_PLAN_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_PLAN_CACHE_MAX = 64
_PLAN_CACHE_LOCK = threading.Lock()


def _compiled_plan(
    sp: SlicedProgram,
    batch: int,
    chunk_steps: int,
    split_complex: bool,
    precision: str | None,
):
    import jax
    import jax.numpy as jnp

    from tnc_tpu.ops.backends import lanemix_env

    key = (
        sp.signature(),
        batch,
        chunk_steps,
        split_complex,
        precision,
        lanemix_env(),
    )
    with _PLAN_CACHE_LOCK:
        hit = _PLAN_CACHE.get(key)
        if hit is not None:
            _PLAN_CACHE.move_to_end(key)
            return hit

    chunks = split_program(sp.program, chunk_steps)

    # which slots carry a batch axis (sliced leaves + anything computed
    # from a batched slot)
    batched: set[int] = {
        slot for slot, info in enumerate(sp.slot_slices) if info
    }
    batched_after_chunk: list[set[int]] = []
    current = set(batched)
    for chunk in chunks:
        for step in chunk.steps:
            if step.lhs in current or step.rhs in current:
                current.add(step.lhs)
        batched_after_chunk.append(set(current))

    def gather_slot(arr, info, idx_batch):
        """arr: full buffer; idx_batch: [B, n_sliced_legs] -> [B, ...]."""
        return jax.vmap(lambda idx: index_buffer(jnp, arr, info, idx))(
            idx_batch
        )

    def gather_pair(pair, info, idx_batch):
        return (
            gather_slot(pair[0], info, idx_batch),
            gather_slot(pair[1], info, idx_batch),
        )

    chunk_fns = []
    for ci, chunk in enumerate(chunks):
        pre_batched = batched if ci == 0 else batched_after_chunk[ci - 1]
        in_axes_spec = []
        for slot in chunk.in_slots:
            ax = 0 if slot in pre_batched else None
            in_axes_spec.append((ax, ax) if split_complex else ax)
        post_batched = batched_after_chunk[ci]
        out_axes_spec = []
        for slot in chunk.out_slots:
            ax = 0 if slot in post_batched else None
            out_axes_spec.append((ax, ax) if split_complex else ax)

        def single(ins, _chunk=chunk):
            state = dict(zip(_chunk.in_slots, ins))
            if split_complex:
                _run_chunk_split(jnp, _chunk, state, precision)
            else:
                _run_chunk(jnp, _chunk, state)
            return tuple(state[s] for s in _chunk.out_slots)

        def _has_axis(spec):
            return any(
                (s is not None)
                if not isinstance(s, tuple)
                else any(x is not None for x in s)
                for s in spec
            )

        if _has_axis(in_axes_spec):
            fn = jax.jit(
                jax.vmap(
                    single,
                    in_axes=(tuple(in_axes_spec),),
                    out_axes=tuple(out_axes_spec),
                )
            )
        else:
            # chunk touches no sliced data: identical for every slice,
            # run it unbatched (its outputs are unbatched too)
            fn = jax.jit(single)
        chunk_fns.append(fn)

    result_shape = sp.program.stored_result_shape

    if split_complex:

        @jax.jit
        def reduce_batch(acc, out_pair):
            re = jnp.sum(out_pair[0], axis=0).reshape(result_shape)
            im = jnp.sum(out_pair[1], axis=0).reshape(result_shape)
            return acc[0] + re, acc[1] + im

    else:

        @jax.jit
        def reduce_batch(acc, out):
            return acc + jnp.sum(out, axis=0).reshape(result_shape)

    gather = jax.jit(
        lambda full, idx: [
            (
                gather_pair(full[slot], info, idx)
                if split_complex
                else gather_slot(full[slot], info, idx)
            )
            if info
            else full[slot]
            for slot, info in enumerate(sp.slot_slices)
        ]
    )

    plan = (chunks, chunk_fns, gather, reduce_batch)
    with _PLAN_CACHE_LOCK:
        _PLAN_CACHE[key] = plan
        while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
            _PLAN_CACHE.popitem(last=False)
    return plan


def execute_sliced_batched_jax(
    sp: SlicedProgram,
    arrays: Sequence[Any],
    batch: int = 8,
    chunk_steps: int = 64,
    split_complex: bool = True,
    precision: str | None = "float32",
    dtype: str = "complex64",
    device=None,
    enforce_budget: bool = True,
    max_slices: int | None = None,
    host: bool = True,
):
    """Run a sliced program as chunked, slice-batched jitted calls.

    Returns the accumulated result: a complex ndarray (or a
    (real, imag) pair is combined before returning). ``batch`` is
    clamped to the HBM budget (see :mod:`tnc_tpu.ops.budget`; disable
    with ``enforce_budget=False``) and then to the largest divisor of
    the slice count <= the request. ``max_slices`` caps the loop (a
    partial sum over the first slices — benchmark subset mode).

    ``host=False`` returns the device-resident accumulator (a
    (real, imag) pair in split mode) in **stored** shape without any
    device→host transfer — benchmark timing must stay transfer-free:
    on tunneled backends the first D2H permanently degrades dispatch
    (measured 430× on the v5e axon tunnel, TPU_EVIDENCE_r03.md).
    """
    if sp.slicing.num_slices <= 1:
        raise ValueError(
            "execute_sliced_batched_jax expects a sliced program; "
            "use JaxBackend.execute for unsliced networks"
        )
    device_full = place_buffers(arrays, dtype, split_complex, device)
    acc = run_sliced_chunked_placed(
        sp,
        device_full,
        batch=batch,
        chunk_steps=chunk_steps,
        split_complex=split_complex,
        precision=precision,
        dtype=dtype,
        device=device,
        enforce_budget=enforce_budget,
        max_slices=max_slices,
    )
    if not host:
        return acc
    if split_complex:
        from tnc_tpu.ops.split_complex import combine_array

        return combine_array(acc[0], acc[1]).reshape(sp.program.result_shape)
    return np.asarray(acc).reshape(sp.program.result_shape)


def run_sliced_chunked_placed(
    sp: SlicedProgram,
    device_full: Sequence[Any],
    batch: int = 8,
    chunk_steps: int = 64,
    split_complex: bool = True,
    precision: str | None = "float32",
    dtype: str = "complex64",
    device=None,
    enforce_budget: bool = True,
    max_slices: int | None = None,
):
    """Chunked slice-batched execution over already-placed device
    buffers; returns the device-resident accumulator in stored shape
    (a (real, imag) pair in split mode). The distributed local phase
    uses this directly — each partition's buffers are committed to its
    own device, so every dispatch follows the data (one chunked runner
    per device, running concurrently under async dispatch)."""
    import jax.numpy as jnp

    num = sp.slicing.num_slices
    if num <= 1:
        # a partition untouched by global slicing arrives as a 1-slice
        # program: run it straight (no batch axis exists to reduce over).
        # donate=False — the caller owns and may reuse these buffers.
        from tnc_tpu.ops.backends import jit_program

        fn = jit_program(sp.program, split_complex, precision, donate=False)
        return fn(list(device_full))
    if enforce_budget:
        from tnc_tpu.ops.budget import clamp_slice_batch

        batch = clamp_slice_batch(
            sp.program,
            batch,
            device=device,
            split_complex=split_complex,
            dtype_bytes=8 if "128" in str(dtype) else 4,
        )
    if max_slices is not None:
        num = max(1, min(num, max_slices))
    batch = max(1, min(batch, num))
    while num % batch:  # largest divisor <= requested (dims are tiny)
        batch -= 1

    chunks, chunk_fns, gather, reduce_batch = _compiled_plan(
        sp, batch, chunk_steps, split_complex, precision
    )

    # per-slot slice indices, shape [num, n_sliced_legs]
    dims = sp.slicing.dims
    all_indices = np.zeros((num, len(dims)), dtype=np.int32)
    s = np.arange(num)
    for pos in range(len(dims) - 1, -1, -1):
        all_indices[:, pos] = s % dims[pos]
        s //= dims[pos]

    import jax

    def place(x):
        # born on the target device: in the multi-device local phase an
        # uncommitted array would materialize on device 0 and hop over
        # per batch (transfer overhead is the dominant cost on tunneled
        # backends, TPU_EVIDENCE_r03.md)
        return jax.device_put(x, device) if device is not None else jnp.asarray(x)

    part_dtype = "float64" if "128" in str(dtype) else "float32"
    stored_shape = sp.program.stored_result_shape

    def zeros(dt):  # allocated directly on the target, no device-0 hop
        if device is not None:
            return jnp.zeros(stored_shape, dtype=dt, device=device)
        return jnp.zeros(stored_shape, dtype=dt)

    if split_complex:
        acc = (zeros(part_dtype), zeros(part_dtype))
    else:
        acc = zeros(dtype)

    for start in range(0, num, batch):
        idx = place(all_indices[start : start + batch])
        sliced = gather(device_full, idx)
        state = dict(enumerate(sliced))
        for chunk, fn in zip(chunks, chunk_fns):
            ins = tuple(state[s] for s in chunk.in_slots)
            outs = fn(ins)
            for slot, buf in zip(chunk.out_slots, outs):
                state[slot] = buf
            for step in chunk.steps:
                state.pop(step.rhs, None)
        acc = reduce_batch(acc, state[sp.program.result_slot])
    return acc
