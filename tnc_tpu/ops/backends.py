"""Execution backends for compiled contraction programs.

The reference dispatches its pairwise kernel at build time (TBLIS vs MKL
behind the ``mkl`` cargo feature, ``README.md`` Features); here the
contractor is a runtime-pluggable backend:

- :class:`NumpyBackend` — the CPU oracle, complex128.
- :class:`JaxBackend` — the TPU path: the whole program is traced once and
  ``jax.jit``-compiled with **all input buffers donated**, so XLA reuses
  HBM for intermediates and the peak matches the analytic
  ``contract_size_tensors`` prediction. Matmuls land on the MXU; default
  dtype is complex64 (TPU has no native f64; parity target is 1e-5).

Compiled executables are cached by program signature + dtype, so repeated
contractions of equal-shaped networks (e.g. amplitude sweeps) recompile
nothing.
"""

from __future__ import annotations

import warnings
from typing import Any, Sequence

import numpy as np

from tnc_tpu.ops.program import ContractionProgram


class Backend:
    name: str = "base"

    def execute(self, program: ContractionProgram, arrays: Sequence[Any]) -> np.ndarray:
        raise NotImplementedError


def _run_steps(xp, program: ContractionProgram, buffers: list[Any]) -> Any:
    for step in program.steps:
        a = buffers[step.lhs]
        b = buffers[step.rhs]
        a = xp.transpose(a, step.lhs_perm).reshape(step.lhs_mat)
        b = xp.transpose(b, step.rhs_perm).reshape(step.rhs_mat)
        out = xp.matmul(a, b)
        buffers[step.lhs] = out.reshape(step.out_shape)
        buffers[step.rhs] = None  # free eagerly
    return buffers[program.result_slot]


class NumpyBackend(Backend):
    name = "numpy"

    def __init__(self, dtype=np.complex128):
        self.dtype = np.dtype(dtype)

    def execute(self, program: ContractionProgram, arrays: Sequence[Any]) -> np.ndarray:
        buffers = [np.asarray(a, dtype=self.dtype) for a in arrays]
        return np.asarray(_run_steps(np, program, buffers))


class JaxBackend(Backend):
    """jit-compiled whole-path execution on the default JAX device."""

    name = "jax"

    def __init__(self, dtype="complex64", donate: bool = True, device=None):
        import jax

        self._jax = jax
        self.dtype = dtype
        self.donate = donate
        self.device = device
        self._cache: dict[tuple, Any] = {}

    def _compiled(self, program: ContractionProgram):
        key = (program.signature(), str(self.dtype))
        fn = self._cache.get(key)
        if fn is None:
            jax = self._jax
            import jax.numpy as jnp

            def run(buffers: list[Any]) -> Any:
                return _run_steps(jnp, program, list(buffers))

            donate = (0,) if self.donate else ()
            fn = jax.jit(run, donate_argnums=donate)
            self._cache[key] = fn
        return fn

    def execute(self, program: ContractionProgram, arrays: Sequence[Any]) -> np.ndarray:
        import jax.numpy as jnp

        buffers = [
            self._jax.device_put(jnp.asarray(a, dtype=self.dtype), self.device)
            for a in arrays
        ]
        result = self._run(program, buffers)
        return np.asarray(result)

    def _run(self, program: ContractionProgram, buffers: list[Any]):
        with warnings.catch_warnings():
            # Tiny gate inputs are routinely not reusable for larger
            # intermediates; XLA's per-buffer warning is pure noise here.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            return self._compiled(program)(buffers)

    def execute_on_device(self, program: ContractionProgram, arrays: Sequence[Any]):
        """Like :meth:`execute` but leaves the result on device (no host
        round-trip) — used for benchmarking and distributed fan-in.
        """
        import jax.numpy as jnp

        buffers = [
            self._jax.device_put(jnp.asarray(a, dtype=self.dtype), self.device)
            for a in arrays
        ]
        return self._run(program, buffers)


_BACKENDS: dict[str, Backend] = {}


def get_backend(name: str | Backend | None = None) -> Backend:
    """Resolve a backend by name ('numpy', 'jax'), instance, or default."""
    if isinstance(name, Backend):
        return name
    if name is None:
        name = "numpy"
    backend = _BACKENDS.get(name)
    if backend is None:
        if name == "numpy":
            backend = NumpyBackend()
        elif name == "jax":
            backend = JaxBackend()
        elif name == "jax64":
            backend = JaxBackend(dtype="complex128")
        else:
            raise ValueError(f"Unknown backend '{name}'")
        _BACKENDS[name] = backend
    return backend
